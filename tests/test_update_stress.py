"""Rolling updates under stress: concurrent breach, scale during update,
back-to-back updates."""

from grove_tpu.api.pod import is_ready
from grove_tpu.sim.harness import SimHarness
from tests.test_rolling_update import converge_update, simple1


def with_image(image):
    pcs = simple1()
    for clique in pcs.spec.template.cliques:
        clique.spec.pod_spec.containers[0].image = image
    return pcs


class TestUpdateStress:
    def test_breach_during_update_does_not_gang_terminate(self):
        """The update-in-progress marker suspends MinAvailableBreached, so a
        crash mid-update never triggers gang termination (which would fight
        the updater)."""
        harness = SimHarness(num_nodes=32)
        pcs = simple1()
        pcs.spec.template.termination_delay = 10.0  # hair-trigger
        harness.apply(pcs)
        harness.converge()
        pclq_uid = harness.store.get(
            "PodClique", "default", "simple1-0-logger"
        ).metadata.uid

        updated = with_image("busybox:v2")
        updated.spec.template.termination_delay = 10.0
        harness.apply(updated)
        harness.engine.drain()
        # crash logger mid-update and sit well past the termination delay
        harness.cluster.fail_pod("default", "simple1-0-logger-0")
        harness.cluster.fail_pod("default", "simple1-0-logger-1")
        assert converge_update(harness, max_rounds=240), harness.tree()
        harness.converge()
        # the PCLQ was updated in place, not gang-terminated (same uid)
        pclq = harness.store.get("PodClique", "default", "simple1-0-logger")
        assert pclq.metadata.uid == pclq_uid
        pods = harness.store.list("Pod")
        assert all(is_ready(p) for p in pods), harness.tree()
        # the crashed pods were rebuilt from the NEW template, not the old
        assert {c.image for p in pods for c in p.spec.containers} == {
            "busybox:v2"
        }

    def test_scale_out_during_update_lands_on_new_template(self):
        harness = SimHarness(num_nodes=32)
        harness.apply(simple1())
        harness.converge()
        harness.apply(with_image("busybox:v2"))
        harness.engine.drain()
        # HPA scales the group out while the update runs
        pcsg = harness.store.get(
            "PodCliqueScalingGroup", "default", "simple1-0-workers"
        )
        pcsg.spec.replicas = 3
        harness.store.update(pcsg)
        assert converge_update(harness, max_rounds=240), harness.tree()
        harness.converge()
        pods = harness.store.list("Pod")
        assert len(pods) == 9 + 2 * 4
        assert all(is_ready(p) for p in pods), harness.tree()
        assert {c.image for p in pods for c in p.spec.containers} == {
            "busybox:v2"
        }

    def test_back_to_back_updates_converge_to_last(self):
        harness = SimHarness(num_nodes=32)
        harness.apply(simple1())
        harness.converge()
        harness.apply(with_image("busybox:v2"))
        harness.engine.drain()
        harness.advance(2.0)
        harness.engine.drain()
        # supersede mid-flight
        harness.apply(with_image("busybox:v3"))
        assert converge_update(harness, max_rounds=240), harness.tree()
        harness.converge()
        pods = harness.store.list("Pod")
        assert all(is_ready(p) for p in pods), harness.tree()
        assert {c.image for p in pods for c in p.spec.containers} == {
            "busybox:v3"
        }
