"""Rolling updates under stress: concurrent breach, scale during update,
back-to-back updates, operator crash/resume mid-update."""

from grove_tpu.api.pod import is_ready
from grove_tpu.sim.harness import SimHarness
from tests.test_rolling_update import converge_update, simple1


def with_image(image):
    pcs = simple1()
    for clique in pcs.spec.template.cliques:
        clique.spec.pod_spec.containers[0].image = image
    return pcs


def restart_operator(harness: SimHarness) -> None:
    """Kill and recreate the operator mid-flight: the engine, its workqueues,
    watch subscriptions, and the in-memory expectations store all die; the
    new instance re-lists every primary object (informer initial sync) and
    must resume purely from status-persisted progress — the reference's
    stateless crash/resume model (RollingUpdateProgress structs,
    podcliqueset.go:93-115, scalinggroup.go:105-129)."""
    from grove_tpu.controller.common import OperatorContext
    from grove_tpu.controller.register import register_controllers
    from grove_tpu.runtime.engine import Engine

    harness.store._watchers.clear()  # the crashed process's watches vanish
    harness.engine = Engine(harness.store, harness.clock)
    harness.ctx = OperatorContext(
        store=harness.store, clock=harness.clock, topology=harness.topology
    )
    register_controllers(harness.engine, harness.ctx, harness.config)
    # informer initial LIST → every existing primary enqueued once
    for ctrl in harness.engine.controllers:
        for obj in harness.store.list(ctrl.kind):
            ctrl.queue.add(
                (ctrl.kind, obj.metadata.namespace, obj.metadata.name)
            )


class DeletionCounter:
    """Counts pod deletions across operator restarts (a pod updated twice
    would be deleted twice)."""

    def __init__(self, harness: SimHarness) -> None:
        self.harness = harness
        self.counts = {}
        self.attach()

    def attach(self) -> None:
        def on_event(ev):
            if ev.kind == "Pod" and ev.type == "Deleted":
                name = ev.obj.metadata.name
                self.counts[name] = self.counts.get(name, 0) + 1

        self.harness.store.subscribe(on_event)


class TestUpdateStress:
    def test_breach_during_update_does_not_gang_terminate(self):
        """The update-in-progress marker suspends MinAvailableBreached, so a
        crash mid-update never triggers gang termination (which would fight
        the updater)."""
        harness = SimHarness(num_nodes=32)
        pcs = simple1()
        pcs.spec.template.termination_delay = 10.0  # hair-trigger
        harness.apply(pcs)
        harness.converge()
        pclq_uid = harness.store.get(
            "PodClique", "default", "simple1-0-logger"
        ).metadata.uid

        updated = with_image("busybox:v2")
        updated.spec.template.termination_delay = 10.0
        harness.apply(updated)
        harness.engine.drain()
        # crash logger mid-update and sit well past the termination delay
        harness.cluster.fail_pod("default", "simple1-0-logger-0")
        harness.cluster.fail_pod("default", "simple1-0-logger-1")
        assert converge_update(harness, max_rounds=240), harness.tree()
        harness.converge()
        # the PCLQ was updated in place, not gang-terminated (same uid)
        pclq = harness.store.get("PodClique", "default", "simple1-0-logger")
        assert pclq.metadata.uid == pclq_uid
        pods = harness.store.list("Pod")
        assert all(is_ready(p) for p in pods), harness.tree()
        # the crashed pods were rebuilt from the NEW template, not the old
        assert {c.image for p in pods for c in p.spec.containers} == {
            "busybox:v2"
        }

    def test_scale_out_during_update_lands_on_new_template(self):
        harness = SimHarness(num_nodes=32)
        harness.apply(simple1())
        harness.converge()
        harness.apply(with_image("busybox:v2"))
        harness.engine.drain()
        # HPA scales the group out while the update runs
        pcsg = harness.store.get(
            "PodCliqueScalingGroup", "default", "simple1-0-workers"
        )
        pcsg.spec.replicas = 3
        harness.store.update(pcsg)
        assert converge_update(harness, max_rounds=240), harness.tree()
        harness.converge()
        pods = harness.store.list("Pod")
        assert len(pods) == 9 + 2 * 4
        assert all(is_ready(p) for p in pods), harness.tree()
        assert {c.image for p in pods for c in p.spec.containers} == {
            "busybox:v2"
        }

    def test_crash_resume_at_three_interruption_points(self):
        """Kill/recreate the operator at three distinct mid-update states —
        (1) a PCS replica selected (currentlyUpdating set), (2) a PCSG
        replica mid-swap (readyReplicaIndicesSelectedToUpdate non-empty),
        (3) a PCLQ with pods half old / half new template — and require the
        resumed operator to finish from status-persisted progress without
        repeating (no pod deleted twice) or skipping (every pod on the new
        template) replicas."""
        harness = SimHarness(num_nodes=64)
        pcs = simple1()
        pcs.spec.replicas = 2  # replica ordering only matters with >1
        # 3 PCSG replicas: the PCSG's own one-ready-replica-at-a-time swap
        # then spans several control rounds (an observable mid-swap window)
        pcs.spec.template.pod_clique_scaling_group_configs[0].replicas = 3
        harness.apply(pcs)
        harness.converge()
        counter = DeletionCounter(harness)

        updated = with_image("busybox:v2")
        updated.spec.replicas = 2
        updated.spec.template.pod_clique_scaling_group_configs[0].replicas = 3
        harness.apply(updated)

        def pcs_mid_replica() -> bool:
            p = harness.store.list("PodCliqueSet")[0]
            prog = p.status.rolling_update_progress
            return prog is not None and prog.currently_updating is not None

        def pcsg_mid_swap() -> bool:
            for g in harness.store.list("PodCliqueScalingGroup"):
                prog = g.status.rolling_update_progress
                if prog is not None and (
                    prog.ready_replica_indices_selected_to_update
                ):
                    return True
            return False

        def pclq_half_updated() -> bool:
            from grove_tpu.api import names as namegen

            by_pclq = {}
            for pod in harness.store.list("Pod"):
                pclq = pod.metadata.labels.get(namegen.LABEL_PODCLIQUE)
                h = pod.metadata.labels.get(namegen.LABEL_POD_TEMPLATE_HASH)
                by_pclq.setdefault(pclq, set()).add(h)
            return any(len(hashes) > 1 for hashes in by_pclq.values())

        def run_until(condition, max_rounds=240) -> bool:
            for _ in range(max_rounds):
                harness.engine.drain()
                harness.schedule()
                harness.cluster.kubelet_tick()
                harness.engine.drain()
                if condition():
                    return True
                p = harness.store.list("PodCliqueSet")[0]
                prog = p.status.rolling_update_progress
                if prog is not None and prog.update_ended_at is not None:
                    return False  # update finished before the trigger hit
                harness.advance(2.0)
            return False

        for trigger in (pcs_mid_replica, pcsg_mid_swap, pclq_half_updated):
            assert run_until(trigger), (
                f"interruption point never reached: {trigger.__name__}"
            )
            restart_operator(harness)
            counter.attach()  # the new process watches again

        assert converge_update(harness, max_rounds=360), harness.tree()
        harness.converge()
        pods = harness.store.list("Pod")
        # no skips: every pod rebuilt from the new template and ready
        assert all(is_ready(p) for p in pods), harness.tree()
        assert {c.image for p in pods for c in p.spec.containers} == {
            "busybox:v2"
        }
        # no repeats: each original pod was deleted exactly once for its
        # update (a replayed replica would delete its new pods again)
        over_deleted = {n: c for n, c in counter.counts.items() if c > 1}
        assert not over_deleted, f"pods updated more than once: {over_deleted}"
        # progress bookkeeping closed out
        prog = harness.store.list("PodCliqueSet")[0].status.rolling_update_progress
        assert prog.update_ended_at is not None
        assert prog.currently_updating is None

    def test_back_to_back_updates_converge_to_last(self):
        harness = SimHarness(num_nodes=32)
        harness.apply(simple1())
        harness.converge()
        harness.apply(with_image("busybox:v2"))
        harness.engine.drain()
        harness.advance(2.0)
        harness.engine.drain()
        # supersede mid-flight
        harness.apply(with_image("busybox:v3"))
        assert converge_update(harness, max_rounds=240), harness.tree()
        harness.converge()
        pods = harness.store.list("Pod")
        assert all(is_ready(p) for p in pods), harness.tree()
        assert {c.image for p in pods for c in p.spec.containers} == {
            "busybox:v3"
        }
