"""Decision explainability (PR 13, docs/observability.md "Admission
explain"): wire-shape conformance for the three surfaces, the seeded
churn-storm TRUTHFULNESS property (a verdict is a prediction of the next
solve — fits_now=True must be followed by admission, every blocked_on
stage must match an independent NumPy recount), the read-only pin
(store rv vector + delta-state fingerprint byte-identical across an
explain/what-if burst), and the journey gap fix (pending gangs visible
at /debug/journeys with their last verdict)."""

import json
import random
import urllib.error
import urllib.request

import numpy as np
import pytest

from grove_tpu.api import names as namegen
from grove_tpu.api.meta import get_condition
from grove_tpu.api.pod import is_scheduled, is_terminating
from grove_tpu.api.types import COND_PODGANG_SCHEDULED
from grove_tpu.observability.events import (
    DETAIL_DISRUPTION_HOLD,
    DETAIL_INSUFFICIENT_CAPACITY,
    DETAIL_QUEUE_POSITION,
    DETAIL_QUOTA_CEILING,
    DETAIL_TOPOLOGY_FRAGMENTATION,
    REGISTERED_DETAILS,
)
from grove_tpu.observability.explain import FUNNEL_STAGES
from grove_tpu.sim.multitenant import (
    _explain_pcs,
    build_explain_scenario,
    tenant_queue,
)


def _get_json(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def _post_json(url: str, body: dict):
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _scheduled(harness, namespace: str, name: str) -> bool:
    gang = harness.store.get("PodGang", namespace, name)
    if gang is None:
        return False
    cond = get_condition(gang.status.conditions, COND_PODGANG_SCHEDULED)
    return cond is not None and cond.is_true()


@pytest.fixture(scope="module")
def scenario():
    """The contended scenario (one build per module — every verdict class
    at once) BEFORE any confirming converge."""
    harness, refs = build_explain_scenario()
    return harness, refs


class TestVerdicts:
    def test_three_classes_at_once(self, scenario):
        harness, refs = scenario
        frag = harness.explain.explain("default", refs["frag"])
        assert frag["binding_constraint"] == "topology"
        assert frag["detail"] == DETAIL_TOPOLOGY_FRAGMENTATION
        assert not frag["fits_now"]
        capped = harness.explain.explain("default", refs["capped"])
        assert capped["binding_constraint"] == "quota"
        assert capped["detail"] == DETAIL_QUOTA_CEILING
        fits = harness.explain.explain("default", refs["fits"])
        assert fits["fits_now"] and fits["binding_constraint"] is None

    def test_funnel_shape(self, scenario):
        harness, refs = scenario
        doc = harness.explain.explain("default", refs["frag"])
        stages = [f["stage"] for f in doc["funnel"]]
        assert stages == list(FUNNEL_STAGES)
        for row in doc["funnel"]:
            assert set(row) == {"stage", "surviving_nodes", "ok", "detail"}
            assert isinstance(row["surviving_nodes"], int)
        # blocked_on is exactly the failing funnel rows
        assert doc["blocked_on"] == [
            f for f in doc["funnel"] if not f["ok"]
        ]
        # surviving-node counts are monotone over the elimination stages
        heads = [f["surviving_nodes"] for f in doc["funnel"][:3]]
        assert heads == sorted(heads, reverse=True)
        assert doc["detail"] in REGISTERED_DETAILS

    def test_scheduled_gang_short_verdict(self, scenario):
        harness, refs = scenario
        # a filler gang is long scheduled
        filler = None
        for gang in harness.store.list("PodGang"):
            if gang.metadata.name.startswith("fill-"):
                filler = gang.metadata.name
                break
        doc = harness.explain.explain("default", filler)
        assert doc["state"] == "scheduled" and doc["fits_now"]
        assert doc["funnel"] == []

    def test_unknown_gang_is_none(self, scenario):
        harness, _refs = scenario
        assert harness.explain.explain("default", "no-such-gang") is None

    def test_capacity_report(self, scenario):
        harness, _refs = scenario
        cap = harness.explain.capacity()
        assert cap["kind"] == "CapacityReport"
        assert cap["superDomainLevel"] == "cloud.google.com/gke-tpu-slice"
        by_key = {lvl["key"]: lvl for lvl in cap["levels"]}
        block = by_key["cloud.google.com/gke-tpu-ici-block"]
        assert block["domainCount"] == 2
        # 6 cpu free total, 3 per block → frag = 1 - 3/6 = 0.5
        assert block["fragmentation"]["cpu"] == pytest.approx(0.5)
        assert block["largestDomainFree"]["cpu"] == pytest.approx(3.0)
        rows = block["domains"]
        assert [r["name"] for r in rows] == ["block-0", "block-1"]
        assert sum(r["free"]["cpu"] for r in rows) == pytest.approx(
            cap["totalFree"]["cpu"]
        )

    def test_whatif_drain_flips_and_set_queue(self, scenario):
        harness, refs = scenario
        doc = harness.explain.whatif(
            {
                "gang": {"namespace": "default", "name": refs["frag"]},
                "actions": [
                    {"action": "drain-node", "node": refs["bridge_node"]}
                ],
            }
        )
        assert doc["kind"] == "WhatIfReport"
        assert doc["flipped"] and doc["after"]["fits_now"]
        assert doc["after"]["hypothetical"] is True
        # bumping team-b's ceiling un-blocks the capped gang's quota hold
        # (it still cannot place — 3 cpu on 1-free nodes — so the binding
        # moves deeper down the funnel instead of vanishing)
        doc2 = harness.explain.whatif(
            {
                "gang": {"namespace": "default", "name": refs["capped"]},
                "actions": [
                    {
                        "action": "set-queue",
                        "queue": "team-b",
                        "ceiling": {"cpu": 100.0},
                    }
                ],
            }
        )
        assert doc2["before"]["detail"] == DETAIL_QUOTA_CEILING
        assert doc2["after"]["detail"] != DETAIL_QUOTA_CEILING
        assert not doc2["after"]["fits_now"]

    def test_whatif_rejects_malformed(self, scenario):
        harness, refs = scenario
        with pytest.raises(ValueError):
            harness.explain.whatif({"actions": [{"action": "drain-node"}]})
        with pytest.raises(ValueError):
            harness.explain.whatif(
                {"gang": {"namespace": "default", "name": refs["frag"]},
                 "actions": []}
            )
        with pytest.raises(ValueError):
            harness.explain.whatif(
                {"gang": {"namespace": "default", "name": refs["frag"]},
                 "actions": [{"action": "summon-nodes"}]}
            )
        with pytest.raises(ValueError):
            harness.explain.whatif(
                {"gang": {"namespace": "default", "name": refs["frag"]},
                 "actions": [{"action": "drain-node", "node": "nope"}]}
            )

    def test_all_nodes_cordoned_binds_node_health(self):
        """With zero schedulable nodes the binding constraint is
        node-health / no-schedulable-nodes — 'add capacity' would be the
        wrong advice when the fix is uncordoning."""
        from grove_tpu.observability.events import DETAIL_NO_NODES
        from grove_tpu.sim.harness import SimHarness

        harness = SimHarness(num_nodes=4)
        harness.apply(_explain_pcs("stuck", "default", 1.0))
        for _ in range(6):
            harness.engine.drain()
            harness.clock.advance(1.0)
        for node in harness.cluster.nodes:
            node.cordoned = True
        gangs = [
            g.metadata.name
            for g in harness.store.list("PodGang")
            if g.metadata.name.startswith("stuck")
        ]
        doc = harness.explain.explain("default", gangs[0])
        assert not doc["fits_now"]
        assert doc["binding_constraint"] == "node-health"
        assert doc["detail"] == DETAIL_NO_NODES
        # funnel[0] is the federation "cluster" stage (never a blocker);
        # node-health is the first stage that can fail
        assert doc["funnel"][0]["stage"] == "cluster"
        assert doc["funnel"][0]["ok"] is True
        assert doc["funnel"][1]["stage"] == "node-health"
        assert doc["funnel"][1]["ok"] is False

    def test_read_only_pin(self, scenario):
        """The hard contract: an explain/capacity/what-if burst leaves the
        store rv VECTOR and the delta-state fingerprint byte-identical."""
        harness, refs = scenario
        rv0 = harness.store.resource_version_vector()
        fp0 = harness.scheduler.delta.state_fingerprint()
        for _ in range(3):
            for name in (refs["frag"], refs["fits"], refs["capped"]):
                harness.explain.explain("default", name)
            harness.explain.capacity()
            harness.explain.whatif(
                {
                    "gang": {"namespace": "default", "name": refs["frag"]},
                    "actions": [
                        {"action": "drain-node",
                         "node": refs["bridge_node"]},
                        {"action": "add-nodes", "count": 2,
                         "like": refs["bridge_node"]},
                        {"action": "set-queue", "queue": "team-a",
                         "deserved": {"cpu": 16.0}},
                    ],
                }
            )
        assert harness.store.resource_version_vector() == rv0
        assert harness.scheduler.delta.state_fingerprint() == fp0


class TestWireConformance:
    def test_explain_capacity_whatif_endpoints(self, scenario):
        from grove_tpu.cluster.apiserver import APIServer

        harness, refs = scenario
        server = APIServer(store=harness.store).start()
        server.explain_engine = harness.explain
        try:
            doc = _get_json(
                server.address
                + f"/gangs/default/{refs['frag']}/explain"
            )
            assert doc["kind"] == "GangExplain"
            assert doc["namespace"] == "default"
            assert doc["name"] == refs["frag"]
            assert doc["binding_constraint"] == "topology"
            assert [f["stage"] for f in doc["funnel"]] == list(
                FUNNEL_STAGES
            )
            cap = _get_json(server.address + "/debug/capacity")
            assert cap["kind"] == "CapacityReport"
            assert {"nodes", "totalNodes", "totalFree", "levels",
                    "superDomainLevel", "resources"} <= set(cap)
            out = _post_json(
                server.address + "/debug/whatif",
                {
                    "gang": {"namespace": "default",
                             "name": refs["frag"]},
                    "actions": [
                        {"action": "drain-node",
                         "node": refs["bridge_node"]}
                    ],
                },
            )
            assert out["kind"] == "WhatIfReport" and out["flipped"]
            # 404s: unknown gang, malformed path
            for path in (
                "/gangs/default/nope/explain",
                "/gangs/default/explain",
            ):
                with pytest.raises(urllib.error.HTTPError) as err:
                    urllib.request.urlopen(
                        server.address + path, timeout=10
                    )
                assert err.value.code == 404
            # 400: malformed what-if
            with pytest.raises(urllib.error.HTTPError) as err:
                _post_json(
                    server.address + "/debug/whatif",
                    {"gang": {"namespace": "default"}, "actions": []},
                )
            assert err.value.code == 400
        finally:
            server.stop()

    def test_endpoints_404_without_engine(self):
        from grove_tpu.cluster.apiserver import APIServer

        server = APIServer().start()
        try:
            for path in (
                "/debug/capacity",
                "/gangs/default/g/explain",
            ):
                with pytest.raises(urllib.error.HTTPError) as err:
                    urllib.request.urlopen(
                        server.address + path, timeout=10
                    )
                assert err.value.code == 404
        finally:
            server.stop()

    def test_journeys_pending_list(self, scenario):
        """Journey gap fix: /debug/journeys surfaces un-scheduled gangs
        with age/stage, and the explain engine's last verdict once one
        was computed."""
        from grove_tpu.cluster.apiserver import APIServer
        from grove_tpu.observability.journey import JOURNEYS

        harness, refs = scenario
        JOURNEYS.enable()
        try:
            JOURNEYS.reset()
            # a pending scan marks the stuck gangs' journeys
            harness.explain.explain("default", refs["frag"])
            JOURNEYS.note_seen("default", refs["frag"])
            server = APIServer(store=harness.store).start()
            server.explain_engine = harness.explain
            try:
                doc = _get_json(server.address + "/debug/journeys")
                assert "pending" in doc
                rows = {
                    r["name"]: r
                    for r in doc["pending"]
                }
                assert refs["frag"] in rows
                row = rows[refs["frag"]]
                assert row["stage"] in ("created", "first-scan")
                assert row["age_s"] >= 0.0
                lv = row["last_verdict"]
                assert lv["fits_now"] is False
                assert lv["binding_constraint"] == "topology"
            finally:
                server.stop()
        finally:
            JOURNEYS.reset()
            JOURNEYS.disable()


# ---------------------------------------------------------------------------
# seeded churn-storm truthfulness property
# ---------------------------------------------------------------------------


def _pending_gang_names(harness):
    out = set()
    for pod in harness.cluster._not_ready_pods(None):
        if (
            pod.spec.scheduling_gates
            or is_scheduled(pod)
            or is_terminating(pod)
        ):
            continue
        gang = pod.metadata.labels.get(namegen.LABEL_PODGANG)
        if gang:
            out.add((pod.metadata.namespace, gang))
    return sorted(out)


def _gang_floor_oracle(harness, namespace, name):
    """Independent recount of a pending gang's floor demand: per group,
    min_replicas minus already-scheduled members, times the per-pod
    requests of its pending pods."""
    gang = harness.store.get("PodGang", namespace, name, readonly=True)
    pods = [
        p
        for p in harness.store.scan("Pod", namespace)
        if p.metadata.labels.get(namegen.LABEL_PODGANG) == name
        and not p.spec.scheduling_gates
        and not is_scheduled(p)
        and not is_terminating(p)
    ]
    by_group = {}
    for p in pods:
        by_group.setdefault(
            p.metadata.labels.get(namegen.LABEL_PODCLIQUE, ""), []
        ).append(p)
    floor = {}
    groups = {g.name: g for g in gang.spec.pod_groups}
    for gname, members in by_group.items():
        cr = groups.get(gname)
        already = sum(
            1
            for p in harness.store.scan(
                "Pod", namespace, {namegen.LABEL_PODCLIQUE: gname}
            )
            if is_scheduled(p) and not is_terminating(p)
        )
        min_count = max(
            0,
            (cr.min_replicas if cr is not None else len(members))
            - already,
        )
        reqs = members[0].spec.total_requests()
        for r, q in reqs.items():
            floor[r] = floor.get(r, 0.0) + q * min_count
    return floor


def _oracle_confirms(harness, verdict):
    """NumPy recount of the verdict's binding constraint from raw
    store/cluster state — independent of the introspect code paths."""
    ns, name = verdict["namespace"], verdict["name"]
    binding = verdict["binding_constraint"]
    detail = verdict["detail"]
    nodes = [n for n in harness.cluster.nodes if n.schedulable]
    free = harness.cluster.node_free_all(nodes)
    floor = _gang_floor_oracle(harness, ns, name)
    resources = sorted(
        set(floor) | {r for caps in free.values() for r in caps}
    )
    free_mat = np.array(
        [[free[n.name].get(r, 0.0) for r in resources] for n in nodes],
        dtype=np.float64,
    ) if nodes else np.zeros((0, len(resources)))
    floor_vec = np.array(
        [floor.get(r, 0.0) for r in resources], dtype=np.float64
    )
    if binding == "node-health":
        return len(nodes) == 0
    if binding == "capacity" and detail == DETAIL_INSUFFICIENT_CAPACITY:
        return bool((floor_vec > free_mat.sum(axis=0) + 1e-9).any())
    if binding == "topology" and detail == DETAIL_TOPOLOGY_FRAGMENTATION:
        gang = harness.store.get("PodGang", ns, name, readonly=True)
        tc = gang.spec.topology_constraint
        req = (
            tc.pack_constraint.required
            if tc is not None and tc.pack_constraint is not None
            else None
        )
        if req is None:
            return False
        level_keys = [
            lvl.key for lvl in harness.scheduler.topology.spec.levels
        ]
        li = level_keys.index(req)
        domains = {}
        for i, node in enumerate(nodes):
            path = tuple(
                node.labels.get(k, "") for k in level_keys[: li + 1]
            )
            domains.setdefault(path, []).append(i)
        need = floor_vec > 0
        covered = any(
            bool(
                (
                    free_mat[idxs].sum(axis=0)[need]
                    >= floor_vec[need] - 1e-9
                ).all()
            )
            for idxs in domains.values()
        )
        total_ok = bool(
            (free_mat.sum(axis=0)[need] >= floor_vec[need] - 1e-9).all()
        )
        return (not covered) and total_ok
    if binding == "quota" and detail == DETAIL_QUOTA_CEILING:
        # re-derive the FIFO ceiling hold for the gang's queue
        from grove_tpu.quota.oracle import usage_oracle

        gang = harness.store.get("PodGang", ns, name, readonly=True)
        queue = (
            gang.metadata.labels.get(namegen.LABEL_QUEUE) or "default"
        )
        cr = harness.store.get("Queue", "", queue, readonly=True)
        if cr is None or not cr.spec.ceiling:
            return False
        usage = usage_oracle(harness.store.scan("Pod"), "default").get(
            queue, {}
        )
        # queue-local flat order over the queue's pending gangs
        pending = [
            (gns, gname)
            for gns, gname in _pending_gang_names(harness)
            if (
                harness.store.get("PodGang", gns, gname, readonly=True)
                .metadata.labels.get(namegen.LABEL_QUEUE)
                or "default"
            )
            == queue
        ]
        pending.sort(key=lambda k: f"{k[0]}/{k[1]}")
        cum = dict(usage)
        for gns, gname in pending:
            demand = {}
            gcr = harness.store.get(
                "PodGang", gns, gname, readonly=True
            )
            for group in gcr.spec.pod_groups:
                for ref in group.pod_references:
                    p = harness.store.get(
                        "Pod", ref.namespace, ref.name, readonly=True
                    )
                    if p is not None:
                        for r, q in p.spec.total_requests().items():
                            demand[r] = demand.get(r, 0.0) + q
            over = any(
                cum.get(r, 0.0) + demand.get(r, 0.0) > cap + 1e-9
                for r, cap in cr.spec.ceiling.items()
            )
            if (gns, gname) == (ns, name):
                return over
            if not over:
                for r, q in demand.items():
                    cum[r] = cum.get(r, 0.0) + q
        return False
    if binding == "disruption" and detail == DETAIL_DISRUPTION_HOLD:
        return harness.scheduler.monitor.gang_held(ns, name)
    if detail == DETAIL_QUEUE_POSITION:
        return (verdict.get("queue", {}).get("rank") or 0) > 0
    # node-fragmentation / unsatisfiable: the packing kernel is the
    # authority; the funnel's coarser stages must all have passed
    return all(
        f["ok"]
        for f in verdict["funnel"]
        if f["stage"] in ("node-health", "capacity")
    )


@pytest.mark.parametrize("seed", [7, 42, 1234])
def test_churn_storm_truthfulness(seed):
    """The property the whole engine hangs on: pause a seeded churn
    storm, explain EVERY pending gang, run exactly one scheduling round
    with no intervening churn — every fits_now=True verdict must be
    followed by admission, every fits_now=False verdict must NOT be
    admitted that round, and every blocked verdict's binding constraint
    must survive the independent NumPy recount."""
    from grove_tpu.sim.cluster import make_nodes
    from grove_tpu.sim.harness import SimHarness

    rng = random.Random(seed)
    harness = SimHarness(num_nodes=1)
    harness.cluster.nodes = make_nodes(
        8, capacity={"cpu": 4.0}, hosts_per_ici_block=4,
        blocks_per_slice=1,
    )
    harness.apply_queue(tenant_queue("team-a", 16.0))
    harness.apply_queue(tenant_queue("team-b", 4.0, ceiling_cpu=6.0))
    harness.scheduler.quota.warm(3, 16)
    live = []
    counter = 0

    def submit():
        nonlocal counter
        counter += 1
        kind = rng.random()
        queue = rng.choice(["team-a", "team-b"])
        if kind < 0.25:
            pcs = _explain_pcs(
                f"storm-{seed}-{counter}", queue, 1.0,
                replicas=rng.choice([3, 4, 5]),
                pack_domain="ici-block",
            )
        elif kind < 0.5:
            pcs = _explain_pcs(
                f"storm-{seed}-{counter}", queue,
                rng.choice([2.0, 3.0]),
            )
        else:
            pcs = _explain_pcs(
                f"storm-{seed}-{counter}", queue, 1.0,
                replicas=rng.choice([1, 2]),
            )
        harness.apply(pcs)
        live.append(pcs.metadata.name)

    for _ in range(6):
        submit()
    harness.converge(max_ticks=40)
    # storm: submits, deletes, cordon flaps, partial converges
    for _ in range(10):
        op = rng.random()
        if op < 0.45:
            submit()
        elif op < 0.65 and live:
            victim = live.pop(rng.randrange(len(live)))
            try:
                harness.delete(victim)
            except Exception:
                pass
        elif op < 0.85:
            node = rng.choice(harness.cluster.nodes)
            node.cordoned = not node.cordoned
        if rng.random() < 0.5:
            harness.converge(max_ticks=3)
        else:
            harness.engine.drain()
            harness.clock.advance(1.0)
    # a final burst AFTER the last converge guarantees a non-empty
    # pending frontier to explain (quiet storms otherwise converge
    # everything); settle materialization WITHOUT solving so the
    # verdicts and the confirming round see the same frontier
    for _ in range(4):
        submit()
    for _ in range(6):
        harness.engine.drain()
        harness.clock.advance(1.0)

    pending = _pending_gang_names(harness)
    verdicts = []
    for ns, name in pending:
        v = harness.explain.explain(ns, name)
        assert v is not None
        if v["state"] == "no-pending-pods":
            continue
        verdicts.append(v)
        if not v["fits_now"]:
            # oracle recount runs against the SAME pre-round state the
            # verdict was computed from
            assert v["detail"] in REGISTERED_DETAILS
            assert _oracle_confirms(harness, v), (
                f"seed {seed}: oracle refutes the binding constraint"
                f" for {ns}/{name}: {v}"
            )
    assert verdicts, f"seed {seed}: storm left nothing pending to explain"

    # ONE round, zero intervening churn
    harness.schedule()

    for v in verdicts:
        ns, name = v["namespace"], v["name"]
        admitted = _scheduled(harness, ns, name)
        if v["fits_now"]:
            assert admitted, (
                f"seed {seed}: fits_now=True for {ns}/{name} but the"
                f" next solve did not admit it: {v}"
            )
        else:
            assert not admitted, (
                f"seed {seed}: fits_now=False for {ns}/{name} but the"
                f" next solve admitted it: {v}"
            )
