"""Docs-drift gates (grovelint satellite, docs/static-analysis.md):

1. Event reasons: every reason the code can emit (AST inventory over
   record()/record_event() call sites) ⊆ the registry in
   observability/events.py ⊆ the catalog table in docs/observability.md.
2. Metric names: every literal metric name passed to
   METRICS.inc/set/observe ⊆ the docs/observability.md metrics table, and
   every documented metric exists as a string literal in the code (the
   variable-assigned emitters like `metric="gang_preemptions_total"`
   resolve through the literal inventory).
3. Profiler phases: every literal phase an instrumented site opens
   (AST inventory over PROFILER.phase()/.reconcile() calls) ⊆ the PHASES
   registry in observability/profile.py ⊆ the docs table — the
   event-reason treatment applied to the glass-box layer (PR 12).
4. Journey phases: the JOURNEY_PHASES registry ⇄ the docs table (marks
   are internal to journey.py, so the registry itself is the inventory).

These pin the layers against each other so a new event/metric/phase
cannot ship undocumented, and a doc row cannot outlive its emitter.
"""

import pathlib
import re

import pytest

from grove_tpu.analysis.inventory import (
    all_string_literals,
    emitted_event_reasons,
    emitted_metric_names,
    emitted_profile_phases,
)
from grove_tpu.analysis.engine import repo_python_files
from grove_tpu.observability.events import (
    REGISTERED_DETAILS,
    REGISTERED_REASONS,
)
from grove_tpu.observability.explain import FUNNEL_STAGES
from grove_tpu.observability.journey import JOURNEY_PHASES, JOURNEY_SEGMENTS
from grove_tpu.observability.profile import PHASES

ROOT = pathlib.Path(__file__).resolve().parents[1]
OBS_DOC = ROOT / "docs" / "observability.md"


def _table_first_cells(section: str, pattern: str = r"`([A-Za-z0-9_]+)`"):
    """All code spans from the FIRST column of a markdown table section
    (cells may hold several names: `A` / `B` / `C`). Phase tables pass a
    hyphen-aware pattern — phase names like `pending-scan` are one name,
    not two."""
    names = set()
    for line in section.splitlines():
        line = line.strip()
        if not line.startswith("|"):
            continue
        first = line.split("|")[1]
        if set(first.strip()) <= {"-", ":", " "}:
            continue  # separator row
        names.update(re.findall(pattern, first))
    return names


def _doc_section(title: str) -> str:
    doc = OBS_DOC.read_text()
    assert f"## {title}" in doc, f"docs/observability.md lost its '{title}' section"
    return doc.split(f"## {title}", 1)[1].split("\n## ", 1)[0]


class TestEventReasonDrift:
    def test_emitted_subset_of_registry(self):
        emitted = emitted_event_reasons(ROOT)
        unregistered = set(emitted) - set(REGISTERED_REASONS)
        assert not unregistered, (
            "event reasons emitted but not registered in"
            f" observability/events.py: {sorted(unregistered)} (sites:"
            f" {[sorted(emitted[r]) for r in sorted(unregistered)]})"
        )

    def test_registry_subset_of_docs(self):
        documented = _table_first_cells(_doc_section("Event reasons"))
        undocumented = set(REGISTERED_REASONS) - documented
        assert not undocumented, (
            "registered event reasons missing from the"
            " docs/observability.md catalog table:"
            f" {sorted(undocumented)}"
        )

    def test_docs_not_stale(self):
        """Every documented reason is still registered (rows outliving
        their emitters read as live signals to operators)."""
        documented = _table_first_cells(_doc_section("Event reasons"))
        stale = documented - set(REGISTERED_REASONS)
        assert not stale, (
            "docs/observability.md documents reasons no longer in the"
            f" registry: {sorted(stale)}"
        )

    def test_registry_is_emittable(self):
        """Registered but never-emitted reasons are dead registry weight
        (catches renames that orphan a constant)."""
        emitted = set(emitted_event_reasons(ROOT))
        dead = set(REGISTERED_REASONS) - emitted
        assert not dead, (
            "registered reasons with no emitting call site:"
            f" {sorted(dead)}"
        )


class TestMetricNameDrift:
    @pytest.fixture(scope="class")
    def documented(self):
        return _table_first_cells(_doc_section("Metrics catalog"))

    def test_code_metrics_documented(self, documented):
        emitted = emitted_metric_names(ROOT)
        undocumented = set(emitted) - documented
        assert not undocumented, (
            "metrics emitted but missing from the docs/observability.md"
            f" table: {sorted(undocumented)} (sites:"
            f" {[sorted(emitted[m]) for m in sorted(undocumented)]})"
        )

    def test_documented_metrics_exist_in_code(self, documented):
        literals = all_string_literals(ROOT, repo_python_files(ROOT))
        # f-string heads keep their '/label' / '@shard' tails — normalize
        # to base names (observability/metrics.py grammar)
        bases = {
            lit.split("/", 1)[0].split("@", 1)[0] for lit in literals
        }
        missing = {m for m in documented if m not in bases}
        assert not missing, (
            "docs/observability.md documents metrics with no emitting"
            f" literal in grove_tpu/: {sorted(missing)}"
        )


_DASHED = r"`([A-Za-z0-9_-]+)`"


class TestProfilerPhaseDrift:
    """The glass-box analogue of the event-reason gates: instrumented
    phases ⊆ the profile.py PHASES registry ⊆ the docs table, and no doc
    row outlives its call sites."""

    def test_emitted_subset_of_registry(self):
        emitted = emitted_profile_phases(ROOT)
        unregistered = set(emitted) - set(PHASES)
        assert not unregistered, (
            "profiler phases opened but not registered in"
            f" observability/profile.py PHASES: {sorted(unregistered)}"
            f" (sites: {[sorted(emitted[p]) for p in sorted(unregistered)]})"
        )

    def test_registry_subset_of_docs(self):
        documented = _table_first_cells(
            _doc_section("Wall-attribution profiler"), _DASHED
        )
        undocumented = set(PHASES) - documented
        assert not undocumented, (
            "registered profiler phases missing from the"
            " docs/observability.md table:"
            f" {sorted(undocumented)}"
        )

    def test_docs_not_stale(self):
        documented = _table_first_cells(
            _doc_section("Wall-attribution profiler"), _DASHED
        )
        stale = documented - set(PHASES)
        assert not stale, (
            "docs/observability.md documents profiler phases no longer"
            f" in the registry: {sorted(stale)}"
        )

    def test_registry_is_emitted(self):
        """A registered-but-never-opened phase is dead registry weight."""
        emitted = set(emitted_profile_phases(ROOT))
        dead = set(PHASES) - emitted
        assert not dead, (
            "registered profiler phases with no opening call site:"
            f" {sorted(dead)}"
        )


class TestExplainDrift:
    """The explain layer's docs gates (PR 13): the funnel-stage registry
    and the deferral-detail registry ⇄ the docs/observability.md
    "Admission explain" tables, and the fragmentation-statistic
    definition shared VERBATIM with docs/solver.md."""

    FRAG_FORMULA = (
        "frag(level, resource) = 1 − largest single-domain free ∕"
        " total free"
    )

    @pytest.fixture(scope="class")
    def documented(self):
        # the section holds two tables (stages + details); both registries
        # gate against the union, staleness against the union too
        return _table_first_cells(_doc_section("Admission explain"), _DASHED)

    def test_funnel_stages_documented(self, documented):
        missing = set(FUNNEL_STAGES) - documented
        assert not missing, (
            "funnel stages missing from the docs/observability.md"
            f" 'Admission explain' table: {sorted(missing)}"
        )

    def test_details_documented(self, documented):
        missing = set(REGISTERED_DETAILS) - documented
        assert not missing, (
            "registered deferral details missing from the"
            " docs/observability.md 'Admission explain' table:"
            f" {sorted(missing)}"
        )

    def test_docs_not_stale(self, documented):
        stale = documented - set(FUNNEL_STAGES) - set(REGISTERED_DETAILS)
        assert not stale, (
            "docs/observability.md 'Admission explain' documents names"
            " that are neither funnel stages nor registered details:"
            f" {sorted(stale)}"
        )

    def test_fragmentation_definition_shared(self):
        """One definition, two documents: the formula line must appear
        verbatim in both docs/observability.md and docs/solver.md — the
        explain verdicts and the solver's scoring roadmap must never
        describe two different statistics."""
        for doc in (OBS_DOC, ROOT / "docs" / "solver.md"):
            assert self.FRAG_FORMULA in doc.read_text(), (
                f"{doc.name} lost the shared fragmentation-statistic"
                f" definition line: {self.FRAG_FORMULA!r}"
            )

    def test_details_emitted(self):
        """Every registered detail slug has a producing site in the
        explain/introspect/scheduler layer (dead-registry gate, the
        event-reason treatment): slugs are produced via the DETAIL_*
        constants, so the gate is a constant referenced outside
        events.py."""
        import ast

        referenced = set()
        for rel in repo_python_files(ROOT):
            if rel.endswith("observability/events.py"):
                continue
            tree = ast.parse((ROOT / rel).read_text())
            for node in ast.walk(tree):
                if isinstance(node, ast.Name) and node.id.startswith(
                    "DETAIL_"
                ):
                    referenced.add(node.id)
        from grove_tpu.observability import events as _ev

        dead = {
            k
            for k in dir(_ev)
            if k.startswith("DETAIL_") and k not in referenced
        }
        assert not dead, (
            "registered detail constants with no producing reference"
            f" outside events.py: {sorted(dead)}"
        )


class TestServingSignalDrift:
    """The SLO observatory's gates (PR 14): the serving-signals registry
    (observability/timeseries.py SERVING_SIGNALS) ⇄ the
    docs/observability.md "SLO observatory" table, the event-reason
    treatment applied to time-series names."""

    def test_signals_documented(self):
        from grove_tpu.observability.timeseries import SERVING_SIGNALS

        documented = _table_first_cells(
            _doc_section("SLO observatory"), _DASHED
        )
        missing = set(SERVING_SIGNALS) - documented
        assert not missing, (
            "serving signals missing from the docs/observability.md"
            f" 'SLO observatory' table: {sorted(missing)}"
        )

    def test_docs_signals_not_stale(self):
        """Every table row naming a series still exists in the registry
        (the section's one table IS the signals table; prose code spans
        are not table cells, so the gate stays exact)."""
        from grove_tpu.observability.timeseries import SERVING_SIGNALS

        documented = _table_first_cells(
            _doc_section("SLO observatory"), _DASHED
        )
        stale = documented - set(SERVING_SIGNALS)
        assert not stale, (
            "docs/observability.md 'SLO observatory' table documents"
            f" series not in SERVING_SIGNALS: {sorted(stale)}"
        )

    def test_signals_fed(self):
        """Every registered signal has a feeding site (dead-registry
        gate): its SERIES_* constant is READ somewhere — a feed site in
        the journey tracker / serving scenario, or the sampler collector
        in timeseries.py itself (Load context only, so the registry
        definitions and the SERVING_SIGNALS tuple don't self-satisfy)."""
        import ast

        referenced = set()
        for rel in repo_python_files(ROOT):
            tree = ast.parse((ROOT / rel).read_text())
            # the registry tuple's own member list is not a feed
            skip = set()
            for node in ast.walk(tree):
                if (
                    isinstance(node, ast.Assign)
                    and any(
                        isinstance(t, ast.Name)
                        and t.id == "SERVING_SIGNALS"
                        for t in node.targets
                    )
                ):
                    skip = {
                        n for n in ast.walk(node.value)
                        if isinstance(n, ast.Name)
                    }
            for node in ast.walk(tree):
                if node in skip:
                    continue
                name = (
                    node.id
                    if isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    else node.attr
                    if isinstance(node, ast.Attribute)
                    else None
                )
                if name and name.startswith("SERIES_"):
                    referenced.add(name)
        from grove_tpu.observability import timeseries as _ts

        dead = {
            k
            for k in dir(_ts)
            if k.startswith("SERIES_") and k not in referenced
        }
        assert not dead, (
            "registered serving signals with no feeding reference"
            f" outside timeseries.py: {sorted(dead)}"
        )


class TestJourneyPhaseDrift:
    def test_registry_matches_docs(self):
        """Journey phases (and derived segments) ⇄ the docs table — the
        marks are internal to journey.py, so the importable registry is
        the code-side inventory."""
        documented = _table_first_cells(
            _doc_section("Gang journeys"), _DASHED
        )
        assert set(JOURNEY_PHASES) <= documented, (
            "journey phases missing from the docs/observability.md"
            f" table: {sorted(set(JOURNEY_PHASES) - documented)}"
        )
        stale = documented - set(JOURNEY_PHASES)
        assert not stale, (
            "docs/observability.md documents journey phases no longer in"
            f" JOURNEY_PHASES: {sorted(stale)}"
        )
        # every derived segment the decomposition reports is described in
        # the section body (prose, not the table)
        section = _doc_section("Gang journeys")
        missing = [
            seg for seg in JOURNEY_SEGMENTS if f"`{seg}`" not in section
        ]
        assert not missing, (
            "journey segments undescribed in docs/observability.md:"
            f" {missing}"
        )


class TestLedgerActionDrift:
    """The remediation loop's gates (PR 16): the ledger's trigger/action
    registries (observability/ledger.py TRIGGER_KINDS / ACTION_KINDS) ⇄
    the docs/observability.md "Remediation & ledger" kind tables — the
    event-reason treatment applied to the causal ledger's vocabulary, so
    a new action kind cannot ship without its mechanics documented."""

    @property
    def _documented(self):
        return _table_first_cells(
            _doc_section("Remediation & ledger"), _DASHED
        )

    def test_kinds_documented(self):
        from grove_tpu.observability.ledger import (
            ACTION_KINDS,
            TRIGGER_KINDS,
        )

        registered = set(TRIGGER_KINDS) | set(ACTION_KINDS)
        missing = registered - self._documented
        assert not missing, (
            "ledger trigger/action kinds missing from the"
            " docs/observability.md 'Remediation & ledger' tables:"
            f" {sorted(missing)}"
        )

    def test_docs_kinds_not_stale(self):
        """The section's tables ARE the kind tables: every first-column
        code span must name a registered trigger or action kind."""
        from grove_tpu.observability.ledger import (
            ACTION_KINDS,
            TRIGGER_KINDS,
        )

        registered = set(TRIGGER_KINDS) | set(ACTION_KINDS)
        stale = self._documented - registered
        assert not stale, (
            "docs/observability.md 'Remediation & ledger' tables document"
            f" kinds not in the ledger registries: {sorted(stale)}"
        )

    def test_kinds_used_by_the_controller(self):
        """Dead-registry gate: every registered kind constant is READ in
        the controller or its owning module's callers — a kind nobody can
        emit is documentation theater. String-level check: the literal
        value appears outside ledger.py (the controller imports the
        ACTION_*/TRIGGER_* constants, smokes assert against the tuples)."""
        from grove_tpu.observability.ledger import (
            ACTION_KINDS,
            TRIGGER_KINDS,
        )

        corpus = ""
        for rel in repo_python_files(ROOT):
            if rel.endswith("observability/ledger.py"):
                continue
            corpus += (ROOT / rel).read_text()
        constants = {
            "slo-burn": "TRIGGER_SLO_BURN",
            "forecast-peak": "TRIGGER_FORECAST_PEAK",
            "frag-threshold": "TRIGGER_FRAG_THRESHOLD",
            "fail-slow": "TRIGGER_FAILSLOW",
            "drain-node": "ACTION_DRAIN_NODE",
            "migrate-gang": "ACTION_MIGRATE_GANG",
            "scale-up": "ACTION_SCALE_UP",
        }
        dead = [
            kind
            for kind in (*TRIGGER_KINDS, *ACTION_KINDS)
            if constants.get(kind, "\x00") not in corpus
            and f'"{kind}"' not in corpus
        ]
        assert not dead, (
            "ledger kinds with no emitting/asserting reference outside"
            f" ledger.py: {dead}"
        )
