"""Docs-drift gates (grovelint satellite, docs/static-analysis.md):

1. Event reasons: every reason the code can emit (AST inventory over
   record()/record_event() call sites) ⊆ the registry in
   observability/events.py ⊆ the catalog table in docs/observability.md.
2. Metric names: every literal metric name passed to
   METRICS.inc/set/observe ⊆ the docs/observability.md metrics table, and
   every documented metric exists as a string literal in the code (the
   variable-assigned emitters like `metric="gang_preemptions_total"`
   resolve through the literal inventory).

These pin the three layers against each other so a new event/metric
cannot ship undocumented, and a doc row cannot outlive its emitter.
"""

import pathlib
import re

import pytest

from grove_tpu.analysis.inventory import (
    all_string_literals,
    emitted_event_reasons,
    emitted_metric_names,
)
from grove_tpu.analysis.engine import repo_python_files
from grove_tpu.observability.events import REGISTERED_REASONS

ROOT = pathlib.Path(__file__).resolve().parents[1]
OBS_DOC = ROOT / "docs" / "observability.md"


def _table_first_cells(section: str):
    """All code spans from the FIRST column of a markdown table section
    (cells may hold several names: `A` / `B` / `C`)."""
    names = set()
    for line in section.splitlines():
        line = line.strip()
        if not line.startswith("|"):
            continue
        first = line.split("|")[1]
        if set(first.strip()) <= {"-", ":", " "}:
            continue  # separator row
        names.update(re.findall(r"`([A-Za-z0-9_]+)`", first))
    return names


def _doc_section(title: str) -> str:
    doc = OBS_DOC.read_text()
    assert f"## {title}" in doc, f"docs/observability.md lost its '{title}' section"
    return doc.split(f"## {title}", 1)[1].split("\n## ", 1)[0]


class TestEventReasonDrift:
    def test_emitted_subset_of_registry(self):
        emitted = emitted_event_reasons(ROOT)
        unregistered = set(emitted) - set(REGISTERED_REASONS)
        assert not unregistered, (
            "event reasons emitted but not registered in"
            f" observability/events.py: {sorted(unregistered)} (sites:"
            f" {[sorted(emitted[r]) for r in sorted(unregistered)]})"
        )

    def test_registry_subset_of_docs(self):
        documented = _table_first_cells(_doc_section("Event reasons"))
        undocumented = set(REGISTERED_REASONS) - documented
        assert not undocumented, (
            "registered event reasons missing from the"
            " docs/observability.md catalog table:"
            f" {sorted(undocumented)}"
        )

    def test_docs_not_stale(self):
        """Every documented reason is still registered (rows outliving
        their emitters read as live signals to operators)."""
        documented = _table_first_cells(_doc_section("Event reasons"))
        stale = documented - set(REGISTERED_REASONS)
        assert not stale, (
            "docs/observability.md documents reasons no longer in the"
            f" registry: {sorted(stale)}"
        )

    def test_registry_is_emittable(self):
        """Registered but never-emitted reasons are dead registry weight
        (catches renames that orphan a constant)."""
        emitted = set(emitted_event_reasons(ROOT))
        dead = set(REGISTERED_REASONS) - emitted
        assert not dead, (
            "registered reasons with no emitting call site:"
            f" {sorted(dead)}"
        )


class TestMetricNameDrift:
    @pytest.fixture(scope="class")
    def documented(self):
        return _table_first_cells(_doc_section("Metrics catalog"))

    def test_code_metrics_documented(self, documented):
        emitted = emitted_metric_names(ROOT)
        undocumented = set(emitted) - documented
        assert not undocumented, (
            "metrics emitted but missing from the docs/observability.md"
            f" table: {sorted(undocumented)} (sites:"
            f" {[sorted(emitted[m]) for m in sorted(undocumented)]})"
        )

    def test_documented_metrics_exist_in_code(self, documented):
        literals = all_string_literals(ROOT, repo_python_files(ROOT))
        # f-string heads keep their '/label' tail — normalize to base names
        bases = {lit.split("/", 1)[0] for lit in literals}
        missing = {m for m in documented if m not in bases}
        assert not missing, (
            "docs/observability.md documents metrics with no emitting"
            f" literal in grove_tpu/: {sorted(missing)}"
        )
