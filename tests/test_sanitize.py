"""Runtime sanitizer (grove_tpu/analysis/sanitize.py) unit tests: each
dynamic check must detect its failure class, install/uninstall must be
clean, and a sanitized harness converge must stay green."""

import threading

import pytest

from grove_tpu.analysis import sanitize


class TestLockOrderTracker:
    def test_consistent_order_is_clean(self):
        t = sanitize.LockOrderTracker()
        a = sanitize.TrackingLock(threading.Lock(), "A", t)
        b = sanitize.TrackingLock(threading.Lock(), "B", t)
        for _ in range(3):
            with a:
                with b:
                    pass
        assert t.violations == []
        assert t.observed_order() == ["A -> B"]

    def test_inversion_detected(self):
        t = sanitize.LockOrderTracker()
        a = sanitize.TrackingLock(threading.Lock(), "A", t)
        b = sanitize.TrackingLock(threading.Lock(), "B", t)
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        assert len(t.violations) == 1
        assert "inversion" in t.violations[0]

    def test_transitive_inversion_detected(self):
        t = sanitize.LockOrderTracker()
        locks = {
            n: sanitize.TrackingLock(threading.Lock(), n, t)
            for n in "ABC"
        }
        with locks["A"]:
            with locks["B"]:
                pass
        with locks["B"]:
            with locks["C"]:
                pass
        with locks["C"]:
            with locks["A"]:  # closes the A->B->C cycle
                pass
        assert t.violations, "A->B->C->A cycle must be detected"

    def test_reentrant_same_lock_ignored(self):
        t = sanitize.LockOrderTracker()
        inner = threading.RLock()
        a = sanitize.TrackingLock(inner, "A", t)
        with a:
            with a:
                pass
        assert t.violations == []

    def test_cross_thread_order_is_global(self):
        """The partial order is process-global: thread 1 establishing
        A->B makes thread 2's B->A an inversion."""
        t = sanitize.LockOrderTracker()
        a = sanitize.TrackingLock(threading.Lock(), "A", t)
        b = sanitize.TrackingLock(threading.Lock(), "B", t)

        def t1():
            with a:
                with b:
                    pass

        th = threading.Thread(target=t1)
        th.start()
        th.join()
        with b:
            with a:
                pass
        assert len(t.violations) == 1


class TestInstallUninstall:
    def test_span_leak_detection(self, monkeypatch):
        monkeypatch.delenv("GROVE_TPU_SANITIZE", raising=False)
        san = sanitize.install()
        try:
            from grove_tpu.observability.tracing import TRACER

            leaky = TRACER.span("leaky-span")
            with TRACER.span("closed-span"):
                pass
            assert san.spans.leaked() == ["leaky-span"]
            assert any("leaked span" in p for p in san.problems())
            leaky.end()
            assert san.spans.leaked() == []
            assert san.problems() == []
        finally:
            sanitize.uninstall()
        assert not sanitize.active()

    def test_install_wraps_singleton_locks(self, monkeypatch):
        monkeypatch.delenv("GROVE_TPU_SANITIZE", raising=False)
        from grove_tpu.observability.events import EVENTS
        from grove_tpu.observability.metrics import METRICS

        sanitize.install()
        try:
            assert isinstance(EVENTS._lock, sanitize.TrackingLock)
            assert isinstance(METRICS._lock, sanitize.TrackingLock)
            # the wrapped singletons still work end to end
            EVENTS.record(("Pod", "default", "p"), "Normal", "PodBound", "x")
            METRICS.inc("sanitize_test_counter")
        finally:
            sanitize.uninstall()
        assert not isinstance(EVENTS._lock, sanitize.TrackingLock)
        assert not isinstance(METRICS._lock, sanitize.TrackingLock)

    def test_install_is_idempotent(self, monkeypatch):
        monkeypatch.delenv("GROVE_TPU_SANITIZE", raising=False)
        first = sanitize.install()
        try:
            assert sanitize.install() is first
        finally:
            sanitize.uninstall()

    def test_uninstall_restores_externally_set_env(self, monkeypatch):
        """A user-set GROVE_TPU_SANITIZE=1 must survive an
        install()/uninstall() cycle (e.g. one sanitized matrix seed must
        not strip the guard from the seeds after it)."""
        monkeypatch.setenv("GROVE_TPU_SANITIZE", "1")
        sanitize.install()
        sanitize.uninstall()
        import os

        assert os.environ.get("GROVE_TPU_SANITIZE") == "1"
        assert sanitize.store_guard_enabled()

    def test_enabled_env_gates_store_guard(self, monkeypatch):
        monkeypatch.delenv("GROVE_TPU_STORE_GUARD", raising=False)
        monkeypatch.delenv("GROVE_TPU_SANITIZE", raising=False)
        assert not sanitize.store_guard_enabled()
        monkeypatch.setenv("GROVE_TPU_SANITIZE", "1")
        assert sanitize.store_guard_enabled()
        monkeypatch.delenv("GROVE_TPU_SANITIZE", raising=False)
        monkeypatch.setenv("GROVE_TPU_STORE_GUARD", "1")
        assert sanitize.store_guard_enabled()


class TestHarnessChecks:
    @pytest.fixture()
    def harness(self):
        from grove_tpu.sim.harness import SimHarness

        h = SimHarness(num_nodes=4)
        h.apply_yaml(
            """
apiVersion: grove.io/v1alpha1
kind: PodCliqueSet
metadata:
  name: tiny
spec:
  template:
    cliques:
      - name: w
        spec:
          roleName: w
          replicas: 1
          podSpec:
            containers:
              - name: c
                resources:
                  requests:
                    cpu: 1
"""
        )
        h.converge(max_ticks=40)
        return h

    def test_accountant_drift_clean_after_converge(self, harness):
        assert (
            sanitize.accountant_drift(
                harness.scheduler.quota.accountant, harness.store
            )
            == []
        )

    def test_accountant_drift_detects_skew(self, harness):
        acct = harness.scheduler.quota.accountant
        acct.ensure_built(harness.store)
        snap = acct.snapshot()
        assert snap, "converged harness must account some usage"
        queue = next(iter(snap))
        resource = next(iter(snap[queue]))
        # skew the incremental ledger: the recount must catch it
        acct._usage[queue][resource] += 1.5
        problems = sanitize.accountant_drift(acct, harness.store)
        assert problems and "!= recount" in problems[0]
        acct._usage[queue][resource] -= 1.5

    def test_stranded_hold_detected(self, harness):
        monitor = harness.node_monitor
        assert sanitize.stranded_holds(monitor) == []
        # a hold with no scheduled release is the failover bug class
        monitor._held.add(("default", "phantom-gang"))
        problems = sanitize.stranded_holds(monitor)
        assert problems and "stranded" in problems[0]
        monitor._held.discard(("default", "phantom-gang"))

    def test_harness_problems_green_on_healthy_tree(self, harness):
        assert sanitize.harness_problems(harness) == []
