"""Admission webhook HTTP(S) server.

The webhook surface of /root/reference/operator/internal/webhook/register.go:
defaulting (mutating), validation (create + update, incl. ClusterTopology),
and the authorizer — served as AdmissionReview-speaking HTTP endpoints backed
by the pure functions in grove_tpu.admission. The mutating response returns
the fully defaulted object in `response.patchedObject` (a documented
simplification of the reference's JSONPatch encoding — same wire boundary,
simpler patch algebra).

Runs plain HTTP or TLS with certs from grove_tpu.cluster.cert (the cert
controller re-host); registrations for the apiserver come from
`default_registrations`.
"""

from __future__ import annotations

import json
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from grove_tpu.admission.authorization import AuthorizationGuard
from grove_tpu.admission.defaulting import default_podcliqueset
from grove_tpu.admission.validation import (
    validate_cluster_topology,
    validate_podcliqueset,
    validate_podcliqueset_update,
)
from grove_tpu.api.serialize import export_object
from grove_tpu.api.topology import ClusterTopology
from grove_tpu.api.wire import decode_object
from grove_tpu.cluster.apiserver import WebhookRegistration
from grove_tpu.cluster.cert import CertPaths


class WebhookServer:
    def __init__(
        self,
        topology: Optional[ClusterTopology] = None,
        guard: Optional[AuthorizationGuard] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        certs: Optional[CertPaths] = None,
    ) -> None:
        self.topology = topology or ClusterTopology()
        self.guard = guard
        self.certs = certs
        self._httpd = ThreadingHTTPServer((host, port), self._make_handler())
        self._httpd.daemon_threads = True
        if certs is not None:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(str(certs.server_cert), str(certs.server_key))
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True
            )
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        scheme = "https" if self.certs is not None else "http"
        return f"{scheme}://{host}:{port}"

    def start(self) -> "WebhookServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="grove-webhooks", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def registrations(self) -> List[WebhookRegistration]:
        """What the reference registers on its webhook server
        (register.go:35-75): PCS defaulting + validation, ClusterTopology
        validation, and the authorizer over grove-managed child kinds."""
        ca = str(self.certs.ca_cert) if self.certs is not None else None
        regs = [
            WebhookRegistration(
                name="default-podcliqueset",
                kinds=["PodCliqueSet"],
                url=f"{self.address}/webhooks/mutate-podcliqueset",
                mutating=True,
                ca_file=ca,
            ),
            WebhookRegistration(
                name="validate-podcliqueset",
                kinds=["PodCliqueSet"],
                url=f"{self.address}/webhooks/validate-podcliqueset",
                ca_file=ca,
            ),
            WebhookRegistration(
                name="validate-clustertopology",
                kinds=["ClusterTopology"],
                url=f"{self.address}/webhooks/validate-clustertopology",
                ca_file=ca,
            ),
        ]
        if self.guard is not None:
            from grove_tpu.admission.authorization import MANAGED_KINDS

            regs.append(
                WebhookRegistration(
                    name="authorize-managed-resources",
                    kinds=list(MANAGED_KINDS),
                    url=f"{self.address}/webhooks/authorize",
                    operations=("CREATE", "UPDATE", "DELETE"),
                    ca_file=ca,
                )
            )
        return regs

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _respond(self, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _review_response(
                self,
                allowed: bool,
                message: str = "",
                patched: Optional[dict] = None,
            ) -> dict:
                out = {
                    "apiVersion": "admission.k8s.io/v1",
                    "kind": "AdmissionReview",
                    "response": {"allowed": allowed},
                }
                if message:
                    out["response"]["status"] = {"message": message}
                if patched is not None:
                    out["response"]["patchType"] = "Full"
                    out["response"]["patchedObject"] = patched
                return out

            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                review = json.loads(self.rfile.read(length) or b"{}")
                request = review.get("request") or {}
                endpoint = self.path.rstrip("/").rsplit("/", 1)[-1]
                try:
                    handler = {
                        "mutate-podcliqueset": self._mutate_pcs,
                        "validate-podcliqueset": self._validate_pcs,
                        "validate-clustertopology": self._validate_topology,
                        "authorize": self._authorize,
                    }.get(endpoint)
                    if handler is None:
                        return self._respond(
                            self._review_response(
                                False, f"unknown webhook {endpoint!r}"
                            )
                        )
                    return self._respond(handler(request))
                except Exception as e:  # webhook crash = denial, not 500 loop
                    return self._respond(
                        self._review_response(False, f"webhook error: {e}")
                    )

            def _mutate_pcs(self, request: dict) -> dict:
                pcs = decode_object(request["object"])
                default_podcliqueset(pcs)
                return self._review_response(True, patched=export_object(pcs))

            def _validate_pcs(self, request: dict) -> dict:
                pcs = decode_object(request["object"])
                if request.get("operation") == "UPDATE" and request.get(
                    "oldObject"
                ):
                    old = decode_object(request["oldObject"])
                    res = validate_podcliqueset_update(
                        pcs, old, server.topology
                    )
                else:
                    res = validate_podcliqueset(pcs, server.topology)
                if res.ok:
                    return self._review_response(True)
                return self._review_response(False, "; ".join(res.errors))

            def _validate_topology(self, request: dict) -> dict:
                topo = decode_object(request["object"])
                res = validate_cluster_topology(topo)
                if res.ok:
                    return self._review_response(True)
                return self._review_response(False, "; ".join(res.errors))

            def _authorize(self, request: dict) -> dict:
                obj = decode_object(request["object"])
                username = (request.get("userInfo") or {}).get("username", "")
                decision = server.guard.check(
                    username, request.get("operation", "").lower(), obj
                )
                return self._review_response(decision.allowed, decision.reason)

        return Handler
