"""Envtest-style HTTP apiserver over the in-memory Store.

Speaks the k8s REST wire shape the reference operator talks to:

    GET/POST   /apis/{group}/{version}/namespaces/{ns}/{plural}
    GET/PUT/DELETE  .../{plural}/{name}
    PUT        .../{plural}/{name}/status          (status subresource)
    GET        .../{plural}?watch=true             (list+watch stream)
    DELETE     .../{plural}?labelSelector=...      (delete collection)
    /api/v1/... for core kinds; cluster-scoped paths omit namespaces/{ns}

plus /healthz /readyz /metrics. Admission webhooks (mutating → validating)
are invoked over HTTP on create/update of configured kinds, mirroring the
registration boundary of
/root/reference/operator/internal/webhook/register.go:35-75. Writes carry an
optional Impersonate-User header honored via the store's actor context
(authorizer parity: admission/pcs/authorization/handler.go:51-158).

This is both the e2e harness's fake cluster (reference envtest tier,
SURVEY §4.2) and the wire contract an external scheduler (KAI-equivalent)
can consume PodGangs from.
"""

from __future__ import annotations

import json
import math
import queue
import threading
import urllib.parse
import urllib.request
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

from grove_tpu.api.serialize import export_object
from grove_tpu.api.wire import (
    KIND_REGISTRY,
    KindInfo,
    decode_object,
    resolve_path_kind,
)
from grove_tpu.observability.metrics import METRICS
from grove_tpu.runtime.clock import Clock
from grove_tpu.runtime.errors import (
    ERR_CONFLICT,
    ERR_FORBIDDEN,
    ERR_NOT_FOUND,
    GroveError,
)
from grove_tpu.runtime.store import Store, WatchEvent


@dataclass
class WebhookRegistration:
    """One admission webhook the server calls for matching writes
    (webhook/register.go registers defaulting, validation, authorization)."""

    name: str
    kinds: List[str]
    url: str
    mutating: bool = False
    operations: Tuple[str, ...] = ("CREATE", "UPDATE")
    # CA bundle file for TLS webhook endpoints (cert.py output)
    ca_file: Optional[str] = None


@dataclass
class AdmissionDenied(Exception):
    message: str


def _http_status_for(err: GroveError) -> int:
    return {
        ERR_NOT_FOUND: 404,
        ERR_CONFLICT: 409,
        ERR_FORBIDDEN: 403,
    }.get(err.code, 500)


@dataclass
class _WatchSub:
    q: "queue.Queue[Optional[WatchEvent]]"
    kind: str
    namespace: Optional[str]
    selector: Optional[Dict[str, str]]


def _sample_profile(seconds: float, hz: float = 100.0) -> str:
    """In-process sampling profiler: aggregate (file:line function) frame
    counts across ALL threads for `seconds` — the whole-process view a
    pprof endpoint gives, without a tracing profiler's overhead."""
    import sys
    import time as _time

    counts: Dict[str, int] = {}
    own = threading.get_ident()
    deadline = _time.monotonic() + seconds
    interval = 1.0 / hz
    samples = 0
    while _time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == own:
                continue
            while frame is not None:
                code = frame.f_code
                key = f"{code.co_filename}:{frame.f_lineno} {code.co_name}"
                counts[key] = counts.get(key, 0) + 1
                frame = frame.f_back
        samples += 1
        _time.sleep(interval)
    lines = [f"# {samples} samples over {seconds}s at ~{hz:.0f}Hz"]
    for key, n in sorted(counts.items(), key=lambda kv: -kv[1])[:80]:
        lines.append(f"{n:8d} {key}")
    return "\n".join(lines) + "\n"


def parse_label_selector(raw: Optional[str]) -> Optional[Dict[str, str]]:
    if not raw:
        return None
    out: Dict[str, str] = {}
    for part in raw.split(","):
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"unsupported label selector: {raw!r}")
        k, _, v = part.partition("=")
        out[k.strip()] = v.strip()
    return out


class APIServer:
    def __init__(
        self,
        store: Optional[Store] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        webhooks: Optional[List[WebhookRegistration]] = None,
        enable_profiling: bool = False,
        node_provider: Optional[Callable[[], List[dict]]] = None,
    ) -> None:
        self.store = store or Store(Clock())
        self.lock = threading.RLock()
        # GET /nodes source (docs/observability.md): wire-shape node rows
        # — typically NodeHealthMonitor.node_snapshot. Nodes are cluster
        # infrastructure, not store objects, so they arrive by callback;
        # None → an empty list (server without a sim cluster attached).
        self.node_provider = node_provider
        # POST /nodes/{name}/drain and /uncordon (docs/robustness.md):
        # callbacks into the NodeDrainController — name -> wire row, or
        # None for an unknown node. Unset → 404 (no drain controller).
        self.drain_handler: Optional[Callable[[str], Optional[dict]]] = None
        self.uncordon_handler: Optional[Callable[[str], Optional[dict]]] = None
        # admission explain engine (observability/explain.py,
        # docs/observability.md "Admission explain"): GET
        # /gangs/{ns}/{name}/explain, GET /debug/capacity, POST
        # /debug/whatif, and the /debug/journeys pending annotation all
        # serve from it. Unset → 404 (no scheduler attached). Read-only
        # by contract (grovelint GL016), so handlers run WITHOUT
        # server.lock — an explain burst must never stall writes.
        self.explain_engine = None
        # federation tier (federation/router.py, docs/federation.md):
        # GET /federation serves the router's status() document — region
        # registry, placement counts, spillover/re-route counters, the
        # decision-ledger length, and the global quota fold. Arrives by
        # callback like node_provider (the router is sim infrastructure,
        # not a store object). Unset → 404 (no federation tier).
        self.federation_provider: Optional[Callable[[], dict]] = None
        # config-gated like the reference pprof listener (manager.go:108-113)
        # and serialized: concurrent samplers would degrade the whole
        # control plane (every 100Hz stack walk contends on the GIL)
        self.enable_profiling = enable_profiling
        self._profile_lock = threading.Lock()
        self.webhooks = webhooks or []
        self._subs: List[_WatchSub] = []
        self._subs_lock = threading.Lock()
        self.store.subscribe(self._fanout)
        self._httpd = ThreadingHTTPServer((host, port), self._make_handler())
        self._httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "APIServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="grove-apiserver", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        with self._subs_lock:
            for sub in self._subs:
                sub.q.put(None)
            self._subs.clear()

    # -- watch fanout ----------------------------------------------------

    def _fanout(self, ev: WatchEvent) -> None:
        from grove_tpu.runtime.store import matches_labels

        with self._subs_lock:
            subs = list(self._subs)
        for sub in subs:
            if sub.kind != ev.kind:
                continue
            if (
                sub.namespace is not None
                and ev.obj.metadata.namespace != sub.namespace
            ):
                continue
            if not matches_labels(ev.obj, sub.selector):
                continue
            sub.q.put(ev)

    # -- admission -------------------------------------------------------

    def _call_webhook(
        self, reg: WebhookRegistration, review: dict
    ) -> dict:
        data = json.dumps(review).encode()
        req = urllib.request.Request(
            reg.url,
            data=data,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        import ssl

        ctx = None
        if reg.url.startswith("https"):
            ctx = ssl.create_default_context(cafile=reg.ca_file)
        with urllib.request.urlopen(req, timeout=10, context=ctx) as resp:
            return json.loads(resp.read())

    def _admit(
        self,
        doc: dict,
        operation: str,
        username: str,
        old_doc: Optional[dict] = None,
    ) -> dict:
        """Run the webhook chain: mutating first, then validating — the
        order register.go implies (defaulting webhook path precedes
        validation)."""
        kind = doc.get("kind", "")
        for reg in self.webhooks:
            if kind not in reg.kinds or operation not in reg.operations:
                continue
            review = {
                "apiVersion": "admission.k8s.io/v1",
                "kind": "AdmissionReview",
                "request": {
                    "operation": operation,
                    "userInfo": {"username": username},
                    "object": doc,
                    "oldObject": old_doc,
                },
            }
            out = self._call_webhook(reg, review).get("response", {})
            if not out.get("allowed", False):
                raise AdmissionDenied(
                    out.get("status", {}).get("message", "admission denied")
                )
            if reg.mutating and out.get("patchedObject") is not None:
                doc = out["patchedObject"]
        return doc

    # -- handler ---------------------------------------------------------

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            # ---- helpers

            def _send_json(self, code: int, payload) -> None:
                # payload: any JSON document (object or array)
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _error(self, code: int, message: str, reason: str = "") -> None:
                self._send_json(
                    code,
                    {
                        "kind": "Status",
                        "status": "Failure",
                        "code": code,
                        "reason": reason,
                        "message": message,
                    },
                )

            def _query_float(self, name: str, default: float):
                """One finite POSITIVE float query parameter, or None
                when the raw value is unparseable, non-finite, or not
                positive (callers 400) — every current caller is a
                window length, where 0 is meaningless."""
                query = urllib.parse.parse_qs(
                    urllib.parse.urlsplit(self.path).query
                )
                raw = (query.get(name) or [None])[0]
                if raw is None:
                    return default
                try:
                    value = float(raw)
                except ValueError:
                    return None
                if not math.isfinite(value) or value <= 0:
                    return None
                return value

            def _body(self) -> dict:
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length) if length else b"{}"
                return json.loads(raw or b"{}")

            def _route(self):
                """Parse path → (info, namespace, name, subresource, query)."""
                parsed = urllib.parse.urlsplit(self.path)
                query = urllib.parse.parse_qs(parsed.query)
                parts = [
                    urllib.parse.unquote(p)
                    for p in parsed.path.split("/")
                    if p
                ]
                # /api/v1/... (core) or /apis/{group}/{version}/...
                if not parts:
                    return None
                if parts[0] == "api" and len(parts) >= 2:
                    group, version, rest = "", parts[1], parts[2:]
                elif parts[0] == "apis" and len(parts) >= 3:
                    group, version, rest = parts[1], parts[2], parts[3:]
                else:
                    return None
                namespace: Optional[str] = None
                if len(rest) >= 2 and rest[0] == "namespaces":
                    namespace, rest = rest[1], rest[2:]
                if not rest:
                    return None
                info = resolve_path_kind(group, version, rest[0])
                if info is None:
                    return None
                name = rest[1] if len(rest) >= 2 else None
                sub = rest[2] if len(rest) >= 3 else None
                if info.namespaced and namespace is None and name is not None:
                    # namespaced kind addressed without a namespace
                    return None
                if not info.namespaced:
                    namespace = ""
                return info, namespace, name, sub, query

            def _username(self) -> str:
                from grove_tpu.admission.authorization import OPERATOR_USERNAME

                return self.headers.get("Impersonate-User") or OPERATOR_USERNAME

            # ---- verbs

            def do_GET(self):
                path = urllib.parse.urlsplit(self.path).path
                if path in ("/healthz", "/readyz", "/livez"):
                    body = b"ok"
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/metrics":
                    body = METRICS.prometheus_text().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/debug/traces":
                    # span summary: per-span-name count/total/p50/p99
                    # (observability/tracing.py; tracer is process-global —
                    # populated when the operator runs in this process)
                    from grove_tpu.observability.tracing import TRACER

                    return self._send_json(200, TRACER.summary_json())
                if path == "/debug/traces/chrome":
                    # Chrome trace_event array: load in chrome://tracing or
                    # Perfetto (docs/observability.md)
                    from grove_tpu.observability.tracing import TRACER

                    return self._send_json(200, TRACER.chrome_trace())
                if path == "/queues":
                    # quota subsystem summary (docs/quota.md): per-queue
                    # deserved/ceiling/usage/dominant-share + gang counts,
                    # full-scan authoritative (includes implicit queues)
                    from grove_tpu.quota.manager import quota_snapshot

                    with server.lock:
                        items = quota_snapshot(server.store)
                    return self._send_json(
                        200, {"kind": "QueueSummaryList", "items": items}
                    )
                if path == "/nodes":
                    # node health table (docs/robustness.md): name, state
                    # (Ready/NotReady/Lost), cordon flag, drain state,
                    # heartbeat age, capacity, labels, bound-pod count.
                    # NOT under server.lock: in real-cluster mode the
                    # provider's store is an HttpStore pointed back at THIS
                    # server (drain states live in NodeDrain objects), and
                    # the nested request would deadlock on the held lock.
                    # The provider serves from point-in-time copies.
                    items = (
                        server.node_provider()
                        if server.node_provider is not None
                        else []
                    )
                    return self._send_json(
                        200, {"kind": "NodeList", "items": items}
                    )
                if path == "/events":
                    # deduped k8s-style Events (count/first/lastTimestamp),
                    # filterable: ?namespace=...&reason=...&kind=...
                    from grove_tpu.observability.events import EVENTS

                    query = urllib.parse.parse_qs(
                        urllib.parse.urlsplit(self.path).query
                    )

                    def qp(name):
                        return (query.get(name) or [None])[0]

                    items = EVENTS.list(
                        namespace=qp("namespace"),
                        reason=qp("reason"),
                        kind=qp("kind"),
                    )
                    return self._send_json(
                        200,
                        {
                            "kind": "EventList",
                            "items": [e.as_dict() for e in items],
                        },
                    )
                if path == "/debug/profile":
                    query = urllib.parse.parse_qs(
                        urllib.parse.urlsplit(self.path).query
                    )
                    if "seconds" not in query:
                        # wall-attribution report (observability/profile.py,
                        # docs/observability.md): the per-(controller,
                        # shard, phase) self-time ledger — process-global,
                        # populated when the operator runs in this process
                        # with GROVE_TPU_PROFILE=1
                        from grove_tpu.observability.profile import PROFILER

                        return self._send_json(
                            200,
                            dict(
                                {"kind": "ProfileReport"}, **PROFILER.report()
                            ),
                        )
                    # ?seconds=N — pprof-server equivalent: sample every
                    # thread's stack and return aggregated frame counts
                    # (whole-process view, py-spy style — cProfile would
                    # only see this handler thread)
                    if not server.enable_profiling:
                        return self._error(
                            404,
                            "profiling disabled (server.profilingEnabled)",
                        )
                    try:
                        seconds = min(
                            float((query.get("seconds") or ["2"])[0]), 30.0
                        )
                    except ValueError:
                        return self._error(400, "seconds must be a number")
                    if not server._profile_lock.acquire(blocking=False):
                        return self._error(
                            429, "a profile is already in progress"
                        )
                    try:
                        body = _sample_profile(seconds).encode()
                    finally:
                        server._profile_lock.release()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/debug/capacity":
                    # capacity & fragmentation introspection
                    # (docs/observability.md "Admission explain"):
                    # per-topology-level domain free vectors + the
                    # max-contiguous-slab fragmentation statistic
                    if server.explain_engine is None:
                        return self._error(
                            404,
                            "no explain engine attached to this server"
                            " (scheduler not running in this process)",
                        )
                    return self._send_json(
                        200, server.explain_engine.capacity()
                    )
                if path.startswith("/gangs/") and path.endswith("/explain"):
                    # GET /gangs/{ns}/{name}/explain — the admission
                    # explain verdict: constraint-elimination funnel,
                    # fits_now, blocking stages, binding constraint
                    parts = path.split("/")
                    if len(parts) != 5 or not parts[2] or not parts[3]:
                        return self._error(
                            404, "expected /gangs/{namespace}/{name}/explain"
                        )
                    if server.explain_engine is None:
                        return self._error(
                            404,
                            "no explain engine attached to this server"
                            " (scheduler not running in this process)",
                        )
                    doc = server.explain_engine.explain(parts[2], parts[3])
                    if doc is None:
                        return self._error(
                            404,
                            f"PodGang {parts[2]}/{parts[3]} not found",
                            "NotFound",
                        )
                    return self._send_json(200, doc)
                if path.startswith("/gangs/") and path.endswith("/journey"):
                    # GET /gangs/{ns}/{name}/journey — one PodGang's causal
                    # admission record (observability/journey.py): ordered
                    # phase marks, frontier partition, and the queue-wait/
                    # encode/solve/commit/status decomposition
                    parts = path.split("/")
                    if len(parts) != 5 or not parts[2] or not parts[3]:
                        return self._error(
                            404, "expected /gangs/{namespace}/{name}/journey"
                        )
                    from grove_tpu.observability.journey import JOURNEYS

                    doc = JOURNEYS.journey(parts[2], parts[3])
                    if doc is None:
                        return self._error(
                            404,
                            f"no journey recorded for PodGang"
                            f" {parts[2]}/{parts[3]} (journey tracing"
                            " enabled? GROVE_TPU_JOURNEY=1)",
                            "NotFound",
                        )
                    return self._send_json(
                        200, dict({"kind": "GangJourney"}, **doc)
                    )
                if path == "/debug/journeys":
                    # fleet view: admission-latency decomposition + the
                    # critical-path fold over completed journeys, PLUS
                    # the pending gangs (age, current stage, last explain
                    # verdict when one ran) — stuck gangs are visible
                    # here instead of silently absent (journey gap fix) —
                    # plus the per-window admission summary read through
                    # the SLO observatory's time-series engine (?window=N
                    # seconds; the SLO layer cites the SAME numbers)
                    from grove_tpu.observability.journey import JOURNEYS

                    window_s = self._query_float("window", 300.0)
                    if window_s is None:
                        return self._error(
                            400, "window must be a positive finite number"
                        )
                    pending = (
                        server.explain_engine.pending_journeys()
                        if server.explain_engine is not None
                        else JOURNEYS.pending()
                    )
                    return self._send_json(
                        200,
                        {
                            "kind": "JourneySummary",
                            "enabled": JOURNEYS.enabled,
                            "decomposition": JOURNEYS.decomposition(),
                            "critical_path": JOURNEYS.critical_path(),
                            "window": JOURNEYS.window_summary(window_s),
                            "pending": pending,
                        },
                    )
                if path == "/federation":
                    # federation tier (docs/federation.md): the router's
                    # registry + ledger roll-up — per-region state/
                    # placements/pending, spillovers, re-routes, global
                    # quota fold
                    if server.federation_provider is None:
                        return self._error(
                            404,
                            "no federation router attached to this"
                            " server (single-cluster deployment)",
                        )
                    return self._send_json(
                        200, server.federation_provider()
                    )
                if path == "/debug/slo":
                    # SLO observatory (docs/observability.md "SLO
                    # observatory"): per-objective attainment, error
                    # budget, burn rates, breach state — plus every live
                    # time series reduced over one window (?window=N)
                    from grove_tpu.observability.slo import SLO

                    window_s = self._query_float("window", 300.0)
                    if window_s is None:
                        return self._error(
                            400, "window must be a positive finite number"
                        )
                    return self._send_json(
                        200,
                        dict(
                            {"kind": "SloReport"},
                            **SLO.status(series_window=window_s),
                        ),
                    )
                if path == "/debug/forecast":
                    # diurnal+trend forecaster (docs/observability.md
                    # "Remediation & ledger"): per-series horizon
                    # predictions with confidence bands + skill vs the
                    # persistence baseline (?series=a&series=b&horizon=N;
                    # defaults to the watched set) — read-only: the skill
                    # ring is fed by the remediator's scoring calls,
                    # never by this surface
                    from grove_tpu.observability.forecast import FORECASTER

                    horizon_s = self._query_float("horizon", 0.0)
                    if horizon_s is None:
                        return self._error(
                            400, "horizon must be a positive finite number"
                        )
                    fc_query = urllib.parse.parse_qs(
                        urllib.parse.urlsplit(self.path).query
                    )
                    names = [
                        s for s in fc_query.get("series", []) if s
                    ] or None
                    return self._send_json(
                        200,
                        dict(
                            {"kind": "ForecastReport"},
                            **FORECASTER.report(
                                names=names,
                                horizon=horizon_s or None,
                            ),
                        ),
                    )
                if path == "/debug/ledger":
                    # causal decision→effect ledger (docs/observability.md
                    # "Remediation & ledger"): the bounded ring of
                    # trigger→diagnosis→simulation→action→effect chains
                    # plus per-kind/per-outcome tallies
                    from grove_tpu.observability.ledger import LEDGER

                    return self._send_json(
                        200,
                        dict({"kind": "LedgerReport"}, **LEDGER.status()),
                    )
                route = self._route()
                if route is None:
                    return self._error(404, f"unknown path {self.path}")
                info, namespace, name, _sub, query = route
                try:
                    selector = parse_label_selector(
                        (query.get("labelSelector") or [None])[0]
                    )
                except ValueError as e:
                    return self._error(400, str(e))
                if name is not None:
                    with server.lock:
                        obj = server.store.get(info.kind, namespace or "", name)
                    if obj is None:
                        return self._error(
                            404, f"{info.kind} {namespace}/{name} not found",
                            "NotFound",
                        )
                    return self._send_json(200, export_object(obj))
                if (query.get("watch") or ["false"])[0] == "true":
                    return self._watch(info, namespace, selector)
                with server.lock:
                    objs = server.store.list(info.kind, namespace or None, selector)
                return self._send_json(
                    200,
                    {
                        "apiVersion": info.api_version,
                        "kind": f"{info.kind}List",
                        "items": [export_object(o) for o in objs],
                    },
                )

            def _watch(self, info: KindInfo, namespace, selector):
                sub = _WatchSub(
                    q=queue.Queue(), kind=info.kind,
                    namespace=namespace or None, selector=selector,
                )
                # list+watch without a gap: snapshot synthetic ADDED events
                # and register the live subscription under the store lock
                with server.lock:
                    existing = server.store.list(
                        info.kind, namespace or None, selector
                    )
                    with server._subs_lock:
                        server._subs.append(sub)
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def write_chunk(payload: dict) -> None:
                    line = (json.dumps(payload) + "\n").encode()
                    self.wfile.write(f"{len(line):x}\r\n".encode())
                    self.wfile.write(line + b"\r\n")
                    self.wfile.flush()

                try:
                    for obj in existing:
                        write_chunk(
                            {"type": "ADDED", "object": export_object(obj)}
                        )
                    while True:
                        ev = sub.q.get()
                        if ev is None:
                            break
                        write_chunk(
                            {
                                "type": ev.type.upper(),
                                "object": export_object(ev.obj),
                            }
                        )
                except (BrokenPipeError, ConnectionResetError):
                    pass
                finally:
                    with server._subs_lock:
                        if sub in server._subs:
                            server._subs.remove(sub)

            def do_POST(self):
                if urllib.parse.urlsplit(self.path).path == "/debug/whatif":
                    # hypothetical trial solves (docs/observability.md
                    # "Admission explain"): before/after verdicts for a
                    # gang under drain/remove/add-node or queue rewrites
                    # — commits NOTHING (read-only by GL016 contract)
                    if server.explain_engine is None:
                        return self._error(
                            404,
                            "no explain engine attached to this server"
                            " (scheduler not running in this process)",
                        )
                    try:
                        body = self._body()
                    except ValueError:
                        return self._error(400, "invalid JSON body")
                    try:
                        doc = server.explain_engine.whatif(body)
                    except ValueError as e:
                        return self._error(400, str(e))
                    return self._send_json(200, doc)
                # node lifecycle actions (docs/robustness.md drain flow):
                # POST /nodes/{name}/drain | /nodes/{name}/uncordon
                parts = [
                    urllib.parse.unquote(p)
                    for p in urllib.parse.urlsplit(self.path).path.split("/")
                    if p
                ]
                if len(parts) == 3 and parts[0] == "nodes" and parts[2] in (
                    "drain",
                    "uncordon",
                ):
                    handler = (
                        server.drain_handler
                        if parts[2] == "drain"
                        else server.uncordon_handler
                    )
                    if handler is None:
                        return self._error(
                            404, "no drain controller attached to this server"
                        )
                    # node lifecycle actions are operator-tier: with the
                    # authorizer enabled, only the operator identity or an
                    # exempt service account may evict workloads this way
                    # (the same principals the store guard trusts) — an
                    # anonymous client must not drain a node it could not
                    # delete a managed pod from
                    guard = server.store.guard
                    if guard is not None and guard.enabled:
                        username = self._username()
                        if (
                            username != guard.operator_username
                            and username not in guard.exempt
                        ):
                            return self._error(
                                403,
                                f"{parts[2]} of node {parts[1]!r} is denied"
                                f" for user {username!r}: node lifecycle"
                                " actions require the operator identity or"
                                " an exempt service account",
                                "Forbidden",
                            )
                    # not under server.lock — same nested-self-call rule as
                    # GET /nodes: the controller persists the NodeDrain
                    # intent through its own store, which in real-cluster
                    # mode is an HttpStore calling back into this server
                    row = handler(parts[1])
                    if row is None:
                        return self._error(
                            404, f"node {parts[1]!r} not found", "NotFound"
                        )
                    return self._send_json(200, row)
                route = self._route()
                if route is None:
                    return self._error(404, f"unknown path {self.path}")
                info, namespace, _name, _sub, _query = route
                doc = self._body()
                if doc.get("kind") != info.kind:
                    return self._error(
                        400,
                        f"body kind {doc.get('kind')!r} does not match path "
                        f"kind {info.kind!r}",
                    )
                username = self._username()
                try:
                    doc = server._admit(doc, "CREATE", username)
                    obj = decode_object(doc)
                    if info.namespaced:
                        obj.metadata.namespace = namespace or "default"
                    with server.lock, server.store.as_user(username):
                        stored = server.store.create(obj)
                except AdmissionDenied as e:
                    return self._error(422, e.message, "Invalid")
                except GroveError as e:
                    return self._error(_http_status_for(e), str(e))
                return self._send_json(201, export_object(stored))

            def do_PUT(self):
                route = self._route()
                if route is None:
                    return self._error(404, f"unknown path {self.path}")
                info, namespace, name, sub, _query = route
                if name is None:
                    return self._error(405, "PUT requires a resource name")
                doc = self._body()
                username = self._username()
                try:
                    if sub == "status":
                        with server.lock, server.store.as_user(username):
                            current = server.store.get(
                                info.kind, namespace or "", name
                            )
                            if current is None:
                                return self._error(
                                    404, f"{info.kind} {name} not found",
                                    "NotFound",
                                )
                            incoming = decode_object(doc)
                            current.status = incoming.status
                            # status writes respect optimistic concurrency
                            current.metadata.resource_version = (
                                incoming.metadata.resource_version
                            )
                            stored = server.store.update_status(current)
                        return self._send_json(200, export_object(stored))
                    with server.lock:
                        current = server.store.get(info.kind, namespace or "", name)
                    old_doc = export_object(current) if current is not None else None
                    doc = server._admit(doc, "UPDATE", username, old_doc)
                    obj = decode_object(doc)
                    with server.lock, server.store.as_user(username):
                        # spec-endpoint writes never touch status
                        # (subresource semantics). Re-read UNDER the write
                        # lock: the admission round trip above runs unlocked,
                        # and restoring a pre-webhook snapshot would revert
                        # any status a controller wrote in that window.
                        fresh = server.store.get(
                            info.kind, namespace or "", name
                        )
                        if fresh is not None and hasattr(fresh, "status"):
                            obj.status = fresh.status
                        stored = server.store.update(obj)
                        # apiserver rule: removing the last finalizer of a
                        # deleting object completes the deletion
                        server.store.complete_deletion_if_drained(
                            info.kind, stored.metadata.namespace,
                            stored.metadata.name,
                        )
                except AdmissionDenied as e:
                    return self._error(422, e.message, "Invalid")
                except GroveError as e:
                    return self._error(_http_status_for(e), str(e))
                return self._send_json(200, export_object(stored))

            def do_DELETE(self):
                route = self._route()
                if route is None:
                    return self._error(404, f"unknown path {self.path}")
                info, namespace, name, _sub, query = route
                username = self._username()
                try:
                    if name is None:
                        selector = parse_label_selector(
                            (query.get("labelSelector") or [None])[0]
                        )
                        with server.lock, server.store.as_user(username):
                            n = server.store.delete_collection(
                                info.kind, namespace or "", selector
                            )
                        return self._send_json(200, {"deleted": n})
                    with server.lock:
                        current = server.store.get(info.kind, namespace or "", name)
                    if current is not None:
                        server._admit(
                            export_object(current), "DELETE", username
                        )
                    with server.lock, server.store.as_user(username):
                        server.store.delete(info.kind, namespace or "", name)
                except AdmissionDenied as e:
                    return self._error(403, e.message, "Forbidden")
                except GroveError as e:
                    return self._error(_http_status_for(e), str(e))
                return self._send_json(200, {"status": "Success"})

        return Handler
