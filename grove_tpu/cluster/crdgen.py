"""CRD manifest generation from the typed API model.

The counterpart of the reference's embedded CRD YAML
(/root/reference/operator/api/core/v1alpha1/crds/,
/root/reference/scheduler/api/core/v1alpha1/crds/): structural
openAPIV3Schema derived reflectively from the dataclasses, so the manifests
can never drift from the Go^H^Hpython types (the reference enforces the same
with `make check` codegen drift detection, SURVEY §4.4).

`python -m grove_tpu.cli crds` prints or writes them; deploy/crds/ holds the
committed copies (drift-tested in tests/test_cluster_mode.py).
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict, List, Optional

import yaml

from grove_tpu.api.wire import KIND_REGISTRY, KindInfo

# kinds that ship as CRDs (core kinds like Pod are built-in, not CRDs)
CRD_KINDS = (
    "PodCliqueSet",
    "PodClique",
    "PodCliqueScalingGroup",
    "ClusterTopology",
    "PodGang",
    "Queue",
)


def _camel(name: str) -> str:
    head, *rest = name.split("_")
    return head + "".join(w.capitalize() for w in rest)


def _schema_for(hint: Any, depth: int = 0) -> Dict[str, Any]:
    if depth > 12:  # defensive: no recursive types in the model
        return {"x-kubernetes-preserve-unknown-fields": True}
    origin = typing.get_origin(hint)
    if origin is typing.Union:
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        return _schema_for(args[0], depth) if args else {}
    if origin in (list, typing.List):
        (item,) = typing.get_args(hint) or (Any,)
        return {"type": "array", "items": _schema_for(item, depth + 1)}
    if origin in (dict, typing.Dict):
        args = typing.get_args(hint)
        val = args[1] if len(args) == 2 else Any
        if val is Any:
            return {"type": "object", "x-kubernetes-preserve-unknown-fields": True}
        return {
            "type": "object",
            "additionalProperties": _schema_for(val, depth + 1),
        }
    if dataclasses.is_dataclass(hint):
        hints = typing.get_type_hints(hint)
        props = {}
        for f in dataclasses.fields(hint):
            if f.name == "kind":
                continue
            props[_camel(f.name)] = _schema_for(hints[f.name], depth + 1)
        return {"type": "object", "properties": props}
    if hint is bool:
        return {"type": "boolean"}
    if hint is int:
        return {"type": "integer"}
    if hint is float:
        # quantities/durations arrive as strings in user manifests
        return {"x-kubernetes-int-or-string": True}
    if hint is str:
        return {"type": "string"}
    return {"x-kubernetes-preserve-unknown-fields": True}


def generate_crd(kind: str) -> Dict[str, Any]:
    info: KindInfo = KIND_REGISTRY[kind]
    hints = typing.get_type_hints(info.cls)
    spec_schema = (
        _schema_for(hints["spec"]) if "spec" in hints else {"type": "object"}
    )
    status_schema = (
        _schema_for(hints["status"])
        if "status" in hints
        else {"type": "object", "x-kubernetes-preserve-unknown-fields": True}
    )
    versions = [
        {
            "name": info.version,
            "served": True,
            "storage": True,
            "subresources": {"status": {}},
            "schema": {
                "openAPIV3Schema": {
                    "type": "object",
                    "properties": {
                        "apiVersion": {"type": "string"},
                        "kind": {"type": "string"},
                        "metadata": {"type": "object"},
                        "spec": spec_schema,
                        "status": status_schema,
                    },
                }
            },
        }
    ]
    singular = kind.lower()
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{info.plural}.{info.group}"},
        "spec": {
            "group": info.group,
            "names": {
                "kind": kind,
                "listKind": f"{kind}List",
                "plural": info.plural,
                "singular": singular,
            },
            "scope": "Namespaced" if info.namespaced else "Cluster",
            "versions": versions,
        },
    }


def render_crds(kinds=CRD_KINDS) -> str:
    docs = [generate_crd(k) for k in kinds]
    return "\n---\n".join(
        yaml.safe_dump(d, sort_keys=False, default_flow_style=False)
        for d in docs
    )


def write_crds(directory: str, kinds=CRD_KINDS) -> List[str]:
    import pathlib

    out = []
    d = pathlib.Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    for kind in kinds:
        crd = generate_crd(kind)
        path = d / f"{crd['metadata']['name']}.yaml"
        path.write_text(yaml.safe_dump(crd, sort_keys=False, default_flow_style=False))
        out.append(str(path))
    return out
