"""Automatic topology detection: node labels → ClusterTopology.

Ships the reference roadmap's unshipped "Automatic Topology Detection"
(README.md 2026 priorities): instead of an admin hand-writing the
ClusterTopology CR, the level hierarchy is INFERRED from the node labels
already on the cluster — which label keys partition the nodes into a
containment hierarchy, and in which broad→narrow order.

Method (pure host-side set math, no solver involvement):

1. Candidate keys = labels present on every node (a topology key must
   cover the fleet).
2. Keys with identical partitions are deduplicated (prefer well-known
   topology keys), and constant labels (one value fleet-wide, e.g.
   `kubernetes.io/os`) are dropped unless well-known — they carry no
   placement information.
3. Candidates are ordered by domain count and greedily chained under the
   REFINEMENT relation: key B refines key A iff every B-domain lies inside
   exactly one A-domain. Cross-cutting labels (`app`, team tags…) refine
   nothing and fall out; what survives is the maximal containment chain —
   the topology.
4. Each chain level is assigned a domain name: well-known keys pin their
   canonical domain (`kubernetes.io/hostname` → host, GKE TPU labels →
   slice/ici-block, …); unknown keys take the next free slot in the
   broad→narrow domain vocabulary (api/topology.py TOPOLOGY_DOMAIN_ORDER),
   so the result always passes validate_cluster_topology.

`grove-tpu detect-topology` prints the CR; `grove-tpu run
--auto-detect-topology` boots the operator on the inferred hierarchy.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Sequence, Tuple

from grove_tpu.api.meta import ObjectMeta
from grove_tpu.api.topology import (
    TOPOLOGY_DOMAIN_ORDER,
    ClusterTopology,
    ClusterTopologySpec,
    TopologyLevel,
)

# canonical key → domain anchors (reference vocabulary + TPU aliases + the
# standard k8s topology keys)
KNOWN_KEY_DOMAINS: Dict[str, str] = {
    "topology.kubernetes.io/region": "region",
    "topology.kubernetes.io/zone": "zone",
    "cloud.google.com/gke-cluster": "cluster",
    "cloud.google.com/gke-tpu-slice": "slice",
    "cloud.google.com/gke-tpu-ici-block": "ici-block",
    "kubernetes.io/hostname": "host",
}

# one representative domain per order slot, broad → narrow, for keys with no
# canonical anchor
_SLOT_DOMAINS = ("region", "zone", "cluster", "slice", "ici-block", "host", "chip")


class TopologyDetectionError(ValueError):
    """The node labels do not form a usable containment hierarchy."""


def _partitions(
    nodes: Sequence[Tuple[str, Mapping[str, str]]]
) -> Dict[str, Tuple[str, ...]]:
    """key → per-node value tuple (node order fixed), for keys on ALL nodes."""
    if not nodes:
        raise TopologyDetectionError("no nodes to detect a topology from")
    common = set(nodes[0][1])
    for _, labels in nodes[1:]:
        common &= set(labels)
    return {k: tuple(labels[k] for _, labels in nodes) for k in sorted(common)}


def _refines(fine: Tuple[str, ...], coarse: Tuple[str, ...]) -> bool:
    """Every fine-domain lies inside exactly one coarse-domain."""
    seen: Dict[str, str] = {}
    for f, c in zip(fine, coarse):
        prev = seen.setdefault(f, c)
        if prev != c:
            return False
    return True


def detect_topology_levels(
    nodes: Sequence[Tuple[str, Mapping[str, str]]]
) -> List[str]:
    """The maximal containment chain of label keys, broadest first."""
    parts = _partitions(nodes)

    # dedup identical partitions (known keys win, then lexicographic order);
    # the signature is the partition STRUCTURE (dense first-occurrence ids),
    # not the raw values — `zone-a` everywhere and `cluster-0` everywhere
    # are the same (trivial) partition
    def sig(values: Tuple[str, ...]) -> Tuple[int, ...]:
        ids: Dict[str, int] = {}
        return tuple(ids.setdefault(v, len(ids)) for v in values)

    by_sig: Dict[Tuple[int, ...], str] = {}
    for key in sorted(parts, key=lambda k: (k not in KNOWN_KEY_DOMAINS, k)):
        by_sig.setdefault(sig(parts[key]), key)
    candidates = sorted(
        by_sig.values(),
        key=lambda k: (len(set(parts[k])), k not in KNOWN_KEY_DOMAINS, k),
    )
    # constant labels carry no placement signal unless canonical
    candidates = [
        k
        for k in candidates
        if len(set(parts[k])) > 1 or k in KNOWN_KEY_DOMAINS
    ]

    chain: List[str] = []
    for key in candidates:
        if all(_refines(parts[key], parts[kept]) for kept in chain):
            chain.append(key)
    if not chain:
        raise TopologyDetectionError(
            "no label key forms a containment hierarchy across all nodes"
        )
    return chain


def detect_topology(
    nodes: Sequence, name: str = "default"
) -> ClusterTopology:
    """Infer a ClusterTopology from node objects (anything with `.name` and
    `.labels`, or (name, labels) pairs)."""
    pairs = [
        (n[0], n[1]) if isinstance(n, tuple) else (n.name, n.labels)
        for n in nodes
    ]
    chain = detect_topology_levels(pairs)
    if len(chain) > 7:
        # keep the narrowest levels (placement-relevant); name the dropped
        # broad keys so a packDomain/spreadDomain referencing one of them
        # fails validation with a visible cause rather than silently
        dropped = chain[:-7]
        import warnings

        warnings.warn(
            "topology detection found more than 7 containment levels;"
            f" dropping broadest label keys: {', '.join(dropped)}",
            stacklevel=2,
        )
        chain = chain[-7:]

    # assign domain names: known keys pin their slot; unknown keys take the
    # next free slot that keeps the broad→narrow order strict
    levels: List[TopologyLevel] = []
    next_order = 0
    unpinned: List[str] = []

    def flush_unpinned(limit: int) -> None:
        nonlocal next_order
        for key in unpinned:
            if next_order >= limit:
                raise TopologyDetectionError(
                    f"cannot fit detected level {key!r} into the domain"
                    " vocabulary order"
                )
            levels.append(TopologyLevel(domain=_SLOT_DOMAINS[next_order], key=key))
            next_order += 1
        unpinned.clear()

    for key in chain:
        domain = KNOWN_KEY_DOMAINS.get(key)
        if domain is None:
            unpinned.append(key)
            continue
        order = TOPOLOGY_DOMAIN_ORDER[domain]
        if order < next_order + len(unpinned):
            raise TopologyDetectionError(
                f"detected order of {key!r} conflicts with the canonical"
                f" domain vocabulary (needs slot >= {next_order + len(unpinned)},"
                f" canonical is {order})"
            )
        flush_unpinned(order)
        levels.append(TopologyLevel(domain=domain, key=key))
        next_order = order + 1
    flush_unpinned(len(_SLOT_DOMAINS))

    return ClusterTopology(
        metadata=ObjectMeta(name=name, namespace=""),
        spec=ClusterTopologySpec(levels=levels),
    )


def load_nodes_file(path: str) -> List[Tuple[str, Dict[str, str]]]:
    """Node (name, labels) pairs from YAML: accepts a k8s NodeList, a list
    of Node manifests, or a bare [{name, labels}] list."""
    import yaml

    with open(path) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    items: List[dict] = []
    for doc in docs:
        if isinstance(doc, dict) and doc.get("kind") == "NodeList":
            items.extend(doc.get("items") or [])
        elif isinstance(doc, list):
            items.extend(doc)
        else:
            items.append(doc)
    out: List[Tuple[str, Dict[str, str]]] = []
    for item in items:
        if not isinstance(item, dict):
            raise TopologyDetectionError(
                f"{path}: node entries must be mappings with name/labels"
                f" (got {type(item).__name__}: {item!r})"
            )
        meta = item.get("metadata") or {}
        name = item.get("name") or meta.get("name") or f"node-{len(out)}"
        labels = item.get("labels") or meta.get("labels") or {}
        out.append((name, dict(labels)))
    return out
