"""gRPC gang-solver sidecar: the BASELINE north-star boundary.

The reference delegates placement to the external KAI scheduler; the
north star (BASELINE.json) puts the all-or-nothing packing behind a gRPC
sidecar the scheduler plugin calls. This module is that sidecar:
``GangSolver.Solve`` takes the full pending-gang batch + cluster snapshot
(protos/solver.proto) and returns per-gang placements + PlacementScores,
solved by the device-resident wave kernel.

grpcio-tools is not available in this image, so the message classes are
protoc-generated and committed (protos/solver_pb2.py) while the
service/stub layer is written against grpc-python's generic handler API —
wire-compatible with any standard gRPC client/server of this proto.
"""

from __future__ import annotations

from concurrent import futures
from typing import List, Optional

import numpy as np

try:  # grpcio ships in the dev image; declared as the [grpc] extra in
    # pyproject — fail with an actionable message, not a bare ImportError
    import grpc
except ImportError as _exc:  # pragma: no cover
    grpc = None
    _GRPC_IMPORT_ERROR = _exc
else:
    _GRPC_IMPORT_ERROR = None

from grove_tpu.cluster.protos import health_pb2
from grove_tpu.cluster.protos import solver_pb2 as pb

_SERVICE = "grove.solver.v1.GangSolver"
_HEALTH_SERVICE = "grpc.health.v1.Health"

# explicit wire-size ceiling (both directions, server and client): a 10k-gang
# × 5k-node stress request with allocations is ~tens of MB; grpc's 4 MB
# default receive limit would reject it, and UNbounded would let one rogue
# request exhaust the sidecar
MAX_MESSAGE_BYTES = 256 * 1024 * 1024
# request-complexity guard: the dense alloc tensor is gangs × max-groups ×
# nodes int32s; past this cell count (~1 GB for the one array, before the
# kernel's working set) reject as RESOURCE_EXHAUSTED rather than OOM-killing
# the sidecar mid-solve. The BASELINE stress shape (10k gangs × ~4 groups ×
# 5k nodes = 2.0e8) fits under it.
MAX_DENSE_CELLS = 250_000_000


def _require_grpc() -> None:
    if grpc is None:  # pragma: no cover
        raise RuntimeError(
            "the gang-solver sidecar needs grpcio (pip install"
            " 'grove-tpu[grpc]')"
        ) from _GRPC_IMPORT_ERROR


def _topology_from_keys(level_keys: List[str]):
    from grove_tpu.api.topology import (
        ClusterTopology,
        ClusterTopologySpec,
        TopologyLevel,
    )

    if not level_keys:
        return ClusterTopology()
    return ClusterTopology(
        spec=ClusterTopologySpec(
            levels=[
                TopologyLevel(domain=f"level-{i}", key=key)
                for i, key in enumerate(level_keys)
            ]
        )
    )


def _decode_request(request: pb.SolveRequest):
    from grove_tpu.sim.cluster import Node

    nodes = [
        Node(
            name=n.name,
            capacity={q.resource: q.value for q in n.capacity},
            labels=dict(n.labels),
        )
        for n in request.nodes
    ]
    gang_specs = []
    for gang in request.gangs:
        gang_specs.append(
            {
                "name": gang.name,
                "groups": [
                    {
                        "name": grp.name,
                        "demand": {q.resource: q.value for q in grp.demand},
                        "count": grp.count,
                        "min_count": grp.min_count,
                        "required_key": grp.pack_level_key or None,
                        "pinned_node": grp.pinned_node or None,
                    }
                    for grp in gang.groups
                ],
                "required_key": gang.required_level_key or None,
                "preferred_key": gang.preferred_level_key or None,
                "spread_key": gang.spread_level_key or None,
                "spread_min_domains": gang.spread_min_domains or 2,
                "spread_required": gang.spread_required,
                "spread_survivor_nodes": list(gang.spread_survivor_nodes),
                "priority": gang.priority,
                "gang_pinned_node": gang.pinned_node or None,
            }
        )
    topology = _topology_from_keys(list(request.topology_level_keys))
    return nodes, gang_specs, topology


class RequestDecodeError(ValueError):
    """Malformed/undecodable request — maps to INVALID_ARGUMENT."""


# Sticky group-axis padding, server-side twin of GangScheduler._pad_groups:
# the encoder pads the group axis exactly, so without memory the pending
# mix's max group count would flip between requests and every distinct
# shape would force a fresh XLA compile INSIDE the Solve handler — burning
# the client's per-solve deadline (DEADLINE_EXCEEDED → sidecar fallback).
# Grows to the widest template seen this process, never shrinks; the
# shared helper locks the read-modify-write so concurrent Solve RPCs can't
# interleave a narrow request over a wider width (encode.StickyGroupPad).
# Constructed at import time: a lazy check-then-act would itself race two
# first Solve RPCs into separate instances (encode imports no jax, so the
# top-level import costs nothing).
from grove_tpu.solver.encode import StickyGroupPad

_PAD_GROUPS = StickyGroupPad()


def solve_request(request: pb.SolveRequest) -> pb.SolveResponse:
    """Pure request → response solve (shared by the gRPC handler and
    in-process callers/tests)."""
    from grove_tpu.solver.encode import ConstraintError, build_problem
    from grove_tpu.solver.kernel import solve_waves

    try:
        nodes, gang_specs, topology = _decode_request(request)
    except Exception as exc:
        raise RequestDecodeError(str(exc)) from exc
    try:
        problem = build_problem(
            nodes, gang_specs, topology,
            pad_groups=_PAD_GROUPS.grow(gang_specs),
        )
    except ConstraintError as exc:
        # declared-constraint contradictions (unknown hard keys, spread +
        # per-group pack) are the caller's fault → INVALID_ARGUMENT; any
        # other encoder failure stays a server-side INTERNAL error
        raise RequestDecodeError(str(exc)) from exc
    solve_kwargs = {"with_alloc": not request.options.stats_only}
    if request.options.chunk_size:
        solve_kwargs["chunk_size"] = request.options.chunk_size
    if request.options.max_waves:
        solve_kwargs["max_waves"] = request.options.max_waves
    result = solve_waves(problem, **solve_kwargs)

    level_keys = [lvl.key for lvl in topology.spec.levels]
    response = pb.SolveResponse(solve_seconds=result.solve_seconds)
    for gi, spec in enumerate(gang_specs):
        placement = response.placements.add()
        placement.gang = spec["name"]
        placement.admitted = bool(result.admitted[gi])
        placement.placement_score = float(result.score[gi])
        chosen = int(result.chosen_level[gi])
        placement.chosen_level_key = (
            level_keys[chosen] if 0 <= chosen < len(level_keys) else ""
        )
        if result.alloc is not None and placement.admitted:
            alloc = result.alloc[gi]  # [P, N] pod counts
            for pi, grp in enumerate(spec["groups"]):
                for ni in np.nonzero(alloc[pi])[0]:
                    assignment = placement.assignments.add()
                    assignment.group = grp["name"]
                    assignment.node = problem.node_names[int(ni)]
                    assignment.count = int(alloc[pi][ni])
    return response


class SolverServer:
    """Standalone gRPC server for the sidecar. ``start()`` binds (port 0 →
    ephemeral) and returns self; ``address`` is host:port."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, workers: int = 8):
        _require_grpc()
        self._requested = (host, port)
        self._workers = workers
        self._server = None
        self._serving = False
        # long-lived health Watch streams each occupy one pool thread; cap
        # them well below the pool so watchers can never starve Solve
        self._watch_limit = max(workers // 4, 1)
        self._watchers = 0
        self._watchers_lock = __import__("threading").Lock()
        self.address: Optional[str] = None

    def start(self) -> "SolverServer":
        def solve_handler(request: pb.SolveRequest, context) -> pb.SolveResponse:
            # deadline guard BEFORE the solve: past (or about to pass) the
            # client's deadline, the result is garbage to them — don't burn
            # device time computing it (grpc would only notice at send time)
            remaining = context.time_remaining()
            if remaining is not None and remaining < 0.05:
                context.abort(
                    grpc.StatusCode.DEADLINE_EXCEEDED,
                    "client deadline expired before solve started",
                )
            max_groups = max(
                (len(g.groups) for g in request.gangs), default=0
            )
            complexity = (
                len(request.gangs)
                * max(max_groups, 1)
                * max(len(request.nodes), 1)
            )
            if complexity > MAX_DENSE_CELLS:
                context.abort(
                    grpc.StatusCode.RESOURCE_EXHAUSTED,
                    f"request complexity {complexity} gangs x groups x nodes "
                    f"exceeds {MAX_DENSE_CELLS}",
                )
            try:
                response = solve_request(request)
            except RequestDecodeError as exc:
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT, f"bad request: {exc}"
                )
            except Exception as exc:
                # solver/backend failures are SERVER-side and retryable —
                # never INVALID_ARGUMENT (clients treat that as permanent)
                context.abort(
                    grpc.StatusCode.INTERNAL, f"solve failed: {exc}"
                )
            # the solve outran the deadline or the client hung up: skip the
            # (large) response marshal — nobody is listening
            if not context.is_active():
                context.abort(
                    grpc.StatusCode.CANCELLED,
                    "client gone before solve completed",
                )
            return response

        def health_handler(
            request: health_pb2.HealthCheckRequest, context
        ) -> health_pb2.HealthCheckResponse:
            # empty service = server-wide; the solver service by name; any
            # other name is unknown per the health protocol
            if request.service not in ("", _SERVICE):
                return health_pb2.HealthCheckResponse(
                    status=health_pb2.HealthCheckResponse.SERVICE_UNKNOWN
                )
            status = (
                health_pb2.HealthCheckResponse.SERVING
                if self._serving
                else health_pb2.HealthCheckResponse.NOT_SERVING
            )
            return health_pb2.HealthCheckResponse(status=status)

        def health_watch(request, context):
            # Watch contract: emit the current status, hold the stream open,
            # and re-emit on every change (drain flips to NOT_SERVING inside
            # stop()'s grace window). Each live watcher occupies one
            # worker-pool thread, so they are capped at a fraction of the
            # pool — past the cap the stream degrades to one-shot rather
            # than let watchers starve Solve RPCs.
            import time as _time

            def status_for():
                if request.service not in ("", _SERVICE):
                    return health_pb2.HealthCheckResponse.SERVICE_UNKNOWN
                return (
                    health_pb2.HealthCheckResponse.SERVING
                    if self._serving
                    else health_pb2.HealthCheckResponse.NOT_SERVING
                )

            last = status_for()
            yield health_pb2.HealthCheckResponse(status=last)
            with self._watchers_lock:
                if self._watchers >= self._watch_limit:
                    return  # degrade to one-shot; client re-polls
                self._watchers += 1
            try:
                while context.is_active():
                    current = status_for()
                    if current != last:
                        last = current
                        yield health_pb2.HealthCheckResponse(status=current)
                    _time.sleep(0.2)
            finally:
                with self._watchers_lock:
                    self._watchers -= 1

        handlers = [
            grpc.method_handlers_generic_handler(
                _SERVICE,
                {
                    "Solve": grpc.unary_unary_rpc_method_handler(
                        solve_handler,
                        request_deserializer=pb.SolveRequest.FromString,
                        response_serializer=pb.SolveResponse.SerializeToString,
                    )
                },
            ),
            grpc.method_handlers_generic_handler(
                _HEALTH_SERVICE,
                {
                    "Check": grpc.unary_unary_rpc_method_handler(
                        health_handler,
                        request_deserializer=health_pb2.HealthCheckRequest.FromString,
                        response_serializer=health_pb2.HealthCheckResponse.SerializeToString,
                    ),
                    "Watch": grpc.unary_stream_rpc_method_handler(
                        health_watch,
                        request_deserializer=health_pb2.HealthCheckRequest.FromString,
                        response_serializer=health_pb2.HealthCheckResponse.SerializeToString,
                    ),
                },
            ),
        ]
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self._workers),
            options=[
                ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
                ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
            ],
        )
        self._server.add_generic_rpc_handlers(tuple(handlers))
        host, port = self._requested
        bound = self._server.add_insecure_port(f"{host}:{port}")
        self.address = f"{host}:{bound}"
        self._serving = True
        self._server.start()
        return self

    def stop(self, grace: float = 1.0) -> None:
        self._serving = False  # health flips NOT_SERVING during drain
        if self._server is not None:
            self._server.stop(grace).wait()
            self._server = None


class SolverClient:
    """Thin stub for GangSolver (hand-written; wire-compatible with any
    generated stub of protos/solver.proto)."""

    def __init__(self, address: str):
        _require_grpc()
        self._channel = grpc.insecure_channel(
            address,
            options=[
                ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
                ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
            ],
        )
        self._solve = self._channel.unary_unary(
            f"/{_SERVICE}/Solve",
            request_serializer=pb.SolveRequest.SerializeToString,
            response_deserializer=pb.SolveResponse.FromString,
        )
        self._health = self._channel.unary_unary(
            f"/{_HEALTH_SERVICE}/Check",
            request_serializer=health_pb2.HealthCheckRequest.SerializeToString,
            response_deserializer=health_pb2.HealthCheckResponse.FromString,
        )

    def solve(
        self, request: pb.SolveRequest, timeout: float = 120.0
    ) -> pb.SolveResponse:
        return self._solve(request, timeout=timeout)

    def healthy(self, timeout: float = 2.0) -> bool:
        """Standard grpc.health.v1 Check — what kube gRPC probes would hit."""
        try:
            response = self._health(
                health_pb2.HealthCheckRequest(service=_SERVICE), timeout=timeout
            )
        except grpc.RpcError:
            return False
        return response.status == health_pb2.HealthCheckResponse.SERVING

    def close(self) -> None:
        self._channel.close()


def build_request(
    nodes, gang_specs: List[dict], topology=None
) -> pb.SolveRequest:
    """Encode the scheduler-side domain objects into the wire request (the
    inverse of _decode_request; used by in-process callers and tests)."""
    request = pb.SolveRequest()
    for node in nodes:
        n = request.nodes.add()
        n.name = node.name
        for resource, value in sorted(node.capacity.items()):
            q = n.capacity.add()
            q.resource = resource
            q.value = value
        for k, v in node.labels.items():
            n.labels[k] = v
    for spec in gang_specs:
        gang = request.gangs.add()
        gang.name = spec["name"]
        gang.required_level_key = spec.get("required_key") or ""
        gang.preferred_level_key = spec.get("preferred_key") or ""
        gang.spread_level_key = spec.get("spread_key") or ""
        gang.spread_min_domains = int(spec.get("spread_min_domains") or 0)
        gang.spread_required = bool(spec.get("spread_required", False))
        gang.spread_survivor_nodes.extend(
            spec.get("spread_survivor_nodes") or []
        )
        gang.priority = int(spec.get("priority", 0))
        gang.pinned_node = spec.get("gang_pinned_node") or ""
        for grp in spec["groups"]:
            group = gang.groups.add()
            group.name = grp["name"]
            group.count = int(grp["count"])
            group.min_count = int(grp["min_count"])
            group.pack_level_key = grp.get("required_key") or ""
            group.pinned_node = grp.get("pinned_node") or ""
            for resource, value in sorted(grp["demand"].items()):
                q = group.demand.add()
                q.resource = resource
                q.value = value
    if topology is not None:
        request.topology_level_keys.extend(
            lvl.key for lvl in topology.spec.levels
        )
    return request


def main(argv: Optional[List[str]] = None) -> int:
    """Console entry: run the sidecar until interrupted."""
    import argparse
    import sys
    import time

    parser = argparse.ArgumentParser(prog="grove-tpu-solver")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=50061)
    args = parser.parse_args(argv)

    from grove_tpu.utils.platform import ensure_healthy_backend

    note = ensure_healthy_backend(timeout_s=45.0)
    if note != "default":
        print(f"note: {note}", file=sys.stderr)
    server = SolverServer(args.host, args.port).start()
    print(f"gang-solver sidecar listening on {server.address}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
