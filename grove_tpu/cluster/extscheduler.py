"""External gang scheduler: a KAI-stand-in consuming the PodGang contract
over the wire.

The reference delegates placement to the out-of-process KAI scheduler,
which watches PodGang CRs + ungated pods and binds them all-or-nothing
(SURVEY §1 'Scheduler contract'; the reference e2e installs the real KAI —
e2e/setup/kai_scheduler.go:32-69). This module is that consumer for the
TPU build: a standalone process speaking ONLY the HTTP wire format — no
imports from the operator's in-process store — so contract drift between
the operator's PodGang emission and an external scheduler is observable in
tests instead of hidden behind the in-tree solver.

It reuses the solver-backed GangScheduler over an HttpStore, which is the
point: the same class binds in-process (sim) or out-of-process (here),
because the Store interface IS the contract boundary.

    python -m grove_tpu.cluster.extscheduler --apiserver http://...:PORT
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Dict, List, Optional

# NOTE: no module-level solver/jax imports — `python -m ...extscheduler`
# must be able to scrub a wedged accelerator link (ensure_healthy_backend)
# BEFORE anything pulls in jax, or the import itself can hang (the round-1
# rc=124 failure mode).


def run_external_scheduler(
    apiserver: str,
    nodes: List,
    topology=None,
    priority_map: Optional[Dict[str, int]] = None,
    poll: float = 0.2,
    stop=None,
    kubelet: bool = False,
    solver_sidecar: Optional[str] = None,
) -> None:
    """Blocking scheduler loop against a remote apiserver. `kubelet=True`
    additionally runs the kubelet tick (pods become Ready), for e2e setups
    where this process is the only thing animating the data plane."""
    from grove_tpu.api.topology import ClusterTopology
    from grove_tpu.cluster.client import HttpStore
    from grove_tpu.sim.cluster import SimCluster
    from grove_tpu.solver.scheduler import GangScheduler

    store = HttpStore(
        apiserver, watch_kinds=("Pod", "PodGang", "PodClique")
    ).start()
    cluster = SimCluster(store=store, nodes=nodes)
    scheduler = GangScheduler(
        store, cluster, topology or ClusterTopology(),
        priority_map=priority_map or {},
        solver_sidecar=solver_sidecar,
    )
    from grove_tpu.runtime.errors import GroveError

    try:
        while stop is None or not stop.is_set():
            try:
                bound = scheduler.schedule_pending()
                started = cluster.kubelet_tick() if kubelet else 0
            except GroveError as e:
                # conflicts/races with the concurrently-writing operator are
                # normal in a live cluster: re-read next round, never die
                print(f"scheduler round error (retrying): {e}", file=sys.stderr)
                bound = started = 0
            if bound == 0 and started == 0:
                time.sleep(poll)
    finally:
        store.stop()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="grove-tpu-scheduler", description=__doc__
    )
    parser.add_argument("--apiserver", required=True)
    parser.add_argument("--nodes", type=int, default=32)
    parser.add_argument(
        "--kubelet", action="store_true",
        help="also run the kubelet tick (sim data plane)",
    )
    parser.add_argument("--poll-interval", type=float, default=0.2)
    parser.add_argument(
        "--solver-sidecar",
        help="route packing solves through a gRPC gang-solver sidecar"
        " (host:port; see grove-tpu-solver)",
    )
    args = parser.parse_args(argv)

    # a wedged accelerator link must degrade to CPU, never hang the
    # scheduler process (same probe as the CLI entry points)
    from grove_tpu.utils.platform import ensure_healthy_backend

    note = ensure_healthy_backend(timeout_s=45.0)
    if note != "default":
        print(f"note: {note}", file=sys.stderr)

    from grove_tpu.sim.cluster import make_nodes

    print(
        f"external gang scheduler consuming PodGangs from {args.apiserver}",
        flush=True,
    )
    run_external_scheduler(
        args.apiserver,
        make_nodes(args.nodes),
        poll=args.poll_interval,
        kubelet=args.kubelet,
        solver_sidecar=args.solver_sidecar,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
