"""Operator runtime for real-cluster mode.

The manager-bootstrap equivalent of
/root/reference/operator/internal/controller/manager.go:42-115: assemble the
apiserver connection (HttpStore), webhook server + TLS certs, controllers,
solver-backed scheduler, and the run loop. Health/readiness/metrics are
served by the embedded apiserver (`/healthz`, `/readyz`, `/metrics`); when
connecting to an external server the same endpoints are exposed on a small
sidecar listener.

Leader election (manager.go:84-98) comes in two tiers:
  - **Lease-based** (`leader_election=True`): a coordination.k8s.io/v1
    Lease object on the apiserver, client-go protocol (cluster/lease.py) —
    works across hosts, the reference's HA deployment shape.
  - File lock (`leader_lock_path`): exclusive-create lockfile with
    mtime-staleness stealing — single shared filesystem only; kept for
    setups without an apiserver reachable at boot.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from dataclasses import dataclass
from typing import List, Optional

from grove_tpu.api.topology import ClusterTopology
from grove_tpu.cluster.apiserver import APIServer
from grove_tpu.cluster.client import HttpStore
from grove_tpu.cluster.webhook import WebhookServer
from grove_tpu.controller.common import OperatorContext
from grove_tpu.controller.register import register_controllers
from grove_tpu.runtime.engine import Engine
from grove_tpu.sim.cluster import Node, SimCluster
from grove_tpu.solver.scheduler import GangScheduler


class FileLeaderLock:
    """Exclusive-create lockfile with liveness heartbeat (leader election
    stub; manager.go:84-98)."""

    def __init__(self, path: str, stale_after: float = 30.0) -> None:
        self.path = path
        self.stale_after = stale_after
        self.held = False

    def try_acquire(self) -> bool:
        try:
            fd = os.open(self.path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            # steal stale locks (crashed leader with no heartbeat)
            try:
                if time.time() - os.path.getmtime(self.path) > self.stale_after:
                    os.unlink(self.path)
                    return self.try_acquire()
            except OSError:
                pass
            return False
        with os.fdopen(fd, "w") as f:
            f.write(str(os.getpid()))
        self.held = True
        return True

    def heartbeat(self) -> None:
        if self.held:
            os.utime(self.path, None)

    def release(self) -> None:
        if self.held:
            try:
                os.unlink(self.path)
            except OSError:
                pass
            self.held = False

    def acquire_blocking(self, poll: float = 0.5) -> None:
        while not self.try_acquire():
            time.sleep(poll)


@dataclass
class OperatorRuntime:
    """Assembled operator: store client + engine + scheduler over a cluster
    of nodes, against an embedded or external apiserver."""

    store: HttpStore
    engine: Engine
    scheduler: Optional[GangScheduler]
    cluster: Optional[SimCluster]
    apiserver: Optional[APIServer]
    webhooks: Optional[WebhookServer]
    leader_lock: Optional[FileLeaderLock] = None
    # lease-based election (cluster/lease.py): run() campaigns in standby,
    # a background thread renews while leading (decoupled from reconcile
    # round length), and run() re-enters standby on leadership loss
    elector: Optional[object] = None
    # deferred shared-state publication: with election enabled, only the
    # LEADER may create/reconcile the ClusterTopology CR — a standby that
    # booted with a different hierarchy must not overwrite the active
    # leader's published contract
    topology_publish: Optional[object] = None
    # real threaded reconciles (MaxConcurrentReconciles equivalent) — safe
    # here because the HttpStore/apiserver boundary is thread-safe
    threaded: bool = False
    # multi-level autoscaling (HPA controller equivalent, reference
    # components/hpa) — evaluated each control round like the kube HPA sync
    autoscaler: Optional[object] = None
    metrics_provider: Optional[object] = None
    # node-health monitor + voluntary-disruption layer (docs/robustness.md):
    # heartbeat lifecycle/gang recovery, the disruption broker every
    # voluntary evictor consults, and the drain workflow behind
    # POST /nodes/{name}/drain
    node_monitor: Optional[object] = None
    disruption: Optional[object] = None
    drainer: Optional[object] = None
    # durability attachment of the EMBEDDED apiserver's store (WAL +
    # snapshots + background committer; grove_tpu/durability): set when
    # start_operator ran with durability_dir — shutdown() must stop the
    # committer and drain the final group commit
    durability: Optional[object] = None

    def _drain(self) -> int:
        if self.threaded:
            return self.engine.drain_concurrent()
        return self.engine.drain()

    def converge_once(self) -> int:
        """One control round: reconcile, autoscale, schedule, kubelet.
        Store conflicts in the autoscale/schedule passes are routine under
        concurrent writers (the HPA's read-modify-write isn't atomic over
        the wire) — they re-derive next round; the run loop must survive."""
        from grove_tpu.runtime.errors import GroveError

        if self.elector is not None:
            # leadership is maintained by the elector's background renewer;
            # a deposed leader must not act (the standby that stole the
            # lease is already reconciling)
            if not self.elector.is_leader:
                return 0
        if self.topology_publish is not None:
            try:
                self.topology_publish()
            except GroveError:
                # apiserver blip at the takeover moment: keep the publish
                # pending and retry next round — the run loop must survive
                pass
            else:
                self.topology_publish = None
        work = self._drain()
        if self.autoscaler is not None:
            try:
                work += self.autoscaler.tick()
            except GroveError:
                pass  # conflicting writer; next tick re-reads
        if self.node_monitor is not None:
            try:
                work += self.node_monitor.tick()
            except GroveError:
                pass  # transient apiserver blip; level-triggered retry
        if self.drainer is not None:
            try:
                work += self.drainer.tick()
            except GroveError:
                pass  # intent is persisted; the drain resumes next round
        if self.scheduler is not None:
            try:
                work += self.scheduler.schedule_pending()
            except GroveError:
                pass  # conflict or sidecar outage; next round retries
        if self.cluster is not None:
            work += self.cluster.kubelet_tick()
        work += self._drain()
        # SLO observatory (observability/timeseries.py, slo.py): sampling
        # + objective evaluation at the round boundary, mirroring the sim
        # harness's tick-boundary feed — one boolean check while off
        # (arm with GROVE_TPU_TIMESERIES=1 GROVE_TPU_SLO=1; GET
        # /debug/slo and `cli slo` read the result)
        from grove_tpu.observability.slo import SLO
        from grove_tpu.observability.timeseries import TIMESERIES

        if TIMESERIES.enabled:
            now = self.store.clock.now()
            TIMESERIES.sample(now)
            SLO.evaluate(now)
        if self.leader_lock is not None:
            self.leader_lock.heartbeat()
        return work

    def run(self, stop: Optional[threading.Event] = None, poll: float = 0.2) -> None:
        stop = stop or threading.Event()
        try:
            while not stop.is_set():
                if self.elector is not None and not self.elector.is_leader:
                    # standby: campaign until leadership or stop, dropping
                    # queued watch events nobody will drain meanwhile
                    if not self.elector.acquire_blocking(
                        stop, on_wait=self.engine.discard_pending_events
                    ):
                        break
                    # fresh leader: full resync covers the dropped events,
                    # and the scheduler re-learns bindings made by the old
                    # leader (else node_free() over-commits occupied nodes)
                    self.engine.discard_pending_events()
                    self.engine.requeue_all()
                    if self.cluster is not None:
                        self.cluster.rebuild_bindings()
                    if self.node_monitor is not None:
                        # re-prime gang holds/backoff from persisted
                        # conditions: a failover landing mid-outage must
                        # neither strand a held gang (hold without a
                        # scheduled release) nor let every terminated gang
                        # churn the solver unpaced
                        self.node_monitor.resync()
                    continue
                if self.converge_once() == 0:
                    stop.wait(poll)
        finally:
            if self.elector is not None:
                self.elector.release()
            if self.leader_lock is not None:
                self.leader_lock.release()

    def shutdown(self) -> None:
        self.engine.close()
        self.store.stop()
        if self.durability is not None:
            self.durability.close()  # stop the committer, final flush
        if self.webhooks is not None:
            self.webhooks.stop()
        if self.apiserver is not None:
            self.apiserver.stop()
        if self.elector is not None:
            self.elector.release()
        if self.leader_lock is not None:
            self.leader_lock.release()


def start_operator(
    nodes: Optional[List[Node]] = None,
    topology: Optional[ClusterTopology] = None,
    config=None,
    with_webhooks: bool = True,
    with_tls: bool = False,
    with_authorizer: bool = False,
    with_scheduler: bool = True,
    # tri-state: None = default (single-threaded drain, unless
    # GROVE_TPU_CP_WORKERS maps onto threaded reconciles — see below);
    # an explicit True/False always wins over the env knob
    threaded: Optional[bool] = None,
    apiserver_url: Optional[str] = None,
    leader_lock_path: Optional[str] = None,
    leader_election: Optional[bool] = None,
    leader_identity: Optional[str] = None,
    metrics_provider=None,
    durability_dir: Optional[str] = None,
) -> OperatorRuntime:
    """Boot the full real-cluster operator (embedded apiserver unless
    `apiserver_url` points at an external one), mirroring main.go startup:
    config → topology check → certs → webhooks → controllers → run.

    `durability_dir` (embedded apiserver only): recover the store from
    the directory's snapshot + WAL tail before serving — a crash-restart
    then converges like a failover, via the same resync machinery the
    lease-takeover path runs (requeue_all / rebuild_bindings / monitor
    resync) — and attach the WAL with a background group-commit thread."""
    from grove_tpu.config.operator import OperatorConfiguration
    from grove_tpu.sim.cluster import make_nodes

    config = config or OperatorConfiguration()
    topology = topology or ClusterTopology()

    durability = None
    backing_store = None
    recovered_objects = 0
    if durability_dir is not None and apiserver_url is None:
        from grove_tpu.durability import recover_store

        backing_store, recovery = recover_store(durability_dir)
        recovered_objects = recovery.restored_objects

    webhooks = None
    registrations = []
    if with_webhooks:
        certs = None
        if with_tls:
            from grove_tpu.cluster.cert import ensure_certs

            certs = ensure_certs(
                os.path.join(tempfile.gettempdir(), "grove-tpu-webhook-certs")
            )
        guard = None
        if with_authorizer:
            from grove_tpu.admission.authorization import AuthorizationGuard

            guard = AuthorizationGuard(
                enabled=True,
                exempt_users=config.authorizer.exempt_service_accounts,
            )
        webhooks = WebhookServer(
            topology=topology, guard=guard, certs=certs
        ).start()
        registrations = webhooks.registrations()

    apiserver = None
    if apiserver_url is None:
        apiserver = APIServer(
            store=backing_store,
            webhooks=registrations,
            enable_profiling=config.server.profiling_enabled,
        )
        if durability_dir is not None:
            from grove_tpu.durability import StoreDurability

            # attach AFTER recovery, BEFORE the apiserver starts serving:
            # a commit from an early HTTP client must be logged too, or
            # its ack would not survive the next crash-restart; the
            # apiserver's request lock serializes snapshot scans against
            # concurrent handlers
            durability = StoreDurability(
                apiserver.store, durability_dir, lock=apiserver.lock
            )
            durability.start_committer()
        apiserver.start()
        apiserver_url = apiserver.address

    leader_lock = None
    if leader_lock_path:
        leader_lock = FileLeaderLock(leader_lock_path)
        leader_lock.acquire_blocking()

    store = HttpStore(apiserver_url).start()

    # SLO-observatory clock (observability/timeseries.py): ring ticks come
    # from the store's clock from the FIRST reconcile round — a journey
    # completing before the first sampling round must not stamp tick 0
    from grove_tpu.observability.timeseries import TIMESERIES

    if TIMESERIES.enabled:
        TIMESERIES.clock = store.clock

    # materialize the hierarchy as a CR so wire clients can inspect what the
    # operator schedules against (the reference crashes when the configured
    # CR is missing, cmd/main.go validateClusterTopology; here the operator
    # OWNS the CR — incl. an auto-detected one — and publishes it)
    def publish_topology() -> None:
        from grove_tpu.runtime.errors import ERR_CONFLICT, GroveError

        try:
            store.create(topology)
        except GroveError as exc:
            if exc.code != ERR_CONFLICT:
                raise
            # restart / external apiserver: the stored CR must match what
            # the operator actually schedules against — a stale hierarchy
            # (e.g. nodes relabeled before an --auto-detect-topology
            # restart) would make the published contract silently wrong
            stored = store.get("ClusterTopology", "", topology.metadata.name)
            if [(l.domain, l.key) for l in stored.spec.levels] != [
                (l.domain, l.key) for l in topology.spec.levels
            ]:
                stored.spec = topology.spec
                store.update(stored)

    if not topology.metadata.name:
        topology.metadata.name = "default"
    engine = Engine(store, store.clock)
    # parallel control plane (docs/control-plane.md §5): the env opt-in
    # (GROVE_TPU_CP_WORKERS) arms only over a SHARDED in-memory store —
    # cluster mode drains an HttpStore, where per-shard ownership cannot
    # be enforced across the wire. Map the same intent onto this tier's
    # concurrency model instead: MaxConcurrentReconciles-style threaded
    # reconciles (drain_concurrent), which the thread-safe apiserver
    # boundary already supports. An EXPLICIT threaded=True/False from the
    # caller always wins — the env knob names a deterministic feature,
    # so it must never silently override a caller who pinned the
    # single-threaded drain; only the unset (None) default maps.
    if threaded is None:
        from grove_tpu.runtime.workers import workers_from_env

        threaded = engine.workers is None and workers_from_env() > 1
    ctx = OperatorContext(store=store, clock=store.clock, topology=topology)
    register_controllers(engine, ctx, config)
    if recovered_objects:
        # recovered state predates every watch: enqueue it all once — the
        # informer ListAndWatch-restart a fresh process performs (the same
        # resync a lease takeover runs; rebuild_bindings/monitor resync
        # below complete the machinery)
        engine.requeue_all()
    # with_scheduler=False leaves binding entirely to an EXTERNAL scheduler
    # consuming the PodGang contract over the wire (the reference's KAI
    # deployment shape — grove_tpu.cluster.extscheduler is the stand-in)
    cluster = scheduler = node_monitor = disruption = drainer = None
    if with_scheduler:
        cluster = SimCluster(store=store, nodes=nodes or make_nodes(16))
        # restart path: account for pods a predecessor already bound (an
        # external apiserver outlives operator processes)
        cluster.rebuild_bindings()
        scheduler = GangScheduler(
            store,
            cluster,
            topology,
            priority_map=config.solver.priority_classes,
            chunk_size=min(config.solver.chunk_size, 64),
            max_waves=config.solver.max_waves,
            solver_sidecar=config.solver.sidecar_address or None,
        )
        # node-health + voluntary-disruption layer (docs/robustness.md):
        # same wiring shape as the sim harness
        from grove_tpu.controller.nodehealth import NodeHealthMonitor
        from grove_tpu.disruption import (
            DisruptionBroker,
            NodeDrainController,
        )

        node_monitor = NodeHealthMonitor(store, cluster)
        scheduler.monitor = node_monitor
        disruption = DisruptionBroker(store)
        scheduler.broker = disruption
        drainer = NodeDrainController(
            store, cluster, scheduler, node_monitor, disruption
        )
        node_monitor.drain_states = drainer.states
        node_monitor.resync()  # restart path: re-prime persisted requeues
        ctx.disruption = disruption  # rolling update consults it too
        if apiserver is not None:
            apiserver.node_provider = node_monitor.node_snapshot
            apiserver.drain_handler = drainer.request_drain
            apiserver.uncordon_handler = drainer.uncordon
            # decision explainability (docs/observability.md "Admission
            # explain"): GET /gangs/{ns}/{name}/explain, /debug/capacity,
            # POST /debug/whatif — read-only, so no lock coupling
            from grove_tpu.observability.explain import ExplainEngine

            apiserver.explain_engine = ExplainEngine(scheduler)
    from grove_tpu.autoscale.hpa import (
        HorizontalAutoscaler,
        StaticMetricsProvider,
    )

    # real deployments inject a provider backed by their metrics pipeline
    # (HPAs are inert without one — StaticMetricsProvider only serves what
    # tests/sims poke into it)
    metrics_provider = metrics_provider or StaticMetricsProvider()
    autoscaler = HorizontalAutoscaler(store, metrics_provider)
    elector = None
    elect = (
        leader_election
        if leader_election is not None
        else config.leader_election.enabled
    )
    if elect:
        from grove_tpu.cluster.lease import LeaseElector

        le = config.leader_election
        elector = LeaseElector(
            store,
            name=le.resource_name,
            identity=leader_identity,
            lease_duration=le.lease_duration,
            renew_deadline=le.renew_deadline,
            retry_period=le.retry_period,
            background_renew=True,
        )
    else:
        # no election: this process is the only writer — publish now, the
        # startup-crash semantics of the reference's validateClusterTopology
        publish_topology()
    return OperatorRuntime(
        store=store,
        engine=engine,
        scheduler=scheduler,
        cluster=cluster,
        apiserver=apiserver,
        webhooks=webhooks,
        leader_lock=leader_lock,
        elector=elector,
        topology_publish=publish_topology if elect else None,
        threaded=threaded,
        autoscaler=autoscaler,
        metrics_provider=metrics_provider,
        node_monitor=node_monitor,
        disruption=disruption,
        drainer=drainer,
        durability=durability,
    )
