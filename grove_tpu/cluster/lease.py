"""Lease-based leader election over the apiserver.

The reference elects through a coordination.k8s.io/v1 Lease on the
kube-apiserver (manager.go:84-98: LeaderElection + LeaderElectionID +
LeaderElectionReleaseOnCancel). This is the same protocol against our own
apiserver: a Lease object holds (holderIdentity, renewTime,
leaseDurationSeconds, leaseTransitions); candidates race CREATE, the holder
renews every retry period, standbys take over once the holder's renewTime
stops changing for a lease duration, and graceful shutdown clears the
holder so failover is immediate. The store's optimistic concurrency
(resourceVersion conflict on update) is what makes the race safe across
processes — exactly the role the kube apiserver plays for client-go's
leaderelection package.

client-go semantics deliberately preserved:
  - **Skew immunity**: a standby never compares the lease's renewTime
    timestamp against its own wall clock (cross-host clock skew would steal
    live leases). It records WHEN IT LOCALLY OBSERVED the renewTime value
    change and declares expiry only after a full lease duration of local
    monotonic time without a change.
  - **Renew-deadline tolerance**: transient apiserver/transport failures
    during renew do not drop leadership; the leader steps down only after
    failing to renew for `renew_deadline` seconds (then standbys are about
    to take over anyway).
  - **Background renewal**: with `background_renew=True` (the operator run
    loop's mode) a daemon thread renews every `retry_period`, decoupled
    from reconcile-round length — a long converge round can't silently let
    the lease lapse mid-round.
  - No campaign/renew error ever propagates: election is infrastructure
    upkeep; the run loop must survive apiserver restarts.

Unlike the FileLeaderLock (single shared filesystem), this works for any
set of operator hosts that can reach the apiserver — the HA deployment
shape of the reference.
"""

from __future__ import annotations

import os
import socket
import threading
import time
import uuid
from typing import Optional

from grove_tpu.api.meta import ObjectMeta
from grove_tpu.api.types import GenericObject
from grove_tpu.runtime.errors import ERR_CONFLICT, ERR_NOT_FOUND, GroveError


def default_identity() -> str:
    """hostname_pid_nonce — unique per elector (client-go uses
    hostname + '_' + uuid; the nonce also separates two runtimes that
    share a process, as in-process HA tests do)."""
    return f"{socket.gethostname()}_{os.getpid()}_{uuid.uuid4().hex[:6]}"


class LeaseElector:
    """Campaign for, renew, and release one named Lease.

    Protocol:
      - `try_acquire`: create the Lease if absent; adopt it if released or
        locally-observed-expired; renew it if already ours. Returns False
        on any race lost or infrastructure error (campaign again next
        tick).
      - `renew`: heartbeat. Returns False when leadership was LOST —
        the caller must stop acting as leader immediately. Transient
        errors inside `renew_deadline` keep leadership.
      - `release`: clear holderIdentity (keep the object + transitions
        counter) so standbys take over without waiting out the duration.
      - `stop_renewing`: halt the background renewer WITHOUT releasing —
        the crash simulation (and the pre-release step of shutdown).
    """

    def __init__(
        self,
        store,
        name: str = "grove-tpu-leader-election",
        namespace: str = "default",
        identity: Optional[str] = None,
        lease_duration: float = 15.0,
        renew_deadline: float = 10.0,
        retry_period: float = 2.0,
        background_renew: bool = False,
    ) -> None:
        self.store = store
        self.name = name
        self.namespace = namespace
        self.identity = identity or default_identity()
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.background_renew = background_renew
        self.is_leader = False
        # local observation of the current holder's renew progress:
        # (holder, renewTime value, monotonic timestamp of first sighting)
        self._observed: Optional[tuple] = None
        self._last_renew_ok: float = 0.0  # monotonic
        self._renew_stop: Optional[threading.Event] = None

    # -- wire object ------------------------------------------------------

    def _get(self):
        return self.store.get("Lease", self.namespace, self.name)

    def _spec(self, acquire_time: float, transitions: int) -> dict:
        return {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": self.lease_duration,
            "acquireTime": acquire_time,
            "renewTime": time.time(),
            "leaseTransitions": transitions,
        }

    def _won(self) -> bool:
        self.is_leader = True
        self._last_renew_ok = time.monotonic()
        if self.background_renew:
            self._start_renewer()
        return True

    # -- campaign ---------------------------------------------------------

    def _foreign_lease_expired(self, holder: str, renew_time: float) -> bool:
        """Skew-immune expiry: true only after a full lease duration of
        LOCAL monotonic time without observing renewTime change."""
        now = time.monotonic()
        if self._observed is None or self._observed[:2] != (holder, renew_time):
            self._observed = (holder, renew_time, now)
            return False
        return now - self._observed[2] >= self.lease_duration

    def try_acquire(self) -> bool:
        try:
            return self._try_acquire()
        except GroveError:
            return False  # apiserver blip: campaign again next tick

    def _try_acquire(self) -> bool:
        lease = self._get()
        if lease is None:
            obj = GenericObject(
                kind="Lease",
                metadata=ObjectMeta(name=self.name, namespace=self.namespace),
                spec=self._spec(acquire_time=time.time(), transitions=0),
            )
            try:
                self.store.create(obj)
            except GroveError as exc:
                if exc.code == ERR_CONFLICT:
                    return False  # lost the create race
                raise
            return self._won()
        holder = lease.spec.get("holderIdentity") or ""
        renew_time = float(lease.spec.get("renewTime") or 0.0)
        if holder == self.identity:
            # re-adopting our own lease (e.g. apiserver outage outlasted the
            # renew deadline, then recovered before anyone stole it) —
            # _won() must run so the background renewer RESTARTS; renew()
            # alone would leave is_leader=True with nothing renewing
            self.is_leader = True
            return self._won() if self.renew() else False
        if holder and not self._foreign_lease_expired(holder, renew_time):
            return False  # live leader elsewhere
        # released or expired: take over, bumping the transitions counter
        lease.spec = self._spec(
            acquire_time=time.time(),
            transitions=int(lease.spec.get("leaseTransitions") or 0) + 1,
        )
        try:
            self.store.update(lease, bump_generation=False)
        except GroveError as exc:
            if exc.code in (ERR_CONFLICT, ERR_NOT_FOUND):
                return False  # another standby won the takeover
            raise
        return self._won()

    def acquire_blocking(self, stop=None, on_wait=None) -> bool:
        """Standby loop: campaign every retry_period until leadership or
        `stop`; `on_wait` runs between attempts (e.g. dropping queued watch
        events nobody will drain). Returns False only when stopped."""
        while stop is None or not stop.is_set():
            if self.try_acquire():
                return True
            if on_wait is not None:
                on_wait()
            if stop is None:
                time.sleep(self.retry_period)
            else:
                stop.wait(self.retry_period)
        return False

    # -- leadership upkeep ------------------------------------------------

    def renew(self) -> bool:
        """Heartbeat. False = leadership lost; stop leading NOW.
        Infrastructure errors are tolerated until renew_deadline."""
        if not self.is_leader:
            return False
        try:
            lease = self._get()
            if lease is None or lease.spec.get("holderIdentity") != self.identity:
                self._lost()
                return False
            lease.spec = dict(lease.spec, renewTime=time.time())
            self.store.update(lease, bump_generation=False)
            self._last_renew_ok = time.monotonic()
            return True
        except GroveError as exc:
            if exc.code in (ERR_CONFLICT, ERR_NOT_FOUND):
                # a conflict only means LOST if the holder changed — our own
                # concurrent renew (background thread + a manual call) also
                # conflicts, benignly
                try:
                    fresh = self._get()
                except GroveError:
                    fresh = None
                if (
                    fresh is not None
                    and fresh.spec.get("holderIdentity") == self.identity
                ):
                    self._last_renew_ok = time.monotonic()
                    return True
                self._lost()
                return False
            # transport/apiserver blip: keep leading inside the deadline
            if time.monotonic() - self._last_renew_ok > self.renew_deadline:
                self._lost()
                return False
            return True

    def _lost(self) -> None:
        self.is_leader = False
        self._observed = None
        self.stop_renewing()

    # -- background renewer -----------------------------------------------

    def _start_renewer(self) -> None:
        if self._renew_stop is not None and not self._renew_stop.is_set():
            return  # already running
        stop = threading.Event()
        self._renew_stop = stop

        def loop():
            while not stop.wait(self.retry_period):
                if not self.is_leader or not self.renew():
                    break

        threading.Thread(
            target=loop, name=f"lease-renew-{self.name}", daemon=True
        ).start()

    def stop_renewing(self) -> None:
        """Halt background renewal without touching the lease — from here
        the lease ages out like a crashed leader's would."""
        if self._renew_stop is not None:
            self._renew_stop.set()

    def release(self) -> None:
        """Graceful abdication (LeaderElectionReleaseOnCancel): clear the
        holder so the next campaign wins without waiting out the lease."""
        self.stop_renewing()
        if not self.is_leader:
            return
        self.is_leader = False
        self._observed = None
        try:
            lease = self._get()
            if lease is not None and lease.spec.get("holderIdentity") == self.identity:
                lease.spec = dict(lease.spec, holderIdentity="", renewTime=0.0)
                self.store.update(lease, bump_generation=False)
        except GroveError:
            pass  # releasing best-effort; expiry covers the crash path
