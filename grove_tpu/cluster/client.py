"""HttpStore: the Store interface over the k8s-shaped REST API.

The typed-client + informer layer of the reference (generated clientsets in
operator/client/ + scheduler/client/, SURVEY §2.1 'Generated clients') in one
class: CRUD verbs map to HTTP calls against grove_tpu.cluster.apiserver (or
any server speaking the same wire shape), and `start()` opens one list+watch
stream per kind feeding the same subscriber callbacks the in-memory Store
emits — so the Engine and all controllers run UNCHANGED against a live
apiserver.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Dict, List, Optional

from grove_tpu.api.serialize import export_object
from grove_tpu.api.wire import KIND_REGISTRY, decode_object
from grove_tpu.runtime.clock import Clock
from grove_tpu.runtime.errors import (
    ERR_CONFLICT,
    ERR_CREATE_RESOURCE,
    ERR_FORBIDDEN,
    ERR_NOT_FOUND,
    ERR_TRANSPORT,
    GroveError,
)
from grove_tpu.runtime.store import WatchEvent

# kinds the operator watches (controller/register.py wiring)
DEFAULT_WATCH_KINDS = (
    "PodCliqueSet",
    "PodClique",
    "PodCliqueScalingGroup",
    "PodGang",
    "Pod",
)

_CODE_FOR_STATUS = {
    404: ERR_NOT_FOUND,
    409: ERR_CONFLICT,
    403: ERR_FORBIDDEN,
    422: "ERR_VALIDATION",
}


class _PodSpecShim:
    """The single pod-spec field the watch predicates compare."""

    __slots__ = ("scheduling_gates",)

    def __init__(self, gates) -> None:
        self.scheduling_gates = gates


class _OldView:
    """Predicate-sufficient retention of a watched object for
    WatchEvent.old: shares the decoded metadata and status sub-objects and
    keeps spec only where a registered predicate compares it (PodGang spec
    membership; the Pod scheduling-gate list as a shim). Everything else —
    for Pods, the whole container/env template — is dropped, so the
    informer-local `last` map no longer duplicates a second fully-decoded
    copy of every live object (~47k pod specs in cluster mode)."""

    __slots__ = ("kind", "metadata", "status", "spec")

    def __init__(self, obj) -> None:
        self.kind = obj.kind
        self.metadata = obj.metadata
        self.status = getattr(obj, "status", None)
        if obj.kind == "PodGang":
            self.spec = obj.spec  # podgang_phase_or_spec_changed compares it
        elif obj.kind == "Pod":
            self.spec = _PodSpecShim(obj.spec.scheduling_gates)
        else:
            self.spec = None  # no registered predicate reads old.spec


class HttpStore:
    """Store-compatible client over HTTP. Reads are live (no informer lag);
    watches feed subscribe() callbacks from per-kind reader threads."""

    def __init__(
        self,
        base_url: str,
        clock: Optional[Clock] = None,
        watch_kinds=DEFAULT_WATCH_KINDS,
        username: Optional[str] = None,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self.clock = clock or Clock()
        self.cache_lag = False  # no informer-staleness modeling client-side
        self.guard = None
        self.error_injectors: Dict[str, Callable] = {}
        self.watch_kinds = tuple(watch_kinds)
        self._watchers: List[Callable[[WatchEvent], None]] = []
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._username = username
        self._local = threading.local()

    # -- impersonation ----------------------------------------------------

    def as_user(self, username: str):
        from contextlib import contextmanager

        @contextmanager
        def _cm():
            prev = getattr(self._local, "user", None)
            self._local.user = username
            try:
                yield self
            finally:
                self._local.user = prev

        return _cm()

    # -- HTTP plumbing ----------------------------------------------------

    def _path(self, kind: str, namespace: Optional[str], name: Optional[str]) -> str:
        info = KIND_REGISTRY[kind]
        root = "/api/v1" if not info.group else f"/apis/{info.group}/{info.version}"
        parts = [root]
        if info.namespaced and namespace is not None:
            parts.append(f"namespaces/{urllib.parse.quote(namespace)}")
        parts.append(info.plural)
        if name is not None:
            parts.append(urllib.parse.quote(name))
        return "/".join(parts)

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        query: Optional[Dict[str, str]] = None,
        operation: str = "",
    ) -> dict:
        url = self.base_url + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        user = getattr(self._local, "user", None) or self._username
        if user:
            headers["Impersonate-User"] = user
        req = urllib.request.Request(url, data=data, headers=headers, method=method)
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read() or b"{}")
            except Exception:
                payload = {}
            raise GroveError(
                _CODE_FOR_STATUS.get(e.code, ERR_CREATE_RESOURCE),
                payload.get("message", str(e)),
                operation or method.lower(),
            ) from None
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            # transport failure (apiserver restart, connection reset, socket
            # timeout): typed like any other store error so callers' retry
            # paths — reconcile requeues, the external scheduler loop —
            # treat it as transient instead of dying on a raw urllib error
            raise GroveError(
                ERR_TRANSPORT, str(e), operation or method.lower()
            ) from None

    # -- watch ------------------------------------------------------------

    def subscribe(self, fn: Callable[[WatchEvent], None]) -> None:
        self._watchers.append(fn)

    def start(self) -> "HttpStore":
        """Open one list+watch stream per kind (informer equivalent)."""
        for kind in self.watch_kinds:
            t = threading.Thread(
                target=self._watch_loop, args=(kind,),
                name=f"watch-{kind}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()

    def _watch_loop(self, kind: str) -> None:
        path = self._path(kind, None, None)
        url = self.base_url + path + "?watch=true"
        # informer-local last-seen objects: lets MODIFIED events carry the
        # previous object (WatchEvent.old) so transition predicates work in
        # cluster mode too; a reconnect clears it (old=None fails open).
        # Stored as predicate-sufficient _OldView slices, not full decodes.
        last: dict = {}
        while not self._stop.is_set():
            try:
                with urllib.request.urlopen(url, timeout=None) as resp:
                    for raw in resp:
                        if self._stop.is_set():
                            return
                        line = raw.strip()
                        if not line:
                            continue
                        payload = json.loads(line)
                        obj = decode_object(payload["object"])
                        key = (obj.metadata.namespace, obj.metadata.name)
                        # wire uses k8s event casing; Store uses title case
                        type_ = payload["type"].capitalize()
                        old = last.get(key)
                        if type_ == "Deleted":
                            last.pop(key, None)
                        else:
                            last[key] = _OldView(obj)
                        ev = WatchEvent(
                            type=type_, kind=kind, obj=obj, old=old
                        )
                        for w in list(self._watchers):
                            w(ev)
            except Exception:
                if self._stop.is_set():
                    return
                last.clear()
                self._stop.wait(0.2)  # reconnect (server restart etc.)

    # -- CRUD -------------------------------------------------------------

    def create(self, obj, consume: bool = False, share: bool = False):
        # `consume`/`share` are Store-interface fast-path markers; over
        # HTTP every request body is a private JSON export already
        doc = export_object(obj)
        out = self._request(
            "POST",
            self._path(obj.kind, obj.metadata.namespace, None),
            body=doc,
            operation="create",
        )
        return decode_object(out)

    def get(
        self,
        kind: str,
        namespace: str,
        name: str,
        cached: bool = False,
        readonly: bool = False,
    ):
        # `readonly` is a Store-interface contract marker: over HTTP every
        # response is already a private decode, so it changes nothing here
        try:
            out = self._request(
                "GET", self._path(kind, namespace, name), operation="get"
            )
        except GroveError as e:
            if e.code == ERR_NOT_FOUND:
                return None
            raise
        return decode_object(out)

    def scan(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        cached: bool = False,
    ):
        """Store.scan parity: over HTTP a list response is already private
        decoded objects, so scan == iterate the list."""
        return iter(self.list(kind, namespace, label_selector, cached))

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        cached: bool = False,
    ) -> List[object]:
        query = {}
        if label_selector:
            query["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in sorted(label_selector.items())
            )
        out = self._request(
            "GET",
            self._path(kind, namespace, None),
            query=query or None,
            operation="list",
        )
        return [decode_object(item) for item in out.get("items", [])]

    def update(self, obj, bump_generation: bool = True):
        out = self._request(
            "PUT",
            self._path(obj.kind, obj.metadata.namespace, obj.metadata.name),
            body=export_object(obj),
            operation="update",
        )
        return decode_object(out)

    def update_status(self, obj):
        out = self._request(
            "PUT",
            self._path(obj.kind, obj.metadata.namespace, obj.metadata.name)
            + "/status",
            body=export_object(obj),
            operation="update_status",
        )
        return decode_object(out)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._request(
            "DELETE", self._path(kind, namespace, name), operation="delete"
        )

    def read_modify_write(
        self, kind: str, namespace: str, name: str, mutate, attempts: int = 8
    ):
        """Optimistic-concurrency write loop: GET the LIVE object, apply
        `mutate(obj)` (edit in place; return False to skip the write), PUT,
        and retry from a fresh read on 409 — so a racing writer's changes
        are never clobbered (the mutation is re-applied to their version,
        kubectl-style). Returns the updated object, or None if the object
        does not exist / disappeared mid-loop."""
        for _ in range(attempts):
            obj = self.get(kind, namespace, name)
            if obj is None:
                return None
            if mutate(obj) is False:
                return obj
            try:
                return self.update(obj)
            except GroveError as e:
                if e.code != ERR_CONFLICT:
                    raise
        raise GroveError(
            ERR_CONFLICT,
            f"{kind} {namespace}/{name}: write kept conflicting after"
            f" {attempts} attempts",
            "read_modify_write",
        )

    def remove_finalizer(
        self, kind: str, namespace: str, name: str, finalizer: str
    ) -> None:
        """Client-side finalizer drain: the server completes the deletion
        when the list empties."""

        def drop(obj):
            if finalizer not in obj.metadata.finalizers:
                return False
            obj.metadata.finalizers = [
                f for f in obj.metadata.finalizers if f != finalizer
            ]

        self.read_modify_write(kind, namespace, name, drop)

    def delete_collection(
        self,
        kind: str,
        namespace: str,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> int:
        query = {}
        if label_selector:
            query["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in sorted(label_selector.items())
            )
        out = self._request(
            "DELETE",
            self._path(kind, namespace, None),
            query=query or None,
            operation="delete_collection",
        )
        return int(out.get("deleted", 0))
