"""API reference generation from the typed model.

The counterpart of the reference's generated API docs
(/root/reference/docs/api-reference/operator-api.md,
/root/reference/docs/api-reference/scheduler-api.md — produced there by
crd-ref-docs from Go struct comments). Here the same document is derived
reflectively from the dataclasses: field tables (wire name, type, default)
plus descriptions pulled from the comment lines that annotate each field in
the source, so the docs can never drift from the model (drift-tested like
the CRDs, tests/test_cluster_mode.py).

`grove-tpu api-docs [--write PATH]` renders it; docs/api-reference.md holds
the committed copy.
"""

from __future__ import annotations

import dataclasses
import inspect
import re
import typing
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Field-comment extraction
# ---------------------------------------------------------------------------

_FIELD_RE = re.compile(r"^\s+(\w+)\s*(?::|\s*=)")
_COMMENT_RE = re.compile(r"^\s+#\s?(.*)$")


def _field_comments(cls: type) -> Dict[str, str]:
    """Map field name -> the contiguous `#` comment block directly above its
    declaration in the class body (the dataclass idiom this codebase uses for
    per-field docs)."""
    try:
        src = inspect.getsource(cls)
    except (OSError, TypeError):
        return {}
    names = {f.name for f in dataclasses.fields(cls)}
    out: Dict[str, str] = {}
    pending: List[str] = []
    for line in src.splitlines():
        m = _COMMENT_RE.match(line)
        if m:
            pending.append(m.group(1).rstrip())
            continue
        fm = _FIELD_RE.match(line)
        if fm and fm.group(1) in names:
            if pending:
                out[fm.group(1)] = " ".join(pending).strip()
            pending = []
            continue
        if line.strip():  # any other code breaks the comment run
            pending = []
    return out


# the documented wire names come from the SAME helper the serializer uses,
# so they cannot drift from what the wire actually accepts
from grove_tpu.api.serialize import _camel  # noqa: E402


# ---------------------------------------------------------------------------
# Type rendering + reachability walk
# ---------------------------------------------------------------------------


def _render_type(hint: Any, refs: List[type]) -> str:
    origin = typing.get_origin(hint)
    if origin is typing.Union:
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        inner = ", ".join(_render_type(a, refs) for a in args)
        return f"optional {inner}" if len(args) == 1 else f"union[{inner}]"
    if origin in (list, typing.List):
        (item,) = typing.get_args(hint) or (Any,)
        return f"list of {_render_type(item, refs)}"
    if origin in (dict, typing.Dict):
        args = typing.get_args(hint)
        if len(args) == 2 and args[1] is not Any:
            return f"map of string → {_render_type(args[1], refs)}"
        return "object (free-form)"
    if dataclasses.is_dataclass(hint):
        if hint not in refs:
            refs.append(hint)
        return f"[{hint.__name__}](#{hint.__name__.lower()})"
    if hint is Any:
        return "any"
    if hint is type(None):
        return "null"
    return {bool: "boolean", int: "integer", float: "number", str: "string"}.get(
        hint, getattr(hint, "__name__", str(hint))
    )


def _render_default(f: dataclasses.Field) -> str:
    if f.default is not dataclasses.MISSING:
        if f.default is None:
            return ""
        if isinstance(f.default, str):
            return f"`{f.default}`" if f.default else '`""`'
        return f"`{f.default}`"
    if f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
        try:
            v = f.default_factory()  # type: ignore[misc]
        except Exception:
            return ""
        # nested objects and long structured defaults are documented by their
        # own tables; inlining the repr would be noise
        if dataclasses.is_dataclass(v) or len(repr(v)) > 40:
            return ""
        if v in ({}, [], ()):  # empty containers read better blank
            return ""
        return f"`{v}`"
    return "required"


def _doc_summary(cls: type) -> str:
    doc = inspect.getdoc(cls) or ""
    if doc.startswith(f"{cls.__name__}("):  # dataclass auto-signature, not docs
        return ""
    return doc.strip()


def _render_dataclass(cls: type, refs: List[type]) -> str:
    hints = typing.get_type_hints(cls)
    lines = [f"### {cls.__name__}", ""]
    summary = _doc_summary(cls)
    if summary:
        lines += [summary, ""]
    comments = _field_comments(cls)
    lines += [
        "| Field | Type | Default | Description |",
        "|---|---|---|---|",
    ]
    for f in dataclasses.fields(cls):
        desc = comments.get(f.name, "").replace("|", "\\|")
        lines.append(
            f"| `{_camel(f.name)}` | {_render_type(hints[f.name], refs)}"
            f" | {_render_default(f)} | {desc} |"
        )
    lines.append("")
    return "\n".join(lines)


def _section(
    title: str,
    intro: str,
    roots: List[type],
    skip: Optional[set] = None,
) -> str:
    """Render the roots plus every dataclass transitively reachable from
    their fields, each type documented exactly once, in first-reached order.
    Types in `skip` are linked but rendered elsewhere (the shared section)."""
    skip = skip or set()
    refs: List[type] = list(roots)
    out = [f"## {title}", "", intro, ""]
    i = 0
    while i < len(refs):
        if refs[i] not in skip:
            out.append(_render_dataclass(refs[i], refs))
        i += 1
    return "\n".join(out)


# ---------------------------------------------------------------------------
# The document
# ---------------------------------------------------------------------------


def render_api_reference() -> str:
    from grove_tpu.api.meta import Condition, ObjectMeta
    from grove_tpu.api.topology import ClusterTopology
    from grove_tpu.api.types import (
        PodClique,
        PodCliqueScalingGroup,
        PodCliqueSet,
        PodGang,
        Queue,
    )
    from grove_tpu.config.operator import OperatorConfiguration

    header = (
        "# API reference\n\n"
        "Generated from the typed model (`grove-tpu api-docs`); do not edit\n"
        "by hand — regenerate with `grove-tpu api-docs --write"
        " docs/api-reference.md`.\n"
        "Field names are the camelCase wire names accepted in YAML manifests\n"
        "(reference-format manifests load unchanged). Counterpart of the\n"
        "reference's generated API docs"
        " (docs/api-reference/{operator-api,scheduler-api}.md).\n"
    )
    shared_types = {ObjectMeta, Condition}
    operator = _section(
        "Operator API (`grove.io/v1alpha1`)",
        "The user-facing custom resources: `PodCliqueSet` (the one manifest a\n"
        "user writes), its children `PodClique` and `PodCliqueScalingGroup`,\n"
        "and the cluster-scoped `ClusterTopology` hierarchy.",
        [PodCliqueSet, PodClique, PodCliqueScalingGroup, ClusterTopology],
        skip=shared_types,
    )
    scheduler = _section(
        "Scheduler API (`scheduler.grove.io/v1alpha1`)",
        "The gang-scheduling contract consumed by the placement engine (the\n"
        "in-tree TPU solver, the gRPC sidecar, or an external scheduler),\n"
        "plus the cluster-scoped tenant `Queue` of the quota/fair-share\n"
        "subsystem (docs/quota.md).",
        [PodGang, Queue],
        skip=shared_types,
    )
    shared = _section(
        "Shared metadata types",
        "Object metadata and condition types used across both API groups.",
        [ObjectMeta, Condition],
    )
    config = _section(
        "Operator configuration (file API)",
        "The versioned configuration file loaded at operator startup\n"
        "(`grove-tpu run --config`, `grove-tpu config-check`).",
        [OperatorConfiguration],
    )
    return "\n".join([header, operator, scheduler, shared, config])


def write_api_reference(path: str) -> str:
    import pathlib

    p = pathlib.Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(render_api_reference())
    return str(p)
