"""Everything-at-once day: the remediation loop's A/B proving ground.

One seeded serving day composes every stressor the repo knows at once —
diurnal wave + flash crowds (sim/traffic.py), a 3-node crash landing in
the first crowd, an operator node drain mid-run, and tenant quota churn —
and runs it twice from the same seed: remediator ON vs OFF. The delta
between the runs' SLO error-budget trajectories is the loop's value
measured end-to-end, and the ledger ties every ON-run action back to its
trigger/diagnosis/simulation/effect chain (docs/observability.md
"Remediation & ledger").

Also provides the INERT pin: ``cluster_signature()`` hashes the store
population + bindings + node states, and ``inert_ab()`` replays the OFF
day with the remediator's tick physically sabotaged — byte-identical
signatures prove a disabled remediator contributes nothing (the PR-1
one-boolean-check discipline, A/B form).

Shared by ``make remediate-smoke`` (scripts/remediate_smoke.py), the
bench ``--integrated`` ``"remediation"`` block, and
tests/test_remediation.py.
"""

from __future__ import annotations

import hashlib
from typing import Callable, List, Optional, Tuple

from grove_tpu.api.meta import ObjectMeta
from grove_tpu.api.types import Queue, QueueSpec
from grove_tpu.observability.forecast import FORECASTER
from grove_tpu.observability.ledger import LEDGER
from grove_tpu.observability.slo import SLO
from grove_tpu.observability.timeseries import TIMESERIES
from grove_tpu.sim.traffic import (
    FAULT_NODES,
    ServingScenario,
    TrafficModel,
    default_slos,
)

# the objective whose error budget the effect measurements track (the
# cluster-health one — remediation aims at keeping serving ready)
EFFECT_SLO = "ready_fraction"


def cluster_signature(harness) -> str:
    """Deterministic digest of the world: every committed object's
    (kind, ns, name, rv, generation), the pod->node binding table, and
    each node's health/cordon state. Two runs that agree here made the
    same decisions at every step."""
    lines: List[str] = []
    store = harness.store
    for kind in sorted(store.kinds()):
        for obj in store.scan(kind):
            m = obj.metadata
            lines.append(
                f"{kind}|{m.namespace}|{m.name}|{m.resource_version}"
                f"|{m.generation}"
            )
    for (ns, pod), node in sorted(harness.cluster.bindings.items()):
        lines.append(f"bind|{ns}|{pod}|{node}")
    for n in harness.cluster.nodes:
        lines.append(
            f"node|{n.name}|{n.state}|{int(n.cordoned)}|{int(n.crashed)}"
        )
    lines.sort()
    return hashlib.sha256("\n".join(lines).encode()).hexdigest()


def _quota_churn(harness, tenants: List[str]) -> Tuple[Callable, Callable]:
    """Two fault callables: clamp the heaviest tenant's queue hard (scale
    churn piles into QueuePending), then relax it (the backlog floods
    back) — the quota stressor of the everything-at-once day."""

    def _clamp() -> None:
        harness.apply_queue(
            Queue(
                metadata=ObjectMeta(name=tenants[0], namespace=""),
                spec=QueueSpec(
                    deserved={"cpu": 2.0}, ceiling={"cpu": 3.0}
                ),
            )
        )

    def _relax() -> None:
        harness.apply_queue(
            Queue(
                metadata=ObjectMeta(name=tenants[0], namespace=""),
                spec=QueueSpec(
                    deserved={"cpu": 32.0}, ceiling={"cpu": 64.0}
                ),
            )
        )

    return _clamp, _relax


def remediation_day(
    seed: int = 2026,
    remediate: bool = False,
    tenants: int = 3,
    num_nodes: int = 24,
    duration: float = 1200.0,
    dt: float = 10.0,
    warm: bool = True,
    flightrec_dir: Optional[str] = None,
    sabotage_tick: bool = False,
) -> dict:
    """One seeded everything-at-once day; returns the run's report doc.

    ``remediate`` arms the controller (forecast scale-up policies per
    scaling group + burn-triggered defrag). ``sabotage_tick`` (OFF runs
    only) replaces the disabled remediator's tick with a tripwire — the
    inert A/B's proof that the disabled path is never consulted."""
    TIMESERIES.reset()
    SLO.reset()
    LEDGER.reset()
    FORECASTER.reset()
    tenant_names = [f"tenant-{i}" for i in range(tenants)]
    model = TrafficModel(seed, tenant_names, horizon=duration)
    scenario = ServingScenario(
        seed=seed,
        tenants=tenants,
        num_nodes=num_nodes,
        model=model,
        warm=warm,
    )
    h = scenario.harness
    from grove_tpu.observability.timeseries import install_serving_collector

    TIMESERIES.enable(clock=h.clock)
    SLO.enable()
    collector = install_serving_collector(
        h.store, scheduler=h.scheduler, clock=h.clock
    )
    for text in default_slos():
        SLO.add(text)
    # dense demand trace: the scenario only feeds traffic_demand once per
    # step, but converge's wake-jumps make steps sparse in virtual time —
    # the forecaster needs the diurnal shape at sampling resolution, so a
    # collector re-evaluates the (pure, seeded) model every sample round
    t_base = h.clock.now()

    def _demand_collector(now: float) -> None:
        rel = now - (scenario.t0 if scenario.t0 is not None else t_base)
        demands = model.demand(rel)
        for tenant in tenant_names:
            for role in ("prefill", "decode"):
                TIMESERIES.gauge(
                    f"traffic_demand/{tenant}/{role}",
                    demands[tenant][role],
                    vt=now,
                )

    TIMESERIES.add_collector(_demand_collector)
    # zero-violation gate (the chaos invariant-4 check, serving edition):
    # every sampling round, no PodCliqueSet may have more voluntarily-
    # disrupted gangs than its disruptionBudget allows — remediation acts
    # through broker grants, so an armed remediator must never move this
    budget_violations: List[str] = []

    def _budget_probe(now: float) -> None:
        for pcs in h.store.scan("PodCliqueSet"):
            budget = pcs.spec.template.disruption_budget
            if budget is None:
                continue
            key = (pcs.metadata.namespace, pcs.metadata.name)
            disrupted = h.disruption.voluntarily_disrupted_gangs(key)
            cap = budget.max_unavailable_gangs or 0
            if disrupted > cap:
                budget_violations.append(
                    f"t={now:.0f}s: PCS {key[0]}/{key[1]} has {disrupted}"
                    f" voluntarily-disrupted gang(s), budget allows {cap}"
                )

    TIMESERIES.add_collector(_budget_probe)
    # the new layers are armed in BOTH runs: ledger/forecaster writes only
    # happen on remediator calls, so arming them is part of the inertness
    # claim, not a confound
    LEDGER.enable(clock=h.clock)
    FORECASTER.enable(
        clock=h.clock, period=model.period, horizon=240.0, history=duration
    )
    watched = []
    for tenant in tenant_names:
        for role in ("prefill", "decode"):
            series = f"traffic_demand/{tenant}/{role}"
            FORECASTER.watch(series)
            watched.append(series)
    if flightrec_dir is not None:
        from grove_tpu.observability.flightrec import FLIGHTREC

        FLIGHTREC.enable(out_dir=flightrec_dir, clock=h.clock)
    if remediate:
        h.remediator.enable(
            effect_slo=EFFECT_SLO,
            effect_window=120.0,
            cooldown=90.0,
        )
        for tenant in tenant_names:
            for role in ("prefill", "decode"):
                h.remediator.add_scale_policy(
                    series=f"traffic_demand/{tenant}/{role}",
                    threshold=3.0,
                    kind="PodCliqueScalingGroup",
                    namespace=tenant,
                    name=f"serve-0-{role}",
                    max_replicas=8,
                )
    elif sabotage_tick:
        def _tripwire() -> int:  # pragma: no cover - must never run
            raise AssertionError(
                "disabled remediator was ticked — inertness broken"
            )

        h.remediator.tick = _tripwire
    # -- the everything-at-once fault schedule (run-relative vt) --------
    faults: List[Tuple[float, Callable[[], None]]] = []
    if scenario.model.crowds:
        crowd = scenario.model.crowds[0]
        victims = [n.name for n in h.cluster.nodes[:FAULT_NODES]]

        def _crash() -> None:
            for name in victims:
                h.cluster.crash_node(name)

        def _restore() -> None:
            for name in victims:
                h.cluster.restart_node(name)

        faults.append((crowd.start + 5.0, _crash))
        faults.append((crowd.start + crowd.duration, _restore))
    drain_node = h.cluster.nodes[-1].name
    faults.append(
        (duration * 0.35, lambda: h.drainer.request_drain(drain_node))
    )
    faults.append(
        (duration * 0.35 + 180.0, lambda: h.drainer.uncordon(drain_node))
    )
    clamp, relax = _quota_churn(h, tenant_names)
    faults.append((duration * 0.55, clamp))
    faults.append((duration * 0.75, relax))
    scenario.faults = sorted(faults, key=lambda f: f[0])
    scenario._fired = 0
    scenario.run(duration, dt=dt)
    # -- report ----------------------------------------------------------
    status = SLO.status()
    objectives = {
        row["name"]: {
            "attainment": row["attainment"],
            "budget_remaining": row["budget_remaining"],
            "state": row["state"],
            "breaches": row["breaches"],
            "recoveries": row["recoveries"],
        }
        for row in status["objectives"]
    }
    forecasts = {}
    for series in watched:
        fc = FORECASTER.forecast(series, now=h.clock.now())
        if fc.get("skill") is not None:
            forecasts[series] = {
                "mae": round(fc["mae"], 4),
                "persistence_mae": round(fc["persistence_mae"], 4),
                "skill": round(fc["skill"], 4),
            }
    ledger = LEDGER.status()
    doc = {
        "seed": seed,
        "remediate": remediate,
        "duration_vt_s": duration,
        "objectives": objectives,
        "budget_remaining": objectives.get(EFFECT_SLO, {}).get(
            "budget_remaining"
        ),
        "scale_ups": scenario.scale_ups,
        "scale_downs": scenario.scale_downs,
        "time_under_min_vt_s": round(scenario.time_under_min, 1),
        "forecast": forecasts,
        "ledger": {
            "recorded_total": ledger["recorded_total"],
            "executed": ledger["executed"],
            "skipped": ledger["skipped"],
            "flip_confirmed_rate": ledger["flip_confirmed_rate"],
            "mean_budget_delta": ledger["mean_budget_delta"],
            "by_kind": ledger["by_kind"],
        },
        "entries": ledger["entries"],
        "budget_violations": budget_violations,
        "signature": cluster_signature(h),
    }
    if flightrec_dir is not None:
        from grove_tpu.observability.flightrec import FLIGHTREC

        doc["flight_bundles"] = list(FLIGHTREC.dumps)
        FLIGHTREC.disable()
    SLO.disable()
    TIMESERIES.disable()
    TIMESERIES.remove_collector(collector)
    TIMESERIES.remove_collector(_demand_collector)
    TIMESERIES.remove_collector(_budget_probe)
    LEDGER.disable()
    FORECASTER.disable()
    h.remediator.disable()
    return doc


def remediation_artifact(
    seed: int = 2026,
    tenants: int = 3,
    num_nodes: int = 24,
    duration: float = 1200.0,
    dt: float = 10.0,
    warm: bool = True,
) -> dict:
    """The bench ``"remediation"`` block: the ON and OFF days from one
    seed, the on/off budget-recovery comparison, actions by kind, the
    flip-confirmed rate, and forecast skill vs the persistence baseline."""
    off = remediation_day(
        seed,
        remediate=False,
        tenants=tenants,
        num_nodes=num_nodes,
        duration=duration,
        dt=dt,
        warm=warm,
    )
    on = remediation_day(
        seed,
        remediate=True,
        tenants=tenants,
        num_nodes=num_nodes,
        duration=duration,
        dt=dt,
        warm=warm,
    )
    b_on = on.get("budget_remaining")
    b_off = off.get("budget_remaining")
    ratio = None
    if b_on is not None and b_off is not None:
        ratio = round((b_on + 1e-9) / (b_off + 1e-9), 4)
    skills = [f["skill"] for f in on["forecast"].values()]
    return {
        "seed": seed,
        "duration_vt_s": duration,
        "actions_by_kind": on["ledger"]["by_kind"],
        "executed": on["ledger"]["executed"],
        "skipped": on["ledger"]["skipped"],
        "flip_confirmed_rate": on["ledger"]["flip_confirmed_rate"],
        "mean_budget_delta": on["ledger"]["mean_budget_delta"],
        "forecast_skill_mean": (
            round(sum(skills) / len(skills), 4) if skills else None
        ),
        "forecast_beats_naive": bool(skills)
        and sum(skills) / len(skills) > 0.0,
        "budget_remaining_on": b_on,
        "budget_remaining_off": b_off,
        "budget_recovery_ratio": ratio,
        "disruption_budget_violations": len(on["budget_violations"])
        + len(off["budget_violations"]),
        "objectives_on": on["objectives"],
        "objectives_off": off["objectives"],
    }


def inert_ab(
    seed: int = 2026,
    tenants: int = 2,
    num_nodes: int = 12,
    duration: float = 300.0,
    dt: float = 10.0,
    warm: bool = False,
) -> Tuple[str, str]:
    """The inertness pin: the OFF day, then the OFF day again with the
    remediator's tick replaced by a tripwire. Returns both cluster
    signatures — byte-identical ⇔ the disabled path is never consulted
    and contributes nothing."""
    a = remediation_day(
        seed,
        remediate=False,
        tenants=tenants,
        num_nodes=num_nodes,
        duration=duration,
        dt=dt,
        warm=warm,
    )
    b = remediation_day(
        seed,
        remediate=False,
        tenants=tenants,
        num_nodes=num_nodes,
        duration=duration,
        dt=dt,
        warm=warm,
        sabotage_tick=True,
    )
    return a["signature"], b["signature"]
