"""Recovery scenario + WAL-overhead A/B (docs/robustness.md durability).

Two drivers share this module:

- ``scripts/recovery_smoke.py`` (`make recovery-smoke`): scripted
  crash-recover-converge run printing replayed records and recovery wall
  time, with hard correctness gates (acked prefix exact, recovered run
  converges to the pre-crash resource tree).
- ``bench.py --integrated`` embeds :func:`durability_artifact` as the
  ``"durability"`` block: WAL overhead %, recovery wall time, replay
  rate, and the inert-A/B verdict.

The A/B is the guard rail the acceptance bar pins: with durability
DISABLED the store path is byte-identical to an undurable run (same
commits, same resourceVersions, same converged tree); with it ENABLED
the only difference is files on disk plus bounded wall overhead.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import time
from typing import List, Optional

from grove_tpu.api.load import load_podcliquesets
from grove_tpu.api.meta import deep_copy
from grove_tpu.api.pod import is_ready
from grove_tpu.api.serialize import export_object
from grove_tpu.sim.harness import SimHarness

_WORKLOAD_YAML = """
apiVersion: grove.io/v1alpha1
kind: PodCliqueSet
metadata:
  name: svc
spec:
  replicas: 1
  template:
    cliques:
      - name: server
        spec:
          roleName: server
          replicas: 1
          podSpec:
            containers:
              - name: s
                image: busybox:stable
                resources:
                  requests:
                    cpu: 200m
      - name: worker
        spec:
          roleName: worker
          replicas: 2
          podSpec:
            containers:
              - name: w
                image: busybox:stable
                resources:
                  requests:
                    cpu: 100m
"""

_BASE = load_podcliquesets(_WORKLOAD_YAML)[0]


def _populate(h: SimHarness, n_sets: int) -> None:
    for i in range(n_sets):
        pcs = deep_copy(_BASE)
        pcs.metadata.name = f"svc-{i:04d}"
        h.apply(pcs)


def store_dump(
    store, canonical_uids: bool = False, include_events: bool = True
) -> dict:
    """Canonical wire dump of the whole committed population — the
    byte-comparable store state the inert A/B and the recovery round trip
    are judged on. ``canonical_uids`` renumbers uids positionally (sorted
    key order) so two runs in ONE process — whose uid counter is global —
    still compare equal when everything else is identical.
    ``include_events=False`` drops fire-and-forget Event objects, which
    are outside the durability contract (real etcd TTLs them away)."""
    out = {}
    for kind in store.kinds():
        if kind == "Event" and not include_events:
            continue
        for obj in store.scan(kind):
            key = f"{kind}/{obj.metadata.namespace}/{obj.metadata.name}"
            out[key] = export_object(obj)
    if canonical_uids:
        mapping = {}
        for key in sorted(out):
            uid = out[key].get("metadata", {}).get("uid")
            if uid and uid not in mapping:
                mapping[uid] = f"uid-canonical-{len(mapping)}"
        for doc in out.values():
            meta = doc.get("metadata", {})
            if meta.get("uid") in mapping:
                meta["uid"] = mapping[meta["uid"]]
            for ref in meta.get("ownerReferences", []) or []:
                if ref.get("uid") in mapping:
                    ref["uid"] = mapping[ref["uid"]]
    return out


def _converged_run(
    n_sets: int, num_nodes: int, durability_dir: Optional[str]
) -> tuple:
    t0 = time.perf_counter()
    h = SimHarness(num_nodes=num_nodes, durability_dir=durability_dir)
    _populate(h, n_sets)
    h.converge(max_ticks=60 + 8 * n_sets)
    wall = time.perf_counter() - t0
    return h, wall


def wal_overhead_ab(n_sets: int = 64, num_nodes: int = 64) -> dict:
    """Identical workload twice — durability off (A) vs on (B). Returns
    the wall overhead and whether the A/B stayed inert (same converged
    tree, same resourceVersion: the WAL must observe, never steer).

    A small UNTIMED warmup run goes first (the first converge in a
    process pays jax/controller import-and-compile costs), and each arm
    takes the better of two runs: per-process allocator/cache state
    drifts across multi-second converges, and a single sample per arm
    misreads that drift as WAL cost."""
    from grove_tpu.observability.metrics import METRICS

    warm, _ = _converged_run(min(n_sets, 8), min(num_nodes, 8), None)
    del warm
    h_a, wall_a = _converged_run(n_sets, num_nodes, None)
    wal_dir = tempfile.mkdtemp(prefix="grove-wal-ab-")
    try:
        flush_before = METRICS.hist_sum.get("wal_flush_seconds", 0.0)
        h_b, wall_b = _converged_run(n_sets, num_nodes, wal_dir)
        wal_cpu = METRICS.hist_sum.get("wal_flush_seconds", 0.0) - flush_before
        stats = h_b.durability.stats()
        inert = (
            store_dump(h_a.store, canonical_uids=True)
            == store_dump(h_b.store, canonical_uids=True)
            and h_a.store.resource_version == h_b.store.resource_version
        )
        h_b.durability.close()
        del h_b
        _h_a2, wall_a2 = _converged_run(n_sets, num_nodes, None)
        del _h_a2
        wal_dir2 = tempfile.mkdtemp(prefix="grove-wal-ab-")
        try:
            h_b2, wall_b2 = _converged_run(n_sets, num_nodes, wal_dir2)
            h_b2.durability.close()
            del h_b2
        finally:
            shutil.rmtree(wal_dir2, ignore_errors=True)
        wall_a = min(wall_a, wall_a2)
        wall_b = min(wall_b, wall_b2)
    finally:
        shutil.rmtree(wal_dir, ignore_errors=True)
    return {
        "sets": n_sets,
        "nodes": num_nodes,
        "wall_off_s": round(wall_a, 3),
        "wall_on_s": round(wall_b, 3),
        # primary overhead figure: the WAL's measured group-commit cost as
        # a share of the SAME run's wall (same-run ratio — stable under
        # machine-load drift that makes cross-run A/B deltas noisy)
        "overhead_pct": round(100.0 * wal_cpu / wall_b, 2),
        "wal_cpu_seconds": round(wal_cpu, 3),
        # cross-run A/B delta, best-of-two per arm (reference figure)
        "overhead_ab_pct": round(100.0 * (wall_b - wall_a) / wall_a, 2),
        "inert_ab_identical": inert,
        "wal_records": stats["flushed_records"],
        "wal_bytes": stats["flushed_bytes"],
        "wal_snapshots": stats["snapshots_taken"],
    }


def recovery_scenario(
    n_sets: int = 64, num_nodes: int = 64, torn_tail: bool = True
) -> dict:
    """Crash-recover-converge: converge a durable population, kill the
    store process (torn tail on disk), recover from the WAL/snapshot,
    audit the acked prefix, cold-boot a control plane over the recovered
    store, and require it to converge back to the pre-crash tree."""
    from grove_tpu.durability import recover_store, verify_acked_prefix
    from grove_tpu.sim.chaos import resource_signature

    wal_dir = tempfile.mkdtemp(prefix="grove-recovery-")
    problems: List[str] = []
    try:
        # two phases around an explicit snapshot, so recovery exercises
        # BOTH halves of the path: snapshot base + WAL-tail replay
        h = SimHarness(num_nodes=num_nodes, durability_dir=wal_dir)
        _populate(h, n_sets // 2)
        h.converge(max_ticks=60 + 8 * n_sets)
        h.durability.snapshot()
        for i in range(n_sets // 2, n_sets):
            pcs = deep_copy(_BASE)
            pcs.metadata.name = f"svc-{i:04d}"
            h.apply(pcs)
        h.converge(max_ticks=60 + 8 * n_sets)
        pre_sig = resource_signature(h.store)
        pre_dump = store_dump(h.store, include_events=False)
        acked_rv = h.durability.wal.durable_rv
        lost = h.durability.simulate_crash(
            torn_tail_bytes=53 if torn_tail else 0
        )
        store, report = recover_store(wal_dir, clock=h.clock, cache_lag=True)
        problems.extend(verify_acked_prefix(wal_dir, store))
        if store.resource_version < acked_rv:
            problems.append(
                f"recovered rv {store.resource_version} behind the acked"
                f" watermark {acked_rv}"
            )
        # the crash hit a converged, fully-flushed store: recovery must be
        # a perfect round trip, not merely prefix-consistent (modulo
        # fire-and-forget Events, which are outside the contract)
        if store_dump(store, include_events=False) != pre_dump:
            problems.append(
                "recovered store differs from the pre-crash committed"
                " state (wire-dump mismatch)"
            )
        restarted = SimHarness.cold_restart(
            store, h.cluster.nodes, config=h.config, durability_dir=wal_dir
        )
        t0 = time.perf_counter()
        restarted.converge(max_ticks=60 + 8 * n_sets)
        reconverge_wall = time.perf_counter() - t0
        pods = restarted.store.list("Pod")
        if not pods or not all(is_ready(p) for p in pods):
            problems.append("recovered run did not converge to all-Ready")
        if resource_signature(restarted.store) != pre_sig:
            problems.append(
                "recovered run's resource tree differs from pre-crash"
            )
        segments = len(
            [f for f in os.listdir(wal_dir) if f.startswith("wal-")]
        )
        restarted.durability.close()
    finally:
        shutil.rmtree(wal_dir, ignore_errors=True)
    doc = report.as_dict()
    doc.update(
        {
            "sets": n_sets,
            "nodes": num_nodes,
            "acked_rv_at_crash": acked_rv,
            "lost_unacked_records": lost,
            "reconverge_wall_s": round(reconverge_wall, 3),
            "segments_after_recovery": segments,
            "problems": problems,
            "ok": not problems,
        }
    )
    return doc


def durability_artifact(n_sets: int = 192, num_nodes: int = 192) -> dict:
    """Compact durability block for the integrated bench artifact. The
    shape is large enough that the overhead ratio measures steady-state
    per-record cost, not per-run fixed costs."""
    ab = wal_overhead_ab(n_sets=n_sets, num_nodes=num_nodes)
    rec = recovery_scenario(n_sets=n_sets, num_nodes=num_nodes)
    return {
        "overhead_pct": ab["overhead_pct"],
        "overhead_ab_pct": ab["overhead_ab_pct"],
        "wal_cpu_seconds": ab["wal_cpu_seconds"],
        "inert_ab_identical": ab["inert_ab_identical"],
        "wal_records": ab["wal_records"],
        "wal_bytes": ab["wal_bytes"],
        "wal_snapshots": ab["wal_snapshots"],
        "recovery_wall_s": rec["wall_seconds"],
        "replayed_records": rec["replayed_records"],
        "replay_records_per_sec": rec["replay_records_per_sec"],
        "torn_tail_truncated": rec["torn_tail"],
        "recovery_ok": rec["ok"],
    }
