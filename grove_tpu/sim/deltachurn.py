"""Steady-state churn loop for the incremental delta-solve
(solver/deltastate.py, docs/solver.md "Incremental delta-solve").

Two drivers share this module:

- ``scripts/delta_smoke.py`` (`make delta-smoke`): a seeded churn loop at
  smoke scale with the per-tick A/B selfcheck armed EVERY tick (the delta
  problem and admissions must be bit-identical to a from-scratch encode +
  full solve, or the run raises), counters checked against floors, plus a
  run-level A/B — the same seeded storm with delta-solve disabled must
  converge to identical bindings and gang phases.
- ``bench.py --integrated`` embeds :func:`delta_artifact` as the
  ``"delta"`` block, riding the already-converged bench harness so the
  churn runs at the REAL 10k-gang × 5k-node shape: steady-state schedule
  p50/p99, re-encode fraction, warm-start hit rate, solve reuses,
  full-solve fallback count, drift count (must be 0), the sampled A/B
  verdict, and a from-scratch comparison segment on the same harness.

The churn mix is the production steady state the tentpole targets: a few
gangs arrive, a few depart, pods fail and get recreated, a node
occasionally flaps out of and back into the schedulable set (the
topology-change full-fallback path). All of it is driven by one seeded RNG
and a fixed tick count, so a replay with the same seed is deterministic —
which is what makes the run-level delta-on/off A/B meaningful.
"""

from __future__ import annotations

import random
import time
from typing import List, Optional

from grove_tpu.api.load import load_podcliquesets
from grove_tpu.api.meta import deep_copy

# small standalone gang — the dominant shape of the integrated bench mix,
# cheap enough that arrivals never overcommit the smoke cluster
_CHURN_YAML = """
apiVersion: grove.io/v1alpha1
kind: PodCliqueSet
metadata:
  name: churn
spec:
  replicas: 1
  template:
    cliques:
      - name: server
        spec:
          roleName: role-server
          replicas: 1
          podSpec:
            containers:
              - name: s
                image: busybox:stable
                resources:
                  requests:
                    cpu: 10m
      - name: worker
        spec:
          roleName: role-worker
          replicas: 2
          podSpec:
            containers:
              - name: w
                image: busybox:stable
                resources:
                  requests:
                    cpu: 10m
"""

_CHURN_BASE = load_podcliquesets(_CHURN_YAML)[0]


def _percentile(samples: List[float], q: float) -> float:
    """Nearest-rank percentile (the bench's tail-honesty convention: never
    report an interpolated value below an observed one)."""
    if not samples:
        return 0.0
    s = sorted(samples)
    k = min(len(s) - 1, max(0, int(round(q * (len(s) - 1) + 0.5)) - 1))
    return s[max(k, int(q * (len(s) - 1)))]


def _tick(h, timings: Optional[List[float]] = None) -> None:
    """One harness tick, converge-shaped, with the scheduler slice timed
    separately — the churn p99 is the ADMISSION hot path's latency, not
    the kubelet's or the reconcilers'."""
    h.engine.drain()
    h.autoscaler.tick()
    h.node_monitor.tick()
    h.drainer.tick()
    t0 = time.perf_counter()
    h.schedule()
    if timings is not None:
        timings.append(time.perf_counter() - t0)
    h.cluster.kubelet_tick()
    h.engine.drain()
    if h.durability is not None:
        h.durability.pump()
    h.clock.advance(1.0)


def churn_loop(
    h,
    ticks: int = 64,
    seed: int = 8,
    selfcheck_every: int = 0,
    flap_every: int = 24,
    namespace: str = "default",
) -> dict:
    """Run a seeded steady-state churn storm on a (converged) harness and
    report the delta-solve counters + schedule-latency percentiles.

    ``selfcheck_every`` > 0 arms the scheduler's ``delta_selfcheck`` A/B on
    every n-th tick (1 = every tick, the smoke's setting): those ticks
    re-derive the problem from scratch and assert problem tensors AND
    solve results are bit-identical, raising on any divergence.

    Also runs with ``sched.delta`` detached (the run-level A/B's control
    leg): the storm replays identically — same rng, same ops — and the
    delta counters are simply absent from the report.
    """
    sched = h.scheduler
    d = sched.delta
    rng = random.Random(seed)
    base = {
        "warm": d.warm_start_hits if d else 0,
        "reuse": d.solve_reuses if d else 0,
        "fallback": d.full_fallbacks if d else 0,
        "drift": d.drift_detected if d else 0,
    }
    ops = {"arrivals": 0, "departures": 0, "pod_fails": 0, "flaps": 0}
    live: List[str] = []  # churn-created sets, oldest first
    timings: List[float] = []
    reencoded = reused = ab_ticks = 0
    ab_seconds = 0.0
    flapped: Optional[str] = None
    prev_selfcheck = sched.delta_selfcheck
    try:
        for i in range(ticks):
            roll = rng.random()
            if roll < 0.45:
                for _ in range(rng.randrange(1, 3)):
                    pcs = deep_copy(_CHURN_BASE)
                    pcs.metadata.name = f"churn-{seed}-{ops['arrivals']:04d}"
                    h.apply(pcs)
                    live.append(pcs.metadata.name)
                    ops["arrivals"] += 1
            elif roll < 0.65 and live:
                h.delete(live.pop(0), namespace)
                ops["departures"] += 1
            elif roll < 0.8 and h.cluster.bindings:
                # kill a bound pod (recreate + re-admission churn); the
                # bindings map is the cheap authority for who is bound
                keys = list(h.cluster.bindings)
                ns, name = keys[rng.randrange(len(keys))]
                h.cluster.fail_pod(ns, name)
                ops["pod_fails"] += 1
            if flap_every and i and i % flap_every == 0:
                # node flap via cordon toggle: leaves and re-enters the
                # schedulable set → two topology-change full fallbacks
                if flapped is None:
                    node = h.cluster.nodes[
                        rng.randrange(len(h.cluster.nodes))
                    ]
                    node.cordoned = True
                    flapped = node.name
                else:
                    for node in h.cluster.nodes:
                        if node.name == flapped:
                            node.cordoned = False
                    flapped = None
                ops["flaps"] += 1
            if selfcheck_every and d is not None:
                sched.delta_selfcheck = i % selfcheck_every == 0
                ab_ticks += int(sched.delta_selfcheck)
            sched.last_selfcheck_seconds = 0.0
            _tick(h, timings)
            # the A/B selfcheck re-derives the whole problem from scratch
            # and re-runs the full solve INSIDE schedule() — a verification
            # harness, never on in production. Charge it to its own ledger,
            # not the admission path's latency.
            ab_seconds += sched.last_selfcheck_seconds
            timings[-1] = max(
                0.0, timings[-1] - sched.last_selfcheck_seconds
            )
            if d is not None:
                reencoded += d.last_reencoded
                reused += d.last_reused
    finally:
        sched.delta_selfcheck = prev_selfcheck
        if flapped is not None:
            for node in h.cluster.nodes:
                if node.name == flapped:
                    node.cordoned = False
    report = {
        "ticks": ticks,
        "seed": seed,
        "ops": ops,
        "schedule_p50_ms": round(_percentile(timings, 0.5) * 1e3, 1),
        "schedule_p99_ms": round(_percentile(timings, 0.99) * 1e3, 1),
        "schedule_mean_ms": round(sum(timings) / len(timings) * 1e3, 1),
        "schedule_max_ms": round(max(timings) * 1e3, 1),
    }
    if d is not None:
        encodes = reencoded + reused
        report.update(
            {
                "spec_encodes": encodes,
                "reencode_fraction": round(reencoded / max(encodes, 1), 4),
                "warm_start_hit_rate": round(reused / max(encodes, 1), 4),
                "warm_start_hits": d.warm_start_hits - base["warm"],
                "solve_reuses": d.solve_reuses - base["reuse"],
                "full_fallbacks": d.full_fallbacks - base["fallback"],
                "drift_detected": d.drift_detected - base["drift"],
                "ab_ticks": ab_ticks,
                "ab_overhead_ms": round(ab_seconds * 1e3, 1),
                "ab_ok": True,  # a failing A/B raises out of churn_loop
            }
        )
    return report


def fullpath_comparison(h, ticks: int = 32, seed: int = 9) -> dict:
    """Comparison segment: the SAME seeded churn mix on the same harness
    with the delta state detached — every tick pays the from-scratch
    bindings repass + node re-encode — so the artifact carries a
    same-process, same-shape, same-storm measurement of what each
    steady-state tick used to cost."""
    sched = h.scheduler
    d, last = sched.delta, sched._delta_last
    sched.delta, sched._delta_last = None, None
    try:
        report = churn_loop(
            h, ticks=ticks, seed=seed, selfcheck_every=0, flap_every=0
        )
    finally:
        sched.delta = d
        if d is not None:
            # the detached segment's binding churn was still folded (the
            # state stays subscribed), but make the resumption airtight:
            # re-derive everything on the next delta tick
            d.invalidate(reason="fullpath-comparison")
        sched._delta_last = last
    return {
        "ticks": ticks,
        "schedule_p50_ms": report["schedule_p50_ms"],
        "schedule_p99_ms": report["schedule_p99_ms"],
        "schedule_mean_ms": report["schedule_mean_ms"],
    }


def compile_warmup(h, namespace: str = "default") -> dict:
    """Pre-compile the steady-state solve shapes before measurement: the
    churn-sized gang bucket at N schedulable nodes AND at N-1 (a flap's
    cordon shrinks the node axis by one, and the node axis is not padded —
    any single cordon lands on the same N-1 compiled shape regardless of
    which node flapped). XLA compiles each shape once per process; a
    steady-state latency measurement that bills a cold compile to one
    arbitrary tick is measuring process warmup, not the admission path.
    The warmup gangs are deleted and drained before returning, so the
    measured population is exactly the caller's."""
    t0 = time.perf_counter()
    names = []
    serial = 0

    def arrive(count: int) -> None:
        nonlocal serial
        for _ in range(count):
            pcs = deep_copy(_CHURN_BASE)
            pcs.metadata.name = f"deltawarm-{serial}"
            serial += 1
            h.apply(pcs)
            names.append(pcs.metadata.name)
        _tick(h)

    # the churn's per-tick pending set is 1-2 fresh gangs: solve both
    # gang buckets at N, then both again while one node is cordoned (N-1)
    arrive(1)
    arrive(2)
    h.cluster.nodes[0].cordoned = True
    arrive(1)
    arrive(2)
    h.cluster.nodes[0].cordoned = False
    _tick(h)
    for name in names:
        h.delete(name, namespace)
    for _ in range(4):
        _tick(h)
    return {"wall_ms": round((time.perf_counter() - t0) * 1e3, 1)}


def delta_artifact(h, ticks: int = 96, seed: int = 8) -> dict:
    """The bench ``"delta"`` block, run on the ALREADY-CONVERGED integrated
    harness (the real 10k-gang × 5k-node steady state): a compile warmup,
    seeded churn with the A/B selfcheck sampled every 16th tick, then the
    from-scratch comparison segment. The acceptance gate is ``p99_lt_1s``
    on the delta path's schedule latency."""
    # same GC discipline as the converge measurement (bench.py
    # _run_population_bench): the store population is large, long-lived,
    # and acyclic — churned objects free promptly by refcount, while a
    # cyclic full collection scans the whole live heap and can land a
    # multi-second pause on one arbitrary tick of the percentile window
    import gc

    gc.collect()
    gc.freeze()
    gc.disable()
    try:
        warmup = compile_warmup(h)
        report = churn_loop(
            h, ticks=ticks, seed=seed, selfcheck_every=16, flap_every=32
        )
        report["full_path"] = fullpath_comparison(h, ticks=32, seed=seed + 1)
    finally:
        gc.enable()
        gc.unfreeze()
        gc.collect()
    report["warmup"] = warmup
    report["p99_lt_1s"] = report["schedule_p99_ms"] < 1000.0
    # mean, not p50: the two segments draw different tick counts from the
    # same storm distribution, and a median just reports which tick TYPE
    # (light vs solve-bearing) straddles the 50th slot of each sample —
    # the mean is composition-honest across segment lengths
    report["speedup_mean"] = round(
        report["full_path"]["schedule_mean_ms"]
        / max(report["schedule_mean_ms"], 0.1),
        2,
    )
    return report


def smoke_ab_run(seed: int, enable_delta: bool, ticks: int = 36) -> tuple:
    """Run-level A/B leg: one seeded storm from a fresh harness; returns
    (bindings, gang phases) — the two legs must be identical, the
    scheduler-level 'delta-solve admissions bit-identical to the full
    solve' acceptance pin at smoke speed."""
    from grove_tpu.sim.harness import SimHarness

    from grove_tpu.models import load_sample

    h = SimHarness(num_nodes=12)
    if not enable_delta:
        h.scheduler.delta = None  # from-scratch control leg
    for i in range(6):
        pcs = deep_copy(_CHURN_BASE)
        pcs.metadata.name = f"seed-{i}"
        h.apply(pcs)
    for i in range(2):
        # standing pending backlog (unplaceable at 12 nodes): keeps real
        # solves running every tick on both legs
        pcs = deep_copy(load_sample("multinode_disaggregated"))
        pcs.metadata.name = f"backlog-{i}"
        h.apply(pcs)
    h.converge(max_ticks=30)
    churn_loop(h, ticks=ticks, seed=seed, selfcheck_every=1)
    h.converge(max_ticks=60)
    bindings = dict(h.cluster.bindings)
    phases = {
        g.metadata.name: g.status.phase
        for g in h.store.list("PodGang", "default")
    }
    return bindings, phases
