"""Parallel-control-plane scenarios: serial-twin A/B + worker sweep.

The determinism contract of the concurrent reconcile workers
(runtime/workers.py, docs/control-plane.md §5) is pinned the way
``delta_selfcheck`` pins the incremental solve: run the SAME event
schedule through the serial drain and the worker drain, and assert the
two control planes are indistinguishable —

- **identical admissions + store content**: canonical-uid wire dumps
  (Events excluded — their evt-N name assignment races by design and
  they are outside the durability contract; per-object resourceVersions
  normalized exactly as the sharded inert A/B does, since Event commits
  interleave into their shard's rv sequence non-deterministically);
- **identical reconcile counts** per converge boundary;
- **identical scalar resourceVersion** (total commit count — Event
  creations included, so a racy lost Event would surface here);
- **identical per-shard WAL acked prefixes**: each shard's durable
  key → envelope state (rv-normalized) and logged record counts.

``parallel_ab`` drives both twins in LOCKSTEP through a seeded
cross-shard event storm (apply / scale / delete / re-apply churn across
tenant namespaces) and compares at EVERY converge boundary, not just at
the end — an ordering divergence that a later converge would wash out
still fails. ``worker_sweep`` is the smoke/bench measurement arm:
one population converged at worker counts 1/2/4/8 with µs/reconcile
and speedup reported (honestly: on GIL builds the sweep demonstrates
bounded overhead; free-threaded builds are where the ownership
boundaries pay out).
"""

from __future__ import annotations

import gc
import json
import time
from typing import Callable, Dict, List, Optional, Tuple

from grove_tpu.api.meta import deep_copy
from grove_tpu.observability.metrics import METRICS
from grove_tpu.runtime.clock import VirtualClock
from grove_tpu.runtime.store import Store
from grove_tpu.sim.harness import SimHarness
from grove_tpu.sim.scale import (
    _BASE,
    _populate,
    _reconcile_count,
    _rv_normalized,
    tenant_namespaces,
)


def _make_harness(
    n_nodes: int,
    num_shards: int,
    workers: int,
    durability_dir: Optional[str] = None,
    backend: str = "thread",
) -> SimHarness:
    """Harness with EXACTLY `workers` drain lanes (1 = the serial drain).

    The engine auto-arms from GROVE_TPU_CP_WORKERS at construction — the
    very opt-in these scenarios exist to validate — so an inherited env
    arming is explicitly torn down: the serial twin must actually be
    serial and each sweep arm must run its labeled worker count, or the
    A/B compares parallel-vs-parallel and the sweep table is fiction."""
    store = Store(VirtualClock(), cache_lag=True, num_shards=num_shards)
    h = SimHarness(
        num_nodes=n_nodes, store=store, durability_dir=durability_dir
    )
    if h.engine.workers is not None and (
        workers <= 1
        or h.engine.workers.workers != workers
        or h.engine.workers.backend != backend
    ):
        h.engine.close()  # drop the env-armed pool (enable_workers below
        # re-arms fresh when this scenario wants a different count/backend)
    if workers > 1 and h.engine.workers is None:
        armed = h.engine.enable_workers(workers, backend=backend)
        assert armed, "worker arming requires a sharded in-memory store"
    return h


def _dump(h: SimHarness) -> dict:
    from grove_tpu.sim.recovery import store_dump

    return _rv_normalized(
        store_dump(h.store, canonical_uids=True, include_events=False)
    )


def _converge_counted(h: SimHarness, max_ticks: int) -> Tuple[int, int]:
    """(reconciles, ticks) for one converge of one harness (the METRICS
    counter is process-global — the twins run strictly in turn)."""
    r0 = _reconcile_count()
    ticks = h.converge(max_ticks=max_ticks)
    return _reconcile_count() - r0, ticks


def durable_state_normalized(wal_dir: str) -> Dict[int, dict]:
    """Per-shard durable prefix as {shard: {key: envelope-minus-rv}} —
    the WAL half of the serial-twin comparison. resourceVersions are
    stripped for the same reason the store dump normalizes them: Event
    commits (unlogged, best-effort) interleave into a shard's rv
    sequence differently under workers, while the DURABLE CONTENT must
    match exactly."""
    import json as _json

    from grove_tpu.durability.wal import _iter_durable_state, list_shard_dirs

    # sharded layout: one stream per shard-NNN dir; unsharded: the dir
    # itself is shard 0's stream (the legacy layout)
    streams = list_shard_dirs(wal_dir) or [(0, wal_dir)]
    out: Dict[int, dict] = {}
    for shard_index, directory in streams:
        state = {}
        for key, env in _iter_durable_state(directory):
            if env is None:
                state["/".join(key)] = None
                continue
            # private normalizable copy (envelopes are JSON by
            # construction; json round-trip instead of deepcopy keeps
            # GL004's no-deepcopy discipline trivially visible)
            env = _json.loads(_json.dumps(env))
            env.pop("rv", None)
            env.get("obj", {}).get("metadata", {}).pop(
                "resourceVersion", None
            )
            state["/".join(key)] = env
        out[shard_index] = state
    # canonical uids, exactly like store_dump(canonical_uids=True): the
    # twins share one process-global uid counter and allocate in a
    # different interleave under workers — identity is positional
    mapping: Dict[str, str] = {}
    for shard_index in sorted(out):
        for key in sorted(out[shard_index]):
            env = out[shard_index][key]
            if env is None:
                continue
            uid = env.get("obj", {}).get("metadata", {}).get("uid")
            if uid and uid not in mapping:
                mapping[uid] = f"uid-canonical-{len(mapping)}"
    for state in out.values():
        for env in state.values():
            if env is None:
                continue
            meta = env.get("obj", {}).get("metadata", {})
            if meta.get("uid") in mapping:
                meta["uid"] = mapping[meta["uid"]]
            for ref in meta.get("ownerReferences", []) or []:
                if ref.get("uid") in mapping:
                    ref["uid"] = mapping[ref["uid"]]
    return out


# ---------------------------------------------------------------------------
# seeded cross-shard event storm (the lockstep schedule both twins replay)
# ---------------------------------------------------------------------------


def storm_steps(
    seed: int, n_sets: int, n_tenants: int, rounds: int = 4
) -> List[Callable[[SimHarness], None]]:
    """Deterministic mutation schedule: each step is a pure function of
    (seed, step index) applied identically to both twins — scale-ups,
    deletions, re-applies and replica churn spread across tenant
    namespaces so every round exercises cross-shard interleavings."""
    import random

    rng = random.Random(seed)
    tenants = tenant_namespaces(n_tenants)
    live = {
        (f"svc-{i:06d}", tenants[i % len(tenants)]) for i in range(n_sets)
    }
    steps: List[Callable[[SimHarness], None]] = []
    next_id = n_sets
    for _ in range(rounds):
        ordered = sorted(live)
        victims = rng.sample(ordered, k=max(1, len(ordered) // 6))
        adds = [
            (f"svc-{next_id + j:06d}", tenants[(next_id + j) % len(tenants)])
            for j in range(max(1, len(ordered) // 8))
        ]
        next_id += len(adds)
        survivors = [s for s in ordered if s not in set(victims)]
        scale = rng.sample(
            survivors, k=max(1, len(survivors) // 8)
        ) if survivors else []
        new_replicas = rng.choice([2, 3])

        def step(
            h: SimHarness,
            _victims=tuple(victims),
            _adds=tuple(adds),
            _scale=tuple(scale),
            _replicas=new_replicas,
        ) -> None:
            for name, ns in _victims:
                h.delete(name, namespace=ns)
            for name, ns in _adds:
                pcs = deep_copy(_BASE)
                pcs.metadata.name = name
                pcs.metadata.namespace = ns
                h.apply(pcs)
            for name, ns in _scale:
                # through the sanctioned apply path (defaulting +
                # update validation), exactly like a user scale-out of
                # the SET replica axis (clique template fields are
                # immutable post-create)
                pcs = deep_copy(_BASE)
                pcs.metadata.name = name
                pcs.metadata.namespace = ns
                pcs.spec.replicas = _replicas
                h.apply(pcs)

        steps.append(step)
        live -= set(victims)
        live |= set(adds)
    return steps


# ---------------------------------------------------------------------------
# serial-twin A/B
# ---------------------------------------------------------------------------


def parallel_ab(
    n_sets: int = 48,
    n_nodes: int = 32,
    num_shards: int = 4,
    workers: int = 4,
    seed: int = 1234,
    n_tenants: int = 8,
    storm_rounds: int = 3,
    wal_dirs: Optional[Tuple[str, str]] = None,
    max_ticks: Optional[int] = None,
    backend: str = "thread",
) -> dict:
    """Lockstep serial-vs-workers twin run; compares at EVERY converge
    boundary. Returns the report; ``problems`` empty ⇔ bit-identical.

    ``backend`` picks the worker twin's executor ("thread" |
    "process") — the serial twin is always the single-threaded drain,
    so one scenario pins BOTH executors to the same contract.

    ``wal_dirs=(serial_dir, workers_dir)`` additionally attaches
    per-shard WAL streams to both twins and compares the durable acked
    prefixes shard by shard after the final converge."""
    ticks = max_ticks or (60 + 8 * n_sets)
    serial = _make_harness(
        n_nodes, num_shards, 1, wal_dirs[0] if wal_dirs else None
    )
    parallel = _make_harness(
        n_nodes,
        num_shards,
        workers,
        wal_dirs[1] if wal_dirs else None,
        backend=backend,
    )
    tenants = tenant_namespaces(n_tenants)
    problems: List[str] = []
    boundaries = 0

    def compare(label: str) -> None:
        nonlocal boundaries
        boundaries += 1
        ds, dp = _dump(serial), _dump(parallel)
        if ds != dp:
            keys = sorted(
                k for k in set(ds) | set(dp) if ds.get(k) != dp.get(k)
            )
            detail = []
            for k in keys[:2]:
                a = json.dumps(ds.get(k), sort_keys=True)
                b = json.dumps(dp.get(k), sort_keys=True)
                off = next(
                    (
                        i
                        for i in range(min(len(a), len(b)))
                        if a[i] != b[i]
                    ),
                    min(len(a), len(b)),
                )
                detail.append(
                    f"{k}: serial[...{a[max(0, off - 60):off + 90]}...]"
                    f" vs parallel[...{b[max(0, off - 60):off + 90]}...]"
                )
            problems.append(
                f"{label}: store content diverged on {len(keys)} key(s):"
                f" {'; '.join(detail)}"
            )
        if (
            serial.store.resource_version
            != parallel.store.resource_version
        ):
            problems.append(
                f"{label}: scalar resourceVersion diverged"
                f" ({serial.store.resource_version} vs"
                f" {parallel.store.resource_version})"
            )

    _populate(serial, n_sets, tenants)
    _populate(parallel, n_sets, tenants)
    r_serial, _ = _converge_counted(serial, ticks)
    r_parallel, _ = _converge_counted(parallel, ticks)
    if r_serial != r_parallel:
        problems.append(
            f"initial converge: reconcile counts diverged"
            f" ({r_serial} vs {r_parallel})"
        )
    compare("initial converge")
    reconciles = [(r_serial, r_parallel)]
    for i, step in enumerate(
        storm_steps(seed, n_sets, n_tenants, rounds=storm_rounds)
    ):
        step(serial)
        step(parallel)
        r_serial, _ = _converge_counted(serial, ticks)
        r_parallel, _ = _converge_counted(parallel, ticks)
        if r_serial != r_parallel:
            problems.append(
                f"storm step {i}: reconcile counts diverged"
                f" ({r_serial} vs {r_parallel})"
            )
        compare(f"storm step {i}")
        reconciles.append((r_serial, r_parallel))
    wal_identical = None
    if wal_dirs is not None:
        serial.durability.pump()
        parallel.durability.pump()
        acked_serial = durable_state_normalized(wal_dirs[0])
        acked_parallel = durable_state_normalized(wal_dirs[1])
        wal_identical = acked_serial == acked_parallel
        if not wal_identical:
            problems.append("per-shard WAL acked prefixes diverged")
    worker_stats = (
        parallel.engine.workers.stats()
        if parallel.engine.workers is not None
        else {}
    )
    serial.engine.close()
    parallel.engine.close()
    return {
        "sets": n_sets,
        "shards": num_shards,
        "workers": workers,
        "backend": backend,
        "seed": seed,
        "boundaries_compared": boundaries,
        "reconciles": reconciles,
        "identical": not problems,
        "problems": problems,
        "wal_acked_identical": wal_identical,
        "worker_stats": worker_stats,
    }


# ---------------------------------------------------------------------------
# worker sweep (the measurement arm)
# ---------------------------------------------------------------------------


def worker_sweep(
    n_sets: int = 192,
    n_nodes: int = 64,
    num_shards: int = 8,
    worker_counts: Tuple[int, ...] = (1, 2, 4, 8),
    backend: str = "thread",
) -> dict:
    """One population converged per worker count; µs/reconcile + speedup
    vs the serial arm. A throwaway warmup converge absorbs the solver's
    XLA compile so the sweep measures control-plane work — AT the
    measured node count: the chunk kernel compiles per (chunk, nodes)
    shape, so a smaller warmup would bill the compile to whichever arm
    runs first (the serial one) and fabricate speedup. GC discipline
    matches the scale bench (freeze/disable across the measured wall)."""
    tenants = tenant_namespaces(min(16, n_sets))
    _warm = _make_harness(n_nodes, num_shards, 1)
    _populate(_warm, n_sets, tenants)
    _warm.converge(max_ticks=60 + 8 * n_sets)
    _warm.engine.close()
    del _warm
    gc.collect()
    rows = []
    base_wall = None
    for workers in worker_counts:
        h = _make_harness(n_nodes, num_shards, workers, backend=backend)
        solver0 = METRICS.hist_sum.get("gang_solve_seconds", 0.0)
        r0 = _reconcile_count()
        gc.collect()
        gc.freeze()
        gc.disable()
        try:
            t0 = time.perf_counter()
            _populate(h, n_sets, tenants)
            h.converge(max_ticks=60 + 8 * n_sets)
            wall = time.perf_counter() - t0
        finally:
            gc.enable()
            gc.unfreeze()
            gc.collect()
        reconciles = _reconcile_count() - r0
        solver_s = METRICS.hist_sum.get("gang_solve_seconds", 0.0) - solver0
        cp = max(wall - solver_s, 0.0)
        from grove_tpu.api.pod import is_ready

        pods = h.store.list("Pod")
        row = {
            "workers": workers,
            # the drain clamps to the shard count (worker_of = shard % W
            # can never feed more than S workers) — report what ran
            "effective_workers": (
                h.engine.workers.workers
                if h.engine.workers is not None
                else 1
            ),
            "wall_seconds": round(wall, 3),
            "control_plane_seconds": round(cp, 3),
            "reconciles": reconciles,
            "us_per_reconcile": round(1e6 * cp / max(reconciles, 1), 1),
            "all_ready": bool(pods) and all(is_ready(p) for p in pods),
        }
        if base_wall is None:
            base_wall = wall
        row["speedup"] = round(base_wall / max(wall, 1e-9), 2)
        if h.engine.workers is not None:
            row["utilization"] = h.engine.workers.utilization(wall)
        rows.append(row)
        h.engine.close()
        del h
        gc.collect()
    return {
        "sets": n_sets,
        "nodes": n_nodes,
        "shards": num_shards,
        "backend": backend,
        "sweep": rows,
    }


def process_codec_ab(
    n_sets: int = 256,
    n_nodes: int = 256,
    num_shards: int = 4,
    workers: int = 2,
) -> dict:
    """Paired coordinator-overlap + boundary-codec A/B at the PR-2
    control-plane bench shape (docs/control-plane.md §5).

    Two process-backend converges of the SAME population, same build:

    - **off**: the pre-shave reflective wire decoder
      (``api/wire.py NO_MEMO``) and the overlap pump unhooked — the
      boundary/coordinator cost profile the process backend had before
      the shave;
    - **on**: memoized per-class decode plans + the scheduler's
      speculative-encode overlap pump (``engine.overlap_hook``).

    Reports µs/reconcile per arm (control-plane time: wall minus solver,
    exactly the worker_sweep metric) and the paired reduction — the
    ≥10%-reduction gate's evidence row, stamped with the ``"host"``
    block so a 1-core bounded-overhead claim and a multi-core speedup
    claim are distinguishable after the fact. Both arms must reconcile
    identically (same deterministic schedule) or the comparison is
    meaningless and the row says so."""
    from grove_tpu.api import wire
    from grove_tpu.observability.hostinfo import host_block

    tenants = tenant_namespaces(min(16, n_sets))
    # warmup absorbs the solver's XLA compile at the measured node count
    # (chunk kernel compiles per (chunk, nodes) shape) — without it the
    # compile bills to the first arm and fabricates a reduction
    _warm = _make_harness(n_nodes, num_shards, 1)
    _populate(_warm, n_sets, tenants)
    _warm.converge(max_ticks=60 + 8 * n_sets)
    _warm.engine.close()
    del _warm
    gc.collect()

    def _arm(shaved: bool) -> dict:
        h = _make_harness(n_nodes, num_shards, workers, backend="process")
        wire.NO_MEMO = not shaved
        if not shaved:
            h.engine.overlap_hook = None
        solver0 = METRICS.hist_sum.get("gang_solve_seconds", 0.0)
        r0 = _reconcile_count()
        gc.collect()
        gc.freeze()
        gc.disable()
        try:
            t0 = time.perf_counter()
            _populate(h, n_sets, tenants)
            h.converge(max_ticks=60 + 8 * n_sets)
            wall = time.perf_counter() - t0
        finally:
            gc.enable()
            gc.unfreeze()
            gc.collect()
            wire.NO_MEMO = False
        reconciles = _reconcile_count() - r0
        solver_s = METRICS.hist_sum.get("gang_solve_seconds", 0.0) - solver0
        cp = max(wall - solver_s, 0.0)
        stats = h.engine.workers.stats() if h.engine.workers else {}
        h.engine.close()
        del h
        gc.collect()
        return {
            "wall_seconds": round(wall, 3),
            "control_plane_seconds": round(cp, 3),
            "reconciles": reconciles,
            "us_per_reconcile": round(1e6 * cp / max(reconciles, 1), 1),
            "boundary_bytes": stats.get("boundary_bytes"),
        }

    off = _arm(shaved=False)
    on = _arm(shaved=True)
    reduction = 1.0 - (
        on["us_per_reconcile"] / max(off["us_per_reconcile"], 1e-9)
    )
    return {
        "shape": {
            "sets": n_sets,
            "nodes": n_nodes,
            "shards": num_shards,
            "workers": workers,
            "backend": "process",
        },
        "off": off,
        "on": on,
        "reconciles_identical": off["reconciles"] == on["reconciles"],
        "us_per_reconcile_reduction_pct": round(100.0 * reduction, 1),
        "gate_10pct_reduction": reduction >= 0.10
        and off["reconciles"] == on["reconciles"],
        "host": host_block(backend="process"),
    }
