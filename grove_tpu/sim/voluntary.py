"""Voluntary-disruption scenario: the `make drain-smoke` core.

One loaded cluster of budgeted PodCliqueSets; drain the node hosting the
most gangs and assert the whole voluntary-disruption contract
(docs/robustness.md):

- every affected gang is evicted WHOLE (gang semantics — never pod by pod),
- the per-PCS ``disruptionBudget`` is never exceeded at ANY tick,
- at least one gang gets a trial-solved placement on the remaining nodes
  BEFORE its pods are evicted (the pre-placement path),
- every drained gang is re-admitted and the node reaches ``Drained``,
- an injected eviction storm OPENS the circuit breaker and a quiet window
  CLOSES it again,
- with no budgets and no drains the broker is inert: admissions are
  byte-identical to a broker-less control plane (A/B guard rail, the
  quota-subsystem pattern).

Shared by scripts/drain_smoke.py and the integrated bench's ``"drain"``
artifact block.
"""

from __future__ import annotations

from typing import Dict, Tuple

from grove_tpu.api.load import load_podcliquesets
from grove_tpu.api.meta import deep_copy
from grove_tpu.api.pod import is_ready
from grove_tpu.api.types import PHASE_RUNNING
from grove_tpu.observability.events import EVENTS
from grove_tpu.sim.harness import SimHarness

_BUDGETED_YAML = """
apiVersion: grove.io/v1alpha1
kind: PodCliqueSet
metadata:
  name: svc
spec:
  replicas: 2
  template:
    disruptionBudget:
      maxUnavailableGangs: 1
      quietWindow: 2s
    cliques:
      - name: worker
        spec:
          roleName: worker
          replicas: 3
          minAvailable: 2
          podSpec:
            containers:
              - name: w
                image: busybox:stable
                resources:
                  requests:
                    cpu: 3
"""
# replicas: 2 → TWO gangs per set under ONE budget (maxUnavailableGangs=1:
# draining a node hosting both must evict them one at a time); cpu 3 → a
# 3-pod gang (9 cpu) never fits one 8-cpu node, so nodes host pods of
# multiple gangs and a drain touches several budgets at once.

_BASE = load_podcliquesets(_BUDGETED_YAML)[0]


def _build(
    n_sets: int,
    num_nodes: int,
    with_budget: bool = True,
    with_broker: bool = True,
) -> SimHarness:
    h = SimHarness(num_nodes=num_nodes)
    if not with_broker:
        # A/B control leg: detach BEFORE anything converges, so the whole
        # admission history runs broker-less (detaching after a converge
        # would compare a run against itself)
        h.scheduler.broker = None
        h.ctx.disruption = None
    for i in range(n_sets):
        pcs = deep_copy(_BASE)
        pcs.metadata.name = f"svc-{i:02d}"
        if not with_budget:
            pcs.spec.template.disruption_budget = None
        h.apply(pcs)
    h.converge()
    return h


def _busiest_node(h: SimHarness) -> Tuple[str, int]:
    """(node hosting pods of the most distinct gangs, gang count)."""
    from grove_tpu.api import names as namegen

    gangs_per_node: Dict[str, set] = {}
    for (ns, pod_name), node in sorted(h.cluster.bindings.items()):
        pod = h.store.get("Pod", ns, pod_name, readonly=True)
        if pod is None:
            continue
        gang = pod.metadata.labels.get(namegen.LABEL_PODGANG)
        if gang:
            gangs_per_node.setdefault(node, set()).add((ns, gang))
    node = max(sorted(gangs_per_node), key=lambda n: len(gangs_per_node[n]))
    return node, len(gangs_per_node[node])


def run_drain_scenario(
    n_sets: int = 3, num_nodes: int = 12, max_ticks: int = 400
) -> Tuple[SimHarness, Dict]:
    """Drain the busiest node under per-tick budget watch. Returns
    (harness, report)."""
    h = _build(n_sets, num_nodes)
    pods_before = len(h.store.list("Pod"))
    target, gangs_on_node = _busiest_node(h)
    h.drainer.request_drain(target)

    budget_max_observed = 0
    budget_exceeded = False
    whole_violations = 0
    ticks = 0
    ticks_to_drained = None
    for _ in range(max_ticks):
        work = h.engine.drain()
        work += h.autoscaler.tick()
        work += h.node_monitor.tick()
        work += h.drainer.tick()
        bound = h.schedule()
        started = h.cluster.kubelet_tick()
        work += h.engine.drain()
        ticks += 1
        # per-tick budget invariant (the acceptance bar: never exceeded)
        for pcs in h.store.scan("PodCliqueSet"):
            budget = pcs.spec.template.disruption_budget
            if budget is None:
                continue
            key = (pcs.metadata.namespace, pcs.metadata.name)
            disrupted = h.disruption.voluntarily_disrupted_gangs(key)
            budget_max_observed = max(budget_max_observed, disrupted)
            if disrupted > (budget.max_unavailable_gangs or 0):
                budget_exceeded = True
        # gang-whole invariant: a gang is never left PARTIALLY evicted by
        # the drain — each drained gang's pods die together, so any gang
        # with a Drained disruption mark must have zero bound pods
        from grove_tpu.api.meta import get_condition
        from grove_tpu.api.types import (
            COND_PODGANG_DISRUPTION_TARGET,
            COND_PODGANG_SCHEDULED,
        )

        for gang in h.store.scan("PodGang"):
            dt = get_condition(
                gang.status.conditions, COND_PODGANG_DISRUPTION_TARGET
            )
            sched = get_condition(
                gang.status.conditions, COND_PODGANG_SCHEDULED
            )
            if (
                dt is None
                or not dt.is_true()
                or dt.reason != "Drained"
                or (sched is not None and sched.is_true())
            ):
                continue
            still_bound = sum(
                1
                for group in gang.spec.pod_groups
                for ref in group.pod_references
                if (ref.namespace, ref.name) in h.cluster.bindings
            )
            if still_bound:
                whole_violations += 1
        if ticks_to_drained is None and h.drainer.drain_state(target) == (
            "Drained"
        ):
            ticks_to_drained = ticks
        if not work and not bound and not started:
            # idle: a requeue backoff, drain retry (quiet window), or gate
            # retry may still be pending — jump to the earliest wakeup
            # (converge() pattern) instead of stopping mid-recovery
            wakes = [
                w
                for w in (
                    h.engine.next_wakeup(),
                    h.autoscaler.next_deadline(),
                    h.node_monitor.next_deadline(),
                    h.drainer.next_deadline(),
                )
                if w is not None
            ]
            wake = min(wakes) if wakes else None
            if wake is not None and wake - h.clock.now() <= 120.0:
                h.clock.advance(max(wake - h.clock.now(), 0.0))
                continue
            if ticks_to_drained is not None:
                break
        h.clock.advance(1.0)

    pods = h.store.list("Pod")
    gangs = h.store.scan("PodGang")
    drained = h.drainer.drained_gangs
    report = {
        "sets": n_sets,
        "nodes": num_nodes,
        "drained_node": target,
        "gangs_on_node": gangs_on_node,
        "drain_evictions": len(drained),
        "pre_placed": sum(1 for d in drained if d["pre_placed"]),
        "budget_cap": 1,
        "budget_max_observed": budget_max_observed,
        "budget_exceeded": budget_exceeded,
        "gang_whole_violations": whole_violations,
        "ticks_to_drained": ticks_to_drained,
        "node_drained": h.drainer.drain_state(target) == "Drained",
        "node_empty": not any(
            n == target for n in h.cluster.bindings.values()
        ),
        "readmitted": (
            len(pods) == pods_before
            and all(is_ready(p) for p in pods)
            and all(g.status.phase == PHASE_RUNNING for g in gangs)
        ),
    }
    return h, report


def run_breaker_storm(h: SimHarness, burst: int = 3) -> Dict:
    """Injected eviction storm against a tight broker: grants must exhaust
    the token bucket (BreakerOpen), further requests are throttled, and the
    quiet window closes it again (BreakerClosed)."""
    from grove_tpu.disruption import DisruptionBroker

    broker = DisruptionBroker(
        h.store,
        bucket_capacity=burst,
        refill_per_second=0.0,
        close_after=5.0,
    )
    broker.arm()
    gangs = sorted(
        h.store.scan("PodGang"),
        key=lambda g: (g.metadata.namespace, g.metadata.name),
    )
    granted = denied = 0
    opened = False
    for gang in gangs:
        if broker.grant([gang], "storm"):
            granted += 1
        else:
            denied += 1
        if broker.breaker_open:
            opened = True
    # while open every request is denied
    denied_while_open = (
        not broker.grant([gangs[0]], "storm") if opened else False
    )
    # a quiet window closes it — but pressure during the window must NOT
    h.clock.advance(broker.close_after + 1.0)
    closed_after_quiet = broker.grant([gangs[0]], "storm")
    return {
        "burst": burst,
        "granted": granted,
        "denied": denied,
        "opened": opened,
        "denied_while_open": denied_while_open,
        "closed_after_quiet": bool(closed_after_quiet),
        "breaker_open_event": bool(EVENTS.list(reason="BreakerOpen")),
        "breaker_closed_event": bool(EVENTS.list(reason="BreakerClosed")),
    }


def inert_ab(n_sets: int = 4, num_nodes: int = 12) -> Dict:
    """A/B guard rail: the same un-budgeted workload with the broker wired
    vs with it DETACHED must produce identical admissions — the broker is
    provably inert when nothing configures it."""

    def run(with_broker: bool):
        h = _build(
            n_sets, num_nodes, with_budget=False, with_broker=with_broker
        )
        return sorted(
            (ns, name, node)
            for (ns, name), node in h.cluster.bindings.items()
        )

    detached = run(False)
    wired = run(True)
    return {
        "identical_admissions": detached == wired,
        "admitted_pods": len(detached),
    }


def drain_artifact() -> Dict:
    """Compact block for the integrated bench artifact (`"drain"`)."""
    h, report = run_drain_scenario()
    report["breaker"] = run_breaker_storm(h)
    report["ab"] = inert_ab()
    report["ok"] = (
        not report["budget_exceeded"]
        and report["gang_whole_violations"] == 0
        and report["pre_placed"] >= 1
        and report["node_drained"]
        and report["readmitted"]
        and report["breaker"]["opened"]
        and report["breaker"]["closed_after_quiet"]
        and report["ab"]["identical_admissions"]
    )
    return report
