"""Simulation harness: operator + sim cluster in one virtual-time loop.

The end-to-end driver mirroring the reference quickstart flow
(README.md:26 — apply a PodCliqueSet, watch pcs/pclq/pcsg/pg/pod materialize).
"""

from __future__ import annotations

from typing import List, Optional

from grove_tpu.admission.defaulting import default_podcliqueset
from grove_tpu.admission.validation import (
    ValidationError,
    validate_or_raise,
    validate_podcliqueset_update,
)
from grove_tpu.api.load import load_podcliquesets
from grove_tpu.api.topology import ClusterTopology
from grove_tpu.api.types import PodCliqueSet
from grove_tpu.controller.common import OperatorContext
from grove_tpu.controller.register import register_controllers
from grove_tpu.runtime.clock import VirtualClock
from grove_tpu.runtime.engine import Engine
from grove_tpu.runtime.store import Store
from grove_tpu.sim.cluster import SimCluster, make_nodes


class SimHarness:
    def __init__(
        self,
        num_nodes: int = 16,
        cache_lag: bool = True,
        topology: Optional[ClusterTopology] = None,
        config=None,  # Optional[OperatorConfiguration]
        store: Optional[Store] = None,
        nodes=None,  # Optional[List[Node]] — carried over on cold restart
        durability_dir: Optional[str] = None,
    ) -> None:
        from grove_tpu.config.operator import OperatorConfiguration

        self.config = config or OperatorConfiguration()
        # `store`: a pre-built (typically disk-recovered) store — the cold
        # restart path; its clock is the harness clock so recovered
        # timestamps stay coherent
        self.clock = store.clock if store is not None else VirtualClock()
        self.store = store if store is not None else Store(
            self.clock, cache_lag=cache_lag
        )
        # durability (grove_tpu/durability, docs/robustness.md): attach the
        # WAL BEFORE any commit below so the log covers the store from rv 1
        # (on a recovered store: before any post-recovery commit). converge
        # pumps the group-commit buffer at tick boundaries — off the
        # reconcile path, deterministic.
        self.durability = None
        if durability_dir is not None:
            self.attach_durability(durability_dir)
        # ClusterTopology lives in the store like any CR; when the config
        # enables it, startup requires the named CR to exist (the reference
        # crashes at boot if enabled-but-missing — cmd/main.go:72-75)
        self.topology = topology or ClusterTopology()
        if self.config.cluster_topology.enabled:
            from grove_tpu.admission.validation import validate_cluster_topology

            res = validate_cluster_topology(self.topology)
            if not res.ok:
                raise ValueError(
                    f"cluster topology invalid: {'; '.join(res.errors)}"
                )
            self.topology.metadata.name = self.config.cluster_topology.name
        # cluster-scoped CR: no namespace, matching the wire/CRD scope and
        # the real-cluster manager's lookup (cluster/manager.py)
        self.topology.metadata.namespace = ""
        # the stored CR is the source of truth — keep its identity (uid/rv);
        # a recovered store already carries it (cold restart)
        existing = self.store.get(
            "ClusterTopology", "", self.topology.metadata.name
        )
        if existing is not None:
            self.topology = existing
        else:
            self.topology = self.store.create(self.topology)
        if self.config.authorizer.enabled:
            from grove_tpu.admission.authorization import AuthorizationGuard

            self.store.guard = AuthorizationGuard(
                enabled=True,
                exempt_users=self.config.authorizer.exempt_service_accounts,
            )
        self.engine = Engine(self.store, self.clock)
        # virtual-clock awareness: spans carry the sim's virtual timestamp
        # (`vt` attr) and event first/last timestamps use virtual time, so
        # traces/events line up with requeue math instead of wall time.
        # Process-global singletons — the newest harness wins (one sim per
        # process in practice).
        from grove_tpu.observability.events import EVENTS
        from grove_tpu.observability.flightrec import FLIGHTREC
        from grove_tpu.observability.journey import JOURNEYS
        from grove_tpu.observability.timeseries import TIMESERIES
        from grove_tpu.observability.tracing import TRACER

        TRACER.clock = self.clock
        EVENTS.clock = self.clock
        JOURNEYS.clock = self.clock
        FLIGHTREC.clock = self.clock
        TIMESERIES.clock = self.clock
        self.ctx = OperatorContext(
            store=self.store, clock=self.clock, topology=self.topology
        )
        register_controllers(self.engine, self.ctx, self.config)
        self.cluster = SimCluster(
            store=self.store,
            nodes=nodes if nodes is not None else make_nodes(num_nodes),
        )
        # TPU-solver-backed gang scheduler (the KAI-replacement); set to None
        # to fall back to the cluster's naive first-fit binder.
        from grove_tpu.solver.scheduler import GangScheduler

        self.scheduler = GangScheduler(
            self.store,
            self.cluster,
            self.topology,
            priority_map=self.config.solver.priority_classes,
            chunk_size=min(self.config.solver.chunk_size, 64),
            max_waves=self.config.solver.max_waves,
            solver_sidecar=self.config.solver.sidecar_address or None,
        )
        # incremental delta-solve (solver/deltastate.py, docs/solver.md):
        # cluster tensors + gang specs folded from the watch stream instead
        # of per-tick full repasses — bit-identical to the from-scratch
        # path (GROVE_TPU_NO_DELTA=1 opts a run out for A/B measurement).
        # Under the runtime sanitizer every tick ALSO re-derives the
        # problem from scratch and asserts bit-equality (delta_selfcheck),
        # so sanitized chaos runs pin the equivalence continuously.
        import os as _os

        from grove_tpu.analysis.sanitize import enabled as _sanitize_enabled

        if _os.environ.get("GROVE_TPU_NO_DELTA", "") not in ("1", "true"):
            self.scheduler.enable_delta()
            if _sanitize_enabled():
                self.scheduler.delta_selfcheck = True
        # partitioned solver frontier (solver/frontier.py): OPT-IN — it
        # changes placement semantics (partition-confined placements with
        # a global residual pass), so only scale-focused runs enable it.
        # Sanitized runs arm the per-tick batched-vs-sequential A/B.
        if _os.environ.get("GROVE_TPU_FRONTIER", "") in ("1", "true"):
            self.scheduler.enable_frontier()
            if _sanitize_enabled():
                self.scheduler.frontier_selfcheck = True
        # admission explain engine (observability/explain.py,
        # docs/observability.md "Admission explain"): on-demand,
        # strictly read-only — nothing runs unless somebody asks
        from grove_tpu.observability.explain import ExplainEngine

        self.explain = ExplainEngine(self.scheduler)
        # node-health monitor (controller/nodehealth.py): heartbeat
        # lifecycle, pod failure on Lost nodes, gang rescue vs. requeue.
        # Inert while no node crashes (one O(nodes) pass per tick).
        from grove_tpu.controller.nodehealth import NodeHealthMonitor

        self.node_monitor = NodeHealthMonitor(self.store, self.cluster)
        self.scheduler.monitor = self.node_monitor
        # overlap pump (docs/control-plane.md §5): the process-backend
        # drain spends worker flight time on speculative gang encode.
        # Inert on the serial engine and the thread backend — only
        # ProcessDrain ever invokes the hook.
        self.engine.overlap_hook = self.scheduler.speculate_encode
        # voluntary-disruption layer (grove_tpu/disruption): one broker
        # gates every voluntary evictor — preemption/reclaim (scheduler),
        # rolling update (ctx), node drain (the controller below). Inert
        # until a disruptionBudget exists or a drain is requested.
        from grove_tpu.disruption import DisruptionBroker, NodeDrainController

        self.disruption = DisruptionBroker(self.store)
        self.scheduler.broker = self.disruption
        self.ctx.disruption = self.disruption
        self.drainer = NodeDrainController(
            self.store,
            self.cluster,
            self.scheduler,
            self.node_monitor,
            self.disruption,
        )
        self.node_monitor.drain_states = self.drainer.states
        # HPA controller equivalent (multi-level autoscaling)
        from grove_tpu.autoscale.hpa import (
            HorizontalAutoscaler,
            StaticMetricsProvider,
        )

        self.metrics_provider = StaticMetricsProvider()
        self.autoscaler = HorizontalAutoscaler(
            self.store, self.metrics_provider, scale_down_stabilization=60.0
        )
        # remediation controller (controller/remediate.py,
        # docs/observability.md "Remediation & ledger"): detect→diagnose→
        # simulate→act→account over the existing mechanism layer. Always
        # constructed, OFF by default — a disabled remediator is provably
        # inert (one boolean check per tick, byte-identical A/B pinned).
        from grove_tpu.controller.remediate import RemediationController

        self.remediator = RemediationController(
            self.store,
            self.cluster,
            self.scheduler,
            self.drainer,
            self.disruption,
            self.autoscaler,
            self.explain,
        )

    def schedule(self) -> int:
        if self.scheduler is not None:
            return self.scheduler.schedule_pending()
        return self.cluster.schedule_pending()

    # -- durability (docs/robustness.md) ---------------------------------

    def attach_durability(
        self,
        directory: str,
        segment_max_bytes: int = 4 * 2**20,
        snapshot_every_bytes: int = 32 * 2**20,
    ):
        """Attach a WAL + snapshot writer to this harness's store.
        Defaults are production-shaped (snapshots amortized over tens of
        MB of log — a snapshot scans the whole population, so a tight
        cadence would dominate small-sim wall time); the chaos/recovery
        scenarios dial the knobs down to exercise rotation + truncation."""
        from grove_tpu.durability import StoreDurability

        self.durability = StoreDurability(
            self.store,
            directory,
            segment_max_bytes=segment_max_bytes,
            snapshot_every_bytes=snapshot_every_bytes,
        )
        return self.durability

    @classmethod
    def cold_restart(
        cls,
        store: Store,
        nodes,
        config=None,
        durability_dir: Optional[str] = None,
    ) -> "SimHarness":
        """Boot a fresh control plane over a recovered store — the
        crash-restart path (docs/robustness.md): every piece of leader
        memory is rebuilt from persisted state exactly like a failover,
        so a cold restart converges the way a lease takeover does."""
        h = cls(
            num_nodes=len(nodes),
            cache_lag=store.cache_lag,
            config=config,
            store=store,
            nodes=nodes,
            durability_dir=durability_dir,
        )
        h.engine.requeue_all()
        h.cluster.rebuild_bindings()
        h.node_monitor.resync()
        return h

    # -- user actions ----------------------------------------------------

    def apply(self, pcs: PodCliqueSet) -> PodCliqueSet:
        from grove_tpu.api.types import Queue

        if isinstance(pcs, Queue):
            return self.apply_queue(pcs)
        default_podcliqueset(pcs)
        existing = self.store.get(
            "PodCliqueSet", pcs.metadata.namespace, pcs.metadata.name
        )
        if existing is None:
            validate_or_raise(pcs, self.topology)
            return self.store.create(pcs)
        res = validate_podcliqueset_update(pcs, existing, self.topology)
        if not res.ok:
            raise ValidationError(res)
        existing.spec = pcs.spec
        return self.store.update(existing)

    def apply_queue(self, queue):
        """Create-or-update a tenant Queue (quota subsystem, docs/quota.md)
        through the same defaulting+validation the webhooks run."""
        from grove_tpu.admission.defaulting import default_queue
        from grove_tpu.admission.validation import validate_queue

        default_queue(queue)
        res = validate_queue(queue)
        if not res.ok:
            raise ValidationError(res)
        existing = self.store.get("Queue", "", queue.metadata.name)
        if existing is None:
            return self.store.create(queue)
        existing.spec = queue.spec
        return self.store.update(existing)

    def apply_yaml(self, text: str) -> List[PodCliqueSet]:
        return [self.apply(p) for p in load_podcliquesets(text)]

    def delete(self, name: str, namespace: str = "default") -> None:
        self.store.delete("PodCliqueSet", namespace, name)

    # -- convergence loop ------------------------------------------------

    def tick_once(self):
        """One tick of the convergence loop WITHOUT any clock advance:
        reconcile ⇄ schedule ⇄ kubelet ⇄ WAL pump ⇄ observatory round.
        Returns ``(work, bound, started)`` so callers can apply the same
        idle test converge() uses. Extracted so a federation tier can
        drive K harnesses in lockstep on one shared virtual clock — the
        body is byte-for-byte the old converge() tick."""
        from grove_tpu.observability.profile import PROFILER
        from grove_tpu.observability.slo import SLO
        from grove_tpu.observability.timeseries import TIMESERIES

        # wall attribution (docs/observability.md "Wall-attribution
        # profiler"): every component of the tick gets a top-level
        # phase (engine/scheduler/WAL open their own finer phases
        # inside), so the roll-up's coverage vs an independent wall
        # measurement is arithmetic. phase() is the shared no-op while
        # profiling is off, and this runs per TICK, not per event —
        # the hot paths keep the `if PROFILER.enabled` guard.
        work = self.engine.drain()
        with PROFILER.phase("tick", controller="autoscaler"):
            work += self.autoscaler.tick()
        with PROFILER.phase("tick", controller="node-monitor"):
            work += self.node_monitor.tick()
        with PROFILER.phase("tick", controller="drain"):
            work += self.drainer.tick()
        bound = self.schedule()
        with PROFILER.phase("tick", controller="kubelet"):
            started = self.cluster.kubelet_tick()
        work += self.engine.drain()
        if self.durability is not None:
            # group commit at the tick boundary — the sim's committer
            # cadence (real mode: the background thread)
            with PROFILER.phase("tick", controller="wal"):
                self.durability.pump()
        # SLO observatory (observability/timeseries.py, slo.py): the
        # sampling round + objective evaluation run at the tick
        # boundary — one boolean check while the observatory is off
        if TIMESERIES.enabled:
            TIMESERIES.sample(self.clock.now())
            SLO.evaluate(self.clock.now())
        # remediation runs AFTER the observatory round so it reads
        # this tick's verdicts, not last tick's (one boolean when off)
        if self.remediator.enabled:
            with PROFILER.phase("tick", controller="remediator"):
                work += self.remediator.tick()
        return work, bound, started

    def next_wake(self) -> Optional[float]:
        """Earliest pending deadline across every deadline source — the
        idle-jump target converge() (and the federation router) uses.
        None means nothing is scheduled to fire."""
        wakes = [
            w
            for w in (
                self.engine.next_wakeup(),
                self.autoscaler.next_deadline(),
                self.node_monitor.next_deadline(),
                self.drainer.next_deadline(),
                self.remediator.next_deadline(),
            )
            if w is not None
        ]
        return min(wakes) if wakes else None

    def converge(self, max_ticks: int = 60, tick_seconds: float = 1.0) -> int:
        """Reconcile ⇄ schedule ⇄ kubelet until quiescent. Each tick advances
        virtual time so requeue_after-based waits can fire."""
        ticks = 0
        for _ in range(max_ticks):
            work, bound, started = self.tick_once()
            ticks += 1
            if bound == 0 and started == 0 and work == 0:
                # idle now — but short-horizon requeues (gate retries), a
                # held HPA scale-down, a node-grace deadline, a gang
                # requeue backoff, or an in-flight drain may be pending;
                # jump to the earliest wakeup rather than stopping early
                wake = self.next_wake()
                if wake is not None and wake - self.clock.now() <= 120.0:
                    self.clock.advance(max(wake - self.clock.now(), 0.0))
                    continue
                break
            self.clock.advance(tick_seconds)
        from grove_tpu.analysis.sanitize import store_guard_enabled

        if store_guard_enabled():
            # test-mode write barrier: a reconciler that mutated a zero-copy
            # readonly view during this converge fails loudly here (the
            # suite sets the flag in conftest, sanitizer mode implies it;
            # production converges don't pay the re-pickle)
            self.store.verify_readonly_integrity()
        return ticks

    def advance(self, seconds: float) -> None:
        self.clock.advance(seconds)

    # -- inspection ------------------------------------------------------

    def tree(self, namespace: str = "default") -> str:
        """kubectl-tree-style dump: pcs > pclq/pcsg > pg > pod."""
        from grove_tpu.api.inspect import render_tree

        return render_tree(self.store, namespace)
