"""Seeded serving traffic generator: the workload the SLO observatory
measures against.

The paper's subject is *serving systems* — prefill/decode workers and
routers behind one PodCliqueSet — yet every scenario so far converges a
mostly static gang mix. This module generates the missing load shape,
deterministically from one seed on the virtual clock (grovelint GL001
runs STRICT here: not even ``perf_counter`` — a traffic trace must replay
bit-identically):

- **diurnal wave**: demand follows a day/night sine (period/amplitude/
  per-tenant phase from the seed);
- **flash crowds**: a seeded schedule of step surges (start, duration,
  magnitude) — the tail events autoscaling must absorb;
- **tenant skew**: per-tenant Zipf-ish weights, so one tenant dominates
  while the tail trickles (the contention shape of PAPERS.md's
  multi-objective-scheduling work);
- **prefill:decode ratio drift**: the share of demand landing on the
  prefill vs decode scaling group drifts sinusoidally — disaggregated
  serving's load mix is not a constant.

:class:`ServingScenario` applies one prefill/decode-shaped PodCliqueSet
per tenant (two PodCliqueScalingGroups with HPA scale configs + a fixed
router clique), then drives the HPA loop each step: demand → observed
utilization per scaling group → ``autoscale/hpa.py`` walks replicas →
scaled PodGangs materialize → the gang solver admits them. Along the way
it measures the serving signals the SLO layer judges: scale-up latency
(HPA bump → gang Ready, virtual seconds), time-under-min-replicas, and
the per-target demand trace. Chaos composes: ``faults`` is a seeded
``(vt, callable)`` schedule, so node loss and drains land mid-flash-crowd
(``scripts/serving_smoke.py`` does exactly that).

Shared by ``make serving-smoke``, the bench ``--integrated`` ``"serving"``
block, and tests/test_slo_observatory.py.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from grove_tpu.api import names as namegen
from grove_tpu.api.load import load_podcliquesets
from grove_tpu.api.pod import is_ready
from grove_tpu.observability.metrics import METRICS, _quantile
from grove_tpu.observability.timeseries import (
    SERIES_SCALEUP_LATENCY,
    TIMESERIES,
)

_SERVING_YAML = """
apiVersion: grove.io/v1alpha1
kind: PodCliqueSet
metadata:
  name: placeholder
spec:
  replicas: 1
  template:
    cliques:
      - name: router
        spec:
          roleName: role-router
          replicas: 1
          podSpec:
            containers:
              - name: router
                image: busybox:stable
                resources:
                  requests:
                    cpu: 250m
      - name: prefill
        spec:
          roleName: role-prefill
          replicas: 1
          podSpec:
            containers:
              - name: prefill
                image: busybox:stable
                resources:
                  requests:
                    cpu: 1
      - name: decode
        spec:
          roleName: role-decode
          replicas: 1
          podSpec:
            containers:
              - name: decode
                image: busybox:stable
                resources:
                  requests:
                    cpu: 500m
    podCliqueScalingGroups:
      - name: prefill
        cliqueNames:
          - prefill
        scaleConfig:
          maxReplicas: {max_prefill}
          metrics:
            - type: Resource
              resource:
                name: cpu
                target:
                  type: Utilization
                  averageUtilization: 80
      - name: decode
        cliqueNames:
          - decode
        scaleConfig:
          maxReplicas: {max_decode}
          metrics:
            - type: Resource
              resource:
                name: cpu
                target:
                  type: Utilization
                  averageUtilization: 80
"""


# nodes the composed chaos fault takes down mid-flash-crowd (and the
# node-axis delta the solver warm-up pre-compiles: N and N-FAULT_NODES)
FAULT_NODES = 3


# the warm-up gang: the same 1-pod/1-group shape a scaled prefill/decode
# replica arrives as (shapes, not request values, drive XLA compiles)
_WARM_YAML = """
apiVersion: grove.io/v1alpha1
kind: PodCliqueSet
metadata:
  name: placeholder
spec:
  replicas: 1
  template:
    cliques:
      - name: w
        spec:
          roleName: role-w
          replicas: 1
          podSpec:
            containers:
              - name: w
                image: busybox:stable
                resources:
                  requests:
                    cpu: 500m
"""


@dataclass
class FlashCrowd:
    start: float
    duration: float
    magnitude: float  # multiplier on top of the diurnal demand

    def active(self, t: float) -> bool:
        return self.start <= t < self.start + self.duration


class TrafficModel:
    """Pure demand function ``demand(t)`` — seeded at construction, then
    deterministic in virtual time. Units are *replica-equivalents*: a
    demand of 3.0 on a scaling group means three replicas' worth of work
    is arriving.

    ``phase_offset`` shifts the whole model along the virtual-time axis
    (the federation tier's per-REGION diurnal offset — each cluster's
    load peaks at a different virtual hour, so follow-the-sun spillover
    is directly benchable): ``TrafficModel(..., phase_offset=dx)`` at
    ``t`` equals the unshifted model at ``t + dx`` exactly, including
    flash crowds, and the seeded construction draws are untouched by
    the offset (same seed ⇒ same weights/phases/crowds at any offset).
    """

    def __init__(
        self,
        seed: int,
        tenants: List[str],
        base: float = 3.0,
        amplitude: float = 0.6,
        period: float = 600.0,
        skew: float = 1.0,
        flash_crowds: int = 2,
        flash_magnitude: float = 3.0,
        flash_duration: float = 90.0,
        ratio: float = 0.55,
        ratio_drift: float = 0.25,
        horizon: float = 1800.0,
        phase_offset: float = 0.0,
    ) -> None:
        rng = random.Random(seed)
        self.tenants = list(tenants)
        self.base = base
        self.amplitude = amplitude
        self.period = period
        self.ratio = ratio
        self.ratio_drift = ratio_drift
        self.horizon = horizon
        self.phase_offset = phase_offset
        # tenant skew: Zipf-ish 1/(rank+1)^skew weights, rank order seeded
        ranks = list(range(len(self.tenants)))
        rng.shuffle(ranks)
        raw = [1.0 / (r + 1.0) ** skew for r in ranks]
        total = sum(raw)
        self.weights = {
            tenant: w / total for tenant, w in zip(self.tenants, raw)
        }
        # per-tenant diurnal phase offsets (staggered peaks)
        self.phases = {
            tenant: rng.uniform(0.0, period) for tenant in self.tenants
        }
        # flash-crowd schedule: seeded starts in the middle 80% of the
        # horizon so surges land on a warmed-up system
        self.crowds = sorted(
            (
                FlashCrowd(
                    start=rng.uniform(0.1 * horizon, 0.9 * horizon),
                    duration=flash_duration * rng.uniform(0.7, 1.3),
                    magnitude=flash_magnitude * rng.uniform(0.8, 1.2),
                )
                for _ in range(flash_crowds)
            ),
            key=lambda c: c.start,
        )

    def flash_multiplier(self, t: float) -> float:
        t = t + self.phase_offset
        m = 1.0
        for crowd in self.crowds:
            if crowd.active(t):
                m = max(m, crowd.magnitude)
        return m

    def prefill_share(self, t: float) -> float:
        """Share of demand landing on prefill at ``t`` (drifts in
        [ratio - drift/2, ratio + drift/2], clamped to (0.05, 0.95))."""
        share = self.ratio + 0.5 * self.ratio_drift * math.sin(
            2.0 * math.pi * (t + self.phase_offset) / (self.period * 1.7)
        )
        return min(0.95, max(0.05, share))

    def demand(self, t: float) -> Dict[str, Dict[str, float]]:
        """tenant -> {"prefill": d, "decode": d} replica-equivalents."""
        # flash_multiplier/prefill_share apply the region offset
        # internally — pass raw t so the shift lands exactly once
        flash = self.flash_multiplier(t)
        local = t + self.phase_offset
        out: Dict[str, Dict[str, float]] = {}
        n = max(1, len(self.tenants))
        for tenant in self.tenants:
            wave = 1.0 + self.amplitude * math.sin(
                2.0 * math.pi * (local + self.phases[tenant]) / self.period
            )
            total = self.base * n * self.weights[tenant] * wave * flash
            share = self.prefill_share(t)
            out[tenant] = {
                "prefill": total * share,
                "decode": total * (1.0 - share),
            }
        return out


class ServingScenario:
    """One prefill/decode serving fleet under generated traffic.

    ``step(dt)`` advances one observation interval: demand at the current
    RUN-RELATIVE virtual time (t=0 is the first step — warm-up and fleet
    construction burn virtual seconds that must not consume the traffic
    model's horizon) becomes observed utilization on each scaling group's
    HPA, due faults fire (``faults`` schedule times are run-relative
    too), and the harness converges (the observatory samples at its tick
    boundaries). Scale-up latency and time-under-min-replicas are
    measured here because only the driver knows when a scale decision
    happened."""

    def __init__(
        self,
        seed: int = 2026,
        tenants: int = 3,
        num_nodes: int = 24,
        max_prefill: int = 12,
        max_decode: int = 12,
        model: Optional[TrafficModel] = None,
        harness=None,
        faults: Optional[List[Tuple[float, Callable[[], None]]]] = None,
        warm: bool = True,
    ) -> None:
        from grove_tpu.sim.harness import SimHarness

        self.tenant_names = [f"tenant-{i}" for i in range(tenants)]
        self.model = model or TrafficModel(seed, self.tenant_names)
        self.harness = harness or SimHarness(num_nodes=num_nodes)
        self.faults = sorted(faults or [], key=lambda f: f[0])
        self._fired = 0
        self.t0: Optional[float] = None  # set by the first step()
        self.scale_ups = 0
        self.scale_downs = 0
        self.time_under_min = 0.0  # virtual seconds any group sat < min
        self.scaleup_samples: List[float] = []
        self._pending_scaleups: Dict[Tuple[str, str], Tuple[float, int]] = {}
        yaml = _SERVING_YAML.format(
            max_prefill=max_prefill, max_decode=max_decode
        )
        for tenant in self.tenant_names:
            pcs = load_podcliquesets(yaml)[0]
            pcs.metadata.name = "serve"
            pcs.metadata.namespace = tenant
            pcs.metadata.labels[namegen.LABEL_QUEUE] = tenant
            self.harness.apply(pcs)
        self.harness.converge(max_ticks=120)
        if warm:
            self._warm_solver()
        # any scale decisions during warm-up are not serving signal
        self.harness.autoscaler.scale_log.clear()

    def _warm_solver(self) -> None:
        """Pre-compile the solve shapes the traffic will hit (the PR-8
        compile-warmup discipline): XLA compiles once per shape per
        process, and an admission-latency measurement that bills a cold
        compile to one arbitrary mid-flash-crowd journey is measuring
        process warmup, not the serving path. Scale-up bursts arrive as
        batches of 1-pod scaled gangs (1..~16 pending per tick), and the
        composed chaos fault shrinks the schedulable node axis by
        FAULT_NODES — so burst the gang buckets at N AND at
        N - FAULT_NODES, then delete the warm-up population."""
        h = self.harness
        yaml = _WARM_YAML
        serial = 0
        names: List[str] = []

        def burst(count: int) -> None:
            nonlocal serial
            for _ in range(count):
                pcs = load_podcliquesets(yaml)[0]
                pcs.metadata.name = f"warm-{serial:03d}"
                pcs.metadata.namespace = self.tenant_names[0]
                serial += 1
                names.append(pcs.metadata.name)
                h.apply(pcs)
            h.converge(max_ticks=60)

        # phase 1: gang buckets at full N (a flash crowd can scale every
        # group at once: tenants × 2 groups × several replicas ⇒ batches
        # past 16 pending in one tick land in the 32 bucket)
        for count in (32, 16, 8, 4, 2, 1):
            burst(count)
        # phase 2: the composed fault's shapes — REAL node crashes (the
        # rescue/requeue solve path compiles its own recovery-pin shapes,
        # which a cordon would not touch), bursts at N - FAULT_NODES,
        # then the nodes rejoin
        victims = [n.name for n in h.cluster.nodes[:FAULT_NODES]]
        for name in victims:
            h.cluster.crash_node(name)
        h.converge(max_ticks=240)
        for count in (32, 16, 8, 4, 2, 1):
            burst(count)
        for name in victims:
            h.cluster.restart_node(name)
        h.converge(max_ticks=240)
        for name in names:
            h.delete(name, self.tenant_names[0])
        names.clear()
        h.converge(max_ticks=120)

    # -- target bookkeeping ----------------------------------------------

    def _targets(self) -> List[Tuple[str, str]]:
        """(namespace, scaling-group name) for every HPA-driven group."""
        return [
            (tenant, f"serve-0-{group}")
            for tenant in self.tenant_names
            for group in ("prefill", "decode")
        ]

    def _pcsg(self, key: Tuple[str, str]):
        return self.harness.store.get(
            "PodCliqueScalingGroup", key[0], key[1], readonly=True
        )

    def _replicas(self, key: Tuple[str, str]) -> int:
        pcsg = self._pcsg(key)
        return int(pcsg.spec.replicas) if pcsg is not None else 0

    def _min_replicas(self, key: Tuple[str, str]) -> int:
        hpa = self.harness.store.get(
            "HorizontalPodAutoscaler", key[0], key[1], readonly=True
        )
        if hpa is None:
            return 1
        return int(hpa.spec.get("minReplicas") or 1)

    def _ready_replicas(self, key: Tuple[str, str]) -> int:
        ns, group = key
        pods = self.harness.store.list(
            "Pod", ns, {namegen.LABEL_PCSG: group}
        )
        return sum(1 for p in pods if is_ready(p))

    # -- driving ---------------------------------------------------------

    def step(self, dt: float = 10.0) -> None:
        """One observation interval: fire due faults, feed utilization,
        converge, account scale events and readiness."""
        now = self.harness.clock.now()
        if self.t0 is None:
            self.t0 = now
        rel = now - self.t0
        while self._fired < len(self.faults) and self.faults[self._fired][0] <= rel:
            self.faults[self._fired][1]()
            self._fired += 1
        demands = self.model.demand(rel)
        for ns, group in self._targets():
            role = "prefill" if group.endswith("prefill") else "decode"
            d = demands[ns][role]
            current = max(1, self._replicas((ns, group)))
            util = 100.0 * d / current
            self.harness.metrics_provider.set(
                "PodCliqueScalingGroup", ns, group, util
            )
            if TIMESERIES.enabled:
                TIMESERIES.gauge(f"traffic_demand/{ns}/{role}", d, vt=now)
            METRICS.set(f"traffic_demand/{ns}-{role}", d)
        self.harness.converge(max_ticks=int(dt), tick_seconds=1.0)
        end = self.harness.clock.now()
        if end - now < dt:
            self.harness.advance(dt - (end - now))
        # one guaranteed sampling round per step at the post-converge
        # instant: converge only samples while it ticks, so an idle system
        # would otherwise contribute NO "all ready" samples and every
        # windowed mean would be biased toward the scale-up dips
        if TIMESERIES.enabled:
            from grove_tpu.observability.slo import SLO

            TIMESERIES.sample(self.harness.clock.now())
            SLO.evaluate(self.harness.clock.now())
        self._account(self.harness.clock.now(), max(dt, end - now))

    def _account(self, now: float, dt: float) -> None:
        """Post-converge bookkeeping: DRAIN the HPA's vt-stamped scale
        log (the decision instant survives the converge that absorbed
        it; consuming by popleft keeps the bounded deque's wraparound
        from silently skipping events a positional cursor would miss),
        complete pending scale-up latency measurements, accrue
        time-under-min."""
        log = self.harness.autoscaler.scale_log
        group_names = {g for _, g in self._targets()}
        while log:
            t_dec, kind, ns, name, previous, desired = log.popleft()
            if kind != "PodCliqueScalingGroup" or name not in group_names:
                continue
            key = (ns, name)
            if desired > previous:
                self.scale_ups += 1
                METRICS.inc("serving_scale_events_total")
                # the FIRST decision starts the clock; a further bump
                # while one is pending re-arms at the higher desired
                # count (the user experiences the full ramp)
                t0 = self._pending_scaleups.get(key, (t_dec, desired))[0]
                self._pending_scaleups[key] = (t0, desired)
            else:
                self.scale_downs += 1
                METRICS.inc("serving_scale_events_total")
                self._pending_scaleups.pop(key, None)
        for key in self._targets():
            ready = self._ready_replicas(key)
            pending = self._pending_scaleups.get(key)
            if pending is not None and ready >= pending[1]:
                latency = max(now - pending[0], 0.0)
                self.scaleup_samples.append(latency)
                self._pending_scaleups.pop(key, None)
                if TIMESERIES.enabled:
                    TIMESERIES.observe(
                        SERIES_SCALEUP_LATENCY, latency, vt=now
                    )
            if ready < self._min_replicas(key):
                self.time_under_min += dt

    def run(self, duration: float, dt: float = 10.0) -> None:
        t_end = self.harness.clock.now() + duration
        while self.harness.clock.now() < t_end:
            self.step(dt)


def default_slos() -> List[str]:
    """The standing serving objectives (grammar form — docs/observability
    "SLO observatory"): virtual-time admission p99, cluster ready
    fraction, and scale-up p99. Scaled to sim time: windows are minutes,
    not the production hours the burn-rate table documents."""
    return [
        "admission_latency_vt:p99 < 60s over 1m target 90%"
        " budget 5m burn 3x 1m/5m",
        "ready_fraction:mean >= 0.88 over 1m target 95% budget 5m"
        " burn 3x 1m/5m",
        f"{SERIES_SCALEUP_LATENCY}:p99 < 120s over 2m target 80%"
        " budget 5m burn 3x 1m/5m",
    ]


def serving_artifact(
    seed: int = 2026,
    tenants: int = 3,
    num_nodes: int = 24,
    duration: float = 1200.0,
    dt: float = 10.0,
    with_fault: bool = True,
    flightrec_dir: Optional[str] = None,
    tap: Optional[Callable[[str, int, float], None]] = None,
) -> dict:
    """The bench ``"serving"`` block: a seeded diurnal + flash-crowd run
    with the full observatory armed, optionally composing a node crash
    into the first flash crowd. Reports SLO attainment/budget per
    objective, scale-up latency p50/p99, time-under-min, per-tenant queue
    wait, and the steady-state admission-p99 gate evaluated through the
    flash crowd (ROADMAP's serving acceptance)."""
    from grove_tpu.observability.journey import JOURNEYS
    from grove_tpu.observability.slo import SLO
    from grove_tpu.observability.timeseries import (
        SERIES_ADMISSION,
        SERIES_QUEUE_WAIT,
        install_serving_collector,
    )

    TIMESERIES.reset()
    SLO.reset()
    # build (and solver-warm) the fleet BEFORE arming the observatory:
    # the measured window must start after the warm-up absorbed the XLA
    # compiles, or the admission p99 reports process warmup (the PR-8
    # compile-warmup discipline). The traffic model's horizon is the RUN
    # duration (step() drives it in run-relative time), so the seeded
    # flash-crowd schedule always lands inside the measured window.
    model = TrafficModel(
        seed, [f"tenant-{i}" for i in range(tenants)], horizon=duration
    )
    scenario = ServingScenario(
        seed=seed, tenants=tenants, num_nodes=num_nodes, model=model
    )
    h = scenario.harness
    JOURNEYS.enable()
    JOURNEYS.reset()
    TIMESERIES.enable(clock=h.clock)
    TIMESERIES.tap = tap
    SLO.enable()
    JOURNEYS.clock = h.clock
    collector = install_serving_collector(
        h.store, scheduler=h.scheduler, clock=h.clock
    )
    if flightrec_dir is not None:
        from grove_tpu.observability.flightrec import FLIGHTREC

        FLIGHTREC.enable(out_dir=flightrec_dir, clock=h.clock)
    for text in default_slos():
        SLO.add(text)
    if with_fault and scenario.model.crowds:
        # FAULT_NODES nodes die right as the first flash crowd peaks —
        # capacity squeeze mid-surge, the everything-at-once shape the
        # ROADMAP serving item names; they rejoin when the crowd passes
        crowd = scenario.model.crowds[0]
        victims = [n.name for n in h.cluster.nodes[:FAULT_NODES]]

        def _crash() -> None:
            for name in victims:
                h.cluster.crash_node(name)

        def _restore() -> None:
            for name in victims:
                h.cluster.restart_node(name)

        scenario.faults = [
            (crowd.start + 5.0, _crash),
            (crowd.start + crowd.duration, _restore),
        ]
    scenario.run(duration, dt=dt)
    status = SLO.status()
    admission = TIMESERIES.window(SERIES_ADMISSION, duration)
    scaleups = sorted(scenario.scaleup_samples)
    queue_wait = {}
    for tenant in scenario.tenant_names:
        doc = TIMESERIES.window(f"{SERIES_QUEUE_WAIT}/{tenant}", duration)
        if doc.get("n"):
            queue_wait[tenant] = {
                "mean_s": round(doc["mean"], 3),
                "max_s": round(doc["max"], 3),
            }
    objectives = {
        row["name"]: {
            "attainment": row["attainment"],
            "budget_remaining": row["budget_remaining"],
            "state": row["state"],
            "breaches": row["breaches"],
            "recoveries": row["recoveries"],
        }
        for row in status["objectives"]
    }
    p99_wall = admission.get("p99", 0.0) if admission.get("count") else 0.0
    doc = {
        "seed": seed,
        "tenants": tenants,
        "duration_vt_s": duration,
        "flash_crowds": len(scenario.model.crowds),
        "fault_injected": bool(with_fault and scenario.model.crowds),
        "objectives": objectives,
        "breaches": sum(o["breaches"] for o in objectives.values()),
        "recoveries": sum(o["recoveries"] for o in objectives.values()),
        "scale_ups": scenario.scale_ups,
        "scale_downs": scenario.scale_downs,
        "scaleup_latency_vt": {
            # the repo's one quantile index rule (metrics._quantile) — the
            # block's p99 must agree with the SLO objective judging the
            # same series
            "n": len(scaleups),
            "p50_s": round(_quantile(scaleups, 0.5), 3) if scaleups else 0.0,
            "p99_s": round(_quantile(scaleups, 0.99), 3) if scaleups else 0.0,
        },
        "time_under_min_vt_s": round(scenario.time_under_min, 1),
        "queue_wait_vt": queue_wait,
        "admission_p99_s": round(p99_wall, 6),
        # the ROADMAP serving gate: steady-state churn admission p99
        # stays under 1 s (wall) THROUGH the flash crowd + fault
        "p99_lt_1s": bool(p99_wall < 1.0),
    }
    if flightrec_dir is not None:
        from grove_tpu.observability.flightrec import FLIGHTREC

        doc["flight_bundles"] = list(FLIGHTREC.dumps)
    SLO.disable()
    TIMESERIES.disable()
    TIMESERIES.tap = None
    # the collector's closure pins the whole scenario harness — a stale
    # one firing on a later re-enable would feed a dead store's gauges
    TIMESERIES.remove_collector(collector)
    JOURNEYS.disable()
    return doc
