"""Seeded chaos harness: deterministic fault schedules over a mixed-gang sim.

The acceptance driver for the node-failure & recovery subsystem
(docs/robustness.md): a fixed seed expands into a fault schedule — node
crashes (beyond the heartbeat grace window: real losses), a flap (crash +
restart inside the window), a transient store outage (the
``Store.error_injectors`` hook), a gang-aware node **drain** (the
voluntary-disruption layer: budget-checked, trial-solved, gang-whole
eviction), and a **leader crash** mid-drain (LeaseElector failover: the
standby takes the lease, rebuilds every piece of leader memory — engine
requeue_all, binding map, monitor holds via ``resync()``, drain intents
from the persisted NodeDrain objects — and the run continues) — replayed
on virtual time over a workload that mixes rescuable gangs (with a
``disruptionBudget``), topology-packed rescuable gangs, and strict
(minAvailable == replicas) gangs that must gang-terminate and requeue.

Every tick asserts the chaos invariants:

1. **No binding targets a Lost node** (level-triggered, after the monitor's
   sweep).
2. **No scheduled gang sits below its MinReplicas floor past the grace
   window** — breaches must resolve (rescue or gang-terminate) within
   ``lost_after`` plus a small slack.
3. **Capacity accounting stays exact**: the incremental quota accountant
   equals a full recount (``quota/oracle.py::usage_oracle``), and no node's
   bound requests exceed its capacity.
4. **No disruptionBudget is ever exceeded**: per budgeted PodCliqueSet, the
   gangs unavailable due to a VOLUNTARY disruption never outnumber
   ``maxUnavailableGangs`` — across drain, failover, everything.
5. **No stranded hold**: every gang the monitor holds in requeue backoff
   has a scheduled release in the workqueue (a hold without one would wait
   forever — the failover-resync bug class).

After the last fault clears, the run must converge: every gang Running,
every pod Ready, nothing on an unhealthy node, and the resource tree equal
to a fault-free twin run of the same workload. Rescued packed gangs are
verified — via actual placements — to have rejoined their survivors'
topology domain (the packing kernel's recovery-pin path).

Shared by ``make chaos-smoke`` / ``make chaos-matrix``
(scripts/chaos_smoke.py), the bench's ``"chaos"`` artifact block, and
tests/test_node_failure.py.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from grove_tpu.analysis.sanitize import accountant_drift, stranded_holds
from grove_tpu.analysis import sanitize
from grove_tpu.api.load import load_podcliquesets
from grove_tpu.api.meta import deep_copy, get_condition
from grove_tpu.api.pod import is_ready
from grove_tpu.api.types import COND_PODGANG_SCHEDULED, PHASE_RUNNING
from grove_tpu.observability.metrics import METRICS
from grove_tpu.runtime.errors import GroveError
from grove_tpu.sim.cluster import NODE_LOST, NODE_READY
from grove_tpu.sim.harness import SimHarness

# Workload shapes (chaos_workload): pods are sized so a 3-pod packed gang
# spans 3 distinct hosts of ONE ici-block (cpu 5 of 8 → one pod per node) —
# crashing one host then exercises the recovery-pin delta-solve, visibly.
_PLAIN_YAML = """
apiVersion: grove.io/v1alpha1
kind: PodCliqueSet
metadata:
  name: plain
spec:
  replicas: 1
  template:
    disruptionBudget:
      maxUnavailableGangs: 1
    cliques:
      - name: worker
        spec:
          roleName: worker
          replicas: 3
          minAvailable: 2
          podSpec:
            containers:
              - name: w
                image: busybox:stable
                resources:
                  requests:
                    cpu: 2
"""

_PACKED_YAML = """
apiVersion: grove.io/v1alpha1
kind: PodCliqueSet
metadata:
  name: packed
spec:
  replicas: 1
  template:
    topologyConstraint:
      packDomain: ici-block
    cliques:
      - name: worker
        spec:
          roleName: worker
          replicas: 3
          minAvailable: 2
          podSpec:
            containers:
              - name: w
                image: busybox:stable
                resources:
                  requests:
                    cpu: 5
"""

_STRICT_YAML = """
apiVersion: grove.io/v1alpha1
kind: PodCliqueSet
metadata:
  name: strict
spec:
  replicas: 1
  template:
    cliques:
      - name: worker
        spec:
          roleName: worker
          replicas: 2
          podSpec:
            containers:
              - name: w
                image: busybox:stable
                resources:
                  requests:
                    cpu: 3
"""
# minAvailable intentionally omitted in strict: defaulting pins it to
# replicas, so ANY pod loss breaches the floor → gang-terminate + requeue.

_SHAPES = {
    "plain": load_podcliquesets(_PLAIN_YAML)[0],
    "packed": load_podcliquesets(_PACKED_YAML)[0],
    "strict": load_podcliquesets(_STRICT_YAML)[0],
}


def chaos_workload(n_each: int = 2) -> List:
    """n_each PodCliqueSets of every shape (plain / packed / strict)."""
    out = []
    for shape, base in sorted(_SHAPES.items()):
        for i in range(n_each):
            pcs = deep_copy(base)
            pcs.metadata.name = f"{shape}-{i:02d}"
            out.append(pcs)
    return out


@dataclass
class Fault:
    at: float  # virtual seconds after the steady-state snapshot
    kind: str  # crash | restart | outage_begin | outage_end
    target: str = ""  # node name for crash/restart
    note: str = ""

    def as_dict(self) -> dict:
        return {
            "at": round(self.at, 2),
            "kind": self.kind,
            "target": self.target,
            "note": self.note,
        }


@dataclass
class ChaosReport:
    seed: int
    ticks: int = 0
    faults: List[dict] = field(default_factory=list)
    node_losses: int = 0
    flaps: int = 0
    rescues: List[dict] = field(default_factory=list)
    requeues: int = 0
    drain_evictions: int = 0
    drains_completed: int = 0
    failovers: int = 0
    scheduler_errors: int = 0
    invariant_violations: List[str] = field(default_factory=list)
    converged: bool = False
    signature_matches_fault_free: bool = False
    pin_verified_rescues: int = 0
    # controlplane_crash mode (docs/robustness.md durability section):
    # crash-restart recoveries performed, WAL records replayed, whether a
    # torn tail was truncated, and the unacked records the crash lost
    recoveries: int = 0
    require_recoveries: int = 0
    replayed_records: int = 0
    torn_tails: int = 0
    lost_unacked_records: int = 0
    recovery_wall_seconds: float = 0.0
    # flight-recorder bundles dumped during the run (one per invariant
    # violation burst, docs/observability.md "Flight recorder") — the
    # postmortem evidence a failing matrix seed ships with its verdict
    flight_bundles: List[str] = field(default_factory=list)
    # remediator-armed mode: ledger entries written while the remediation
    # controller ran live through the fault schedule (executed + skipped)
    remediator_armed: bool = False
    remediations_executed: int = 0
    remediations_skipped: int = 0
    # worker_crash fault (process executor only): reconcile-worker
    # processes SIGKILLed mid-round and repatriated by the coordinator
    # (runtime/procworkers.py); scheduled only when the process drain is
    # armed, and then REQUIRED to have fired
    worker_crashes: int = 0
    require_worker_crashes: int = 0
    # failslow fault (gray failure, docs/robustness.md): a node's
    # heartbeats run late without ever crossing the binary NotReady
    # grace — the suspicion EWMA must flip it Degraded (masked from new
    # placements, running gangs untouched) and back after the heal
    failslow_degraded: int = 0
    failslow_recovered: int = 0
    require_failslow: int = 0

    @property
    def ok(self) -> bool:
        return (
            not self.invariant_violations
            and self.converged
            and self.signature_matches_fault_free
            and self.node_losses >= 2
            and self.flaps >= 1
            and self.requeues >= 1
            and self.pin_verified_rescues >= 1
            and self.drain_evictions >= 1
            and self.drains_completed >= 1
            and self.failovers >= 1
            and self.recoveries >= self.require_recoveries
            and self.worker_crashes >= self.require_worker_crashes
            and self.failslow_degraded >= self.require_failslow
            and self.failslow_recovered >= self.require_failslow
        )

    def as_dict(self) -> dict:
        return {
            "seed": self.seed,
            "ticks": self.ticks,
            "faults": self.faults,
            "node_losses": self.node_losses,
            "flaps": self.flaps,
            "rescues": len(self.rescues),
            "pin_verified_rescues": self.pin_verified_rescues,
            "requeues": self.requeues,
            "drain_evictions": self.drain_evictions,
            "drains_completed": self.drains_completed,
            "failovers": self.failovers,
            "recoveries": self.recoveries,
            "replayed_records": self.replayed_records,
            "torn_tails": self.torn_tails,
            "lost_unacked_records": self.lost_unacked_records,
            "recovery_wall_seconds": round(self.recovery_wall_seconds, 4),
            "scheduler_errors": self.scheduler_errors,
            "invariant_violations": self.invariant_violations,
            "flight_bundles": self.flight_bundles,
            "remediator_armed": self.remediator_armed,
            "remediations_executed": self.remediations_executed,
            "remediations_skipped": self.remediations_skipped,
            "worker_crashes": self.worker_crashes,
            "failslow_degraded": self.failslow_degraded,
            "failslow_recovered": self.failslow_recovered,
            "converged": self.converged,
            "signature_matches_fault_free": self.signature_matches_fault_free,
            "ok": self.ok,
        }


def resource_signature(store) -> List[tuple]:
    """Placement-free shape of the converged resource tree: gangs with
    phase + per-group (size, floor), cliques with replica/ready counts.
    Node assignments are deliberately EXCLUDED — a post-failure cluster
    legitimately places elsewhere; what must match a fault-free run is the
    tree itself."""
    sig: List[tuple] = []
    for gang in sorted(
        store.scan("PodGang"),
        key=lambda g: (g.metadata.namespace, g.metadata.name),
    ):
        groups = tuple(
            sorted(
                (g.name, len(g.pod_references), g.min_replicas)
                for g in gang.spec.pod_groups
            )
        )
        sig.append(
            (
                "pg",
                gang.metadata.namespace,
                gang.metadata.name,
                gang.status.phase,
                groups,
            )
        )
    for pclq in sorted(
        store.scan("PodClique"),
        key=lambda c: (c.metadata.namespace, c.metadata.name),
    ):
        sig.append(
            (
                "pclq",
                pclq.metadata.namespace,
                pclq.metadata.name,
                pclq.status.replicas,
                pclq.status.ready_replicas,
            )
        )
    return sig


class ChaosRunner:
    """One seeded chaos run over a fresh SimHarness."""

    def __init__(
        self,
        seed: int = 1234,
        num_nodes: int = 16,
        n_each: int = 2,
        tick_seconds: float = 1.0,
        not_ready_after: float = 5.0,
        lost_after: float = 15.0,
        controlplane_crash: bool = False,
        durability_dir: Optional[str] = None,
        remediator: bool = False,
        failslow: bool = False,
    ) -> None:
        self.seed = seed
        # failslow: arm the gray-failure arm — suspicion EWMA on the
        # monitor, a seeded fail-slow node in the schedule, Degraded →
        # heal → Ready required by the verdict
        self.failslow = failslow
        self.failslow_threshold = 1.5
        self.failslow_recover = 0.75
        self.num_nodes = num_nodes
        self.n_each = n_each
        self.tick_seconds = tick_seconds
        self.not_ready_after = not_ready_after
        self.lost_after = lost_after
        # controlplane_crash: run the store durably (WAL + snapshots) and
        # kill store+engine mid-convergence — recovery must rebuild the
        # control plane from disk (docs/robustness.md durability section)
        self.controlplane_crash = controlplane_crash
        # remediator-armed mode (docs/observability.md "Remediation &
        # ledger"): the SLO observatory + remediation controller run live
        # through the WHOLE fault schedule — every action it takes must
        # keep the chaos invariants (budget invariant 4 above all) green
        self.remediator_armed = remediator
        self._own_durability_dir = controlplane_crash and durability_dir is None
        if self._own_durability_dir:
            import tempfile

            durability_dir = tempfile.mkdtemp(prefix="grove-chaos-wal-")
        self.durability_dir = durability_dir
        self.harness = self._build_harness(durable=controlplane_crash)
        self.report = ChaosReport(
            seed=seed,
            require_recoveries=1 if controlplane_crash else 0,
            require_failslow=1 if failslow else 0,
        )
        self._breach_since: Dict[Tuple[str, str], float] = {}
        self._outage_ops = ("create", "update")
        # flight recorder (observability/flightrec.py): armed for the
        # chaotic run so every invariant violation ships its postmortem
        # bundle with the verdict. Test hook: a rel-time at which one
        # clearly-labeled synthetic violation is injected, exercising the
        # dump path end to end without breaking a real invariant.
        self.flight_recorder = True
        self.inject_invariant_failure_at: Optional[float] = None
        self._injected_failure_done = False
        # rescue archives of deposed leaders (the monitor is leader memory;
        # a failover swaps it — completed-rescue records must survive for
        # the report's pin verification)
        self._archived_rescues: List[dict] = []

    def _build_harness(self, durable: bool = False) -> SimHarness:
        h = SimHarness(
            num_nodes=self.num_nodes,
            durability_dir=self.durability_dir if durable else None,
        )
        if h.durability is not None:
            # chaos-sized knobs: force segment rotation AND a mid-run
            # snapshot+truncation, so recovery replays a snapshot base
            # plus a multi-segment tail — under fire, not just in units
            h.durability.wal.segment_max_bytes = 64 * 1024
            h.durability.snapshot_every_bytes = 256 * 1024
        h.node_monitor.not_ready_after = self.not_ready_after
        h.node_monitor.lost_after = self.lost_after
        if self.failslow:
            self._arm_failslow_monitor(h.node_monitor)
        for pcs in chaos_workload(self.n_each):
            h.apply(pcs)
        return h

    def _arm_failslow_monitor(self, monitor) -> None:
        """Turn the suspicion EWMA on with chaos-speed thresholds: the
        injected lag band sits BELOW the binary NotReady grace, so only
        this detector can see the sick node."""
        monitor.failslow_threshold = self.failslow_threshold
        monitor.failslow_recover = self.failslow_recover

    # -- schedule construction -------------------------------------------

    def _node_of_one_pod(self, prefix: str, exclude: set) -> Optional[str]:
        """A node hosting exactly one pod of a `prefix-*` gang whose gang
        has survivors elsewhere — the cleanest rescue target. Falls back to
        any node hosting a pod of that shape."""
        per_node: Dict[str, int] = {}
        candidates: List[str] = []
        h = self.harness
        for (ns, pod_name), node in sorted(h.cluster.bindings.items()):
            if pod_name.startswith(prefix) and node not in exclude:
                per_node[node] = per_node.get(node, 0) + 1
        for node, count in sorted(per_node.items()):
            if count == 1:
                candidates.append(node)
        return (candidates or sorted(per_node) or [None])[0]

    def build_schedule(self, rng: random.Random) -> List[Fault]:
        """Deterministic fault schedule against the converged steady state:
        two real node losses (one hitting a packed gang → rescue via
        recovery pin; one hitting a strict gang → gang requeue), one flap,
        one transient store outage. Times jittered from the seed; targets
        resolved from the (deterministic) steady-state placement."""
        used: set = set()
        loss1 = self._node_of_one_pod("packed-", used)
        used.add(loss1)
        loss2 = self._node_of_one_pod("strict-", used)
        used.add(loss2)
        flap = self._node_of_one_pod("plain-", used) or self._node_of_one_pod(
            "packed-", used
        )
        used.add(flap)
        assert loss1 and loss2 and flap, "steady state left shapes unplaced"
        dead_dwell = self.lost_after + 6.0  # comfortably past the grace
        faults = [
            Fault(rng.uniform(1, 3), "crash", loss1, "loss→rescue (packed)"),
            Fault(
                rng.uniform(4, 6), "crash", loss2, "loss→requeue (strict)"
            ),
            Fault(rng.uniform(7, 9), "crash", flap, "flap begin"),
        ]
        # the flap restarts inside the grace window (NotReady, never Lost)
        flap_start = faults[2].at
        faults.append(
            Fault(
                flap_start
                + self.not_ready_after
                + rng.uniform(1.0, self.lost_after - self.not_ready_after - 2.0),
                "restart",
                flap,
                "flap end (inside grace)",
            )
        )
        # transient store outage while recovery is in flight
        outage_at = rng.uniform(10, 14)
        faults.append(Fault(outage_at, "outage_begin", note="store outage"))
        faults.append(
            Fault(outage_at + rng.uniform(2.0, 4.0), "outage_end")
        )
        # voluntary disruption: drain a node hosting a BUDGETED (plain)
        # gang after the outage has cleared — cordon, budget-checked
        # gang-whole eviction with trial-solve pre-placement
        drain = self._node_of_one_pod("plain-", used)
        assert drain, "no drainable node hosts a plain pod"
        used.add(drain)
        drain_at = rng.uniform(18.5, 19.5)
        faults.append(
            Fault(drain_at, "drain", drain, "voluntary drain (budgeted)")
        )
        # kill the leader mid-drain: the standby takes the lease, rebuilds
        # leader memory from the store (requeue_all, rebuild_bindings,
        # monitor resync, persisted NodeDrain intents) and finishes the job
        faults.append(
            Fault(
                drain_at + rng.uniform(0.5, 1.5),
                "leader_crash",
                note="failover mid-drain",
            )
        )
        # worker-process executor armed (GROVE_TPU_CP_BACKEND=process):
        # SIGKILL a reconcile worker while the late re-admission burst is
        # in flight — the coordinator must repatriate its shards and
        # re-execute its keys inline, deterministically (never hang).
        # Scheduled AFTER the leader crash: failover swaps the engine,
        # and a kill armed on the deposed drain would be torn down unfired
        if hasattr(self.harness.engine.workers, "chaos_kill_worker"):
            self.report.require_worker_crashes = 1
            faults.append(
                Fault(
                    dead_dwell + rng.uniform(1.0, 2.0),
                    "worker_crash",
                    note="SIGKILL reconcile worker mid-round (process"
                    " executor); repatriate + inline re-execution",
                )
            )
        # lost nodes come back late — capacity returns, requeued gangs must
        # re-admit atomically
        for i, node in enumerate((loss1, loss2)):
            faults.append(
                Fault(
                    dead_dwell + rng.uniform(0, 3) + 2 * i,
                    "restart",
                    node,
                    "capacity returns",
                )
            )
        if self.controlplane_crash:
            # kill store+engine after capacity returned, while re-admission
            # is still converging: recovery must rebuild the whole control
            # plane from the WAL/snapshot (torn tail injected at the crash)
            # and the rehydrated holds/backoff must finish the job
            faults.append(
                Fault(
                    dead_dwell + rng.uniform(5.2, 5.8),
                    "controlplane_crash",
                    note="store+engine crash, recover from disk",
                )
            )
        # the drained node rejoins the pool once everything else is back
        faults.append(
            Fault(
                dead_dwell + rng.uniform(6.0, 8.0),
                "uncordon",
                drain,
                "drained node returns to service",
            )
        )
        if self.failslow:
            # gray failure: a FOURTH node goes fail-slow mid-run — late
            # heartbeats inside the NotReady grace (binary detector
            # blind), healed only after everything else recovered. Drawn
            # last so the unarmed schedule keeps its exact rng sequence.
            gray = self._node_of_one_pod(
                "packed-", used
            ) or self._node_of_one_pod("plain-", used)
            assert gray, "no candidate node for the fail-slow fault"
            used.add(gray)
            faults.append(
                Fault(
                    rng.uniform(11, 13),
                    "failslow_begin",
                    gray,
                    "gray failure: heartbeats late, below binary grace",
                )
            )
            faults.append(
                Fault(
                    dead_dwell + rng.uniform(9.0, 10.0),
                    "failslow_end",
                    gray,
                    "fail-slow healed (suspicion must decay to Ready)",
                )
            )
        faults.sort(key=lambda f: f.at)
        return faults

    def _apply_fault(self, fault: Fault) -> None:
        h = self.harness
        if fault.kind == "crash":
            h.cluster.crash_node(fault.target)
        elif fault.kind == "restart":
            h.cluster.restart_node(fault.target)
        elif fault.kind == "outage_begin":

            def inject(_obj):
                return GroveError(
                    "ERR_STORE_OUTAGE", "injected transient outage", "write"
                )

            for op in self._outage_ops:
                h.store.error_injectors[op] = inject
        elif fault.kind == "outage_end":
            for op in self._outage_ops:
                h.store.error_injectors.pop(op, None)
        elif fault.kind == "drain":
            h.drainer.request_drain(fault.target)
        elif fault.kind == "uncordon":
            h.drainer.uncordon(fault.target)
        elif fault.kind == "leader_crash":
            self._leader_failover()
        elif fault.kind == "controlplane_crash":
            self._controlplane_crash()
        elif fault.kind == "worker_crash":
            self._worker_crash()
        elif fault.kind == "failslow_begin":
            # lag band strictly below not_ready_after=5.0: the binary
            # detector must stay blind for the arm to prove anything
            h.cluster.inject_failslow(
                fault.target,
                seed=self.seed,
                lag_min=2.0,
                lag_max=4.5,
                start_penalty=10.0,
            )
        elif fault.kind == "failslow_end":
            h.cluster.heal_failslow(fault.target)
        self.report.faults.append(fault.as_dict())

    def _worker_crash(self) -> None:
        """Arm the process executor's chaos hook: the reconcile worker
        owning the workload shard is SIGKILLed right after the next batch
        is dispatched to it (runtime/procworkers.py `chaos_kill_worker`).
        Thread-backend and serial control planes have no worker process
        to kill — the fault degrades to a no-op there, and the schedule
        only requires a crash when the process drain is armed."""
        h = self.harness
        drain = h.engine.workers
        if drain is None or not hasattr(drain, "chaos_kill_worker"):
            return
        # the chaos workload lives in one namespace, so its shard's owner
        # is the worker guaranteed to receive batches; lane 0 is the
        # coordinator itself (no process), so fall back to worker 1
        victim = drain.worker_of(h.store.shard_index("default"))
        drain.chaos_kill_worker = victim if victim != 0 else 1
        # the kill fires at the next batch DISPATCHED to the victim — a
        # quiet engine would never give it one. Storm the queue first:
        # requeue_all is a level-triggered re-list (semantically a no-op
        # for idempotent controllers), so this tick's drain is guaranteed
        # to have a round in flight for the SIGKILL to land mid-round
        h.engine.requeue_all()

    # -- control-plane crash (tentpole: durability + recovery) -------------

    def _controlplane_crash(self) -> None:
        """Kill the store process itself — the one fault PR 5's failover
        cannot model (there the store survives; here NOTHING in memory
        does). The WAL's unflushed buffer dies with the process and the
        interrupted write leaves a torn frame on disk. Recovery: rebuild
        the store from snapshot + WAL tail (truncate at the first bad
        CRC), audit the acked prefix (no acked commit lost, no phantom
        state), then cold-boot a full control plane over it with the PR-5
        resync machinery (requeue_all / rebuild_bindings / monitor
        resync / fresh broker+drainer). Node kubelets are separate
        processes — the Node objects carry over with their live state."""
        from grove_tpu.durability import recover_store, verify_acked_prefix

        h = self.harness
        report = self.report
        self._archived_rescues.extend(h.node_monitor.rescues)
        h.engine.close()
        report.lost_unacked_records += h.durability.simulate_crash(
            torn_tail_bytes=41
        )
        store, recovery = recover_store(
            self.durability_dir, clock=h.clock, cache_lag=True
        )
        report.replayed_records += recovery.replayed_records
        report.recovery_wall_seconds += recovery.wall_seconds
        if recovery.torn_tail:
            report.torn_tails += 1
        # recovery invariant 6: the recovered store IS the durable prefix —
        # audited independently against the on-disk log, before any new
        # commit can blur the comparison
        for problem in verify_acked_prefix(self.durability_dir, store):
            report.invariant_violations.append(f"recovery: {problem}")
        restarted = SimHarness.cold_restart(
            store,
            h.cluster.nodes,
            config=h.config,
            durability_dir=self.durability_dir,
        )
        restarted.durability.wal.segment_max_bytes = 64 * 1024
        restarted.durability.snapshot_every_bytes = 256 * 1024
        restarted.node_monitor.not_ready_after = self.not_ready_after
        restarted.node_monitor.lost_after = self.lost_after
        if self.failslow:
            self._arm_failslow_monitor(restarted.node_monitor)
        # an armed fail-slow fault is node state: it rides through the
        # control-plane crash onto the rebuilt SimCluster
        for name in sorted(h.cluster.failslow_names()):
            restarted.cluster.inject_failslow(
                name, *h.cluster.failslow_spec(name)
            )
        # the rebuilt monitor re-primes holds from persisted conditions
        # with the chaos-speed grace windows in place
        restarted.node_monitor.resync()
        if self.remediator_armed:
            # the recovered control plane comes up with a fresh (disabled)
            # remediator — re-arm it; the ledger itself is process-global
            # and survives the crash (it is observability, not leader state)
            self._arm_remediator(restarted)
        self.harness = restarted
        report.recoveries += 1

    # -- leader failover (satellite: leader_crash fault kind) -------------

    def _leader_failover(self) -> None:
        """Crash the leader and promote a standby through the REAL
        LeaseElector protocol, then rebuild every piece of leader memory
        the way cluster/manager.py's run loop does on takeover: fresh
        engine (+ requeue_all), fresh binding map (rebuild_bindings),
        fresh monitor re-primed from persisted conditions (resync), fresh
        scheduler/broker/drainer. Cluster INFRASTRUCTURE — the Node
        objects and the store — carries over; leader memory does not."""
        import time as _time

        from grove_tpu.autoscale.hpa import HorizontalAutoscaler
        from grove_tpu.cluster.lease import LeaseElector
        from grove_tpu.controller.nodehealth import NodeHealthMonitor
        from grove_tpu.controller.register import register_controllers
        from grove_tpu.disruption import (
            DisruptionBroker,
            NodeDrainController,
        )
        from grove_tpu.runtime.engine import Engine
        from grove_tpu.sim.cluster import SimCluster
        from grove_tpu.solver.scheduler import GangScheduler

        h = self.harness
        timings = dict(
            lease_duration=0.3, renew_deadline=0.2, retry_period=0.05
        )
        leader = LeaseElector(
            h.store, identity="chaos-leader", **timings
        )
        assert leader.try_acquire(), "incumbent failed to take the lease"
        leader.stop_renewing()  # crash: the lease ages out un-renewed
        standby = LeaseElector(
            h.store, identity="chaos-standby", **timings
        )
        deadline = _time.monotonic() + 15.0
        while not standby.try_acquire():
            assert (
                _time.monotonic() < deadline
            ), "standby never took over the lease"
            # grovelint: disable=GL001 -- real wall-clock wait: the LeaseElector protocol ages the lease on real time (cluster/lease.py is wall-clock by design); bounded by the deadline above
            _time.sleep(0.05)

        # deposed leader's engine stops draining; the standby builds fresh
        h.engine.close()
        engine = Engine(h.store, h.clock)
        register_controllers(engine, h.ctx, h.config)
        engine.requeue_all()
        cluster = SimCluster(store=h.store, nodes=h.cluster.nodes)
        cluster.rebuild_bindings()
        # fail-slow is NODE state, not leader memory — an armed gray
        # fault must survive the SimCluster rebuild (public accessor:
        # GL022 bans grafting the registry directly)
        for name in sorted(h.cluster.failslow_names()):
            cluster.inject_failslow(name, *h.cluster.failslow_spec(name))
        scheduler = GangScheduler(
            h.store,
            cluster,
            h.topology,
            priority_map=h.config.solver.priority_classes,
            chunk_size=min(h.config.solver.chunk_size, 64),
            max_waves=h.config.solver.max_waves,
        )
        monitor = NodeHealthMonitor(
            h.store,
            cluster,
            not_ready_after=self.not_ready_after,
            lost_after=self.lost_after,
        )
        if self.failslow:
            self._arm_failslow_monitor(monitor)
        scheduler.monitor = monitor
        broker = DisruptionBroker(h.store)
        scheduler.broker = broker
        h.ctx.disruption = broker
        drainer = NodeDrainController(
            h.store, cluster, scheduler, monitor, broker
        )
        monitor.drain_states = drainer.states
        monitor.resync()
        self._archived_rescues.extend(h.node_monitor.rescues)
        h.engine = engine
        h.cluster = cluster
        h.scheduler = scheduler
        h.node_monitor = monitor
        h.disruption = broker
        h.drainer = drainer
        h.autoscaler = HorizontalAutoscaler(
            h.store, h.metrics_provider, scale_down_stabilization=60.0
        )
        # remediator + its explain engine are leader memory over the
        # swapped components — rebuild both (policy config carries over;
        # cooldowns/pending effect windows die with the deposed leader)
        self._rebuild_remediator(h)
        self.report.failovers += 1

    def _rebuild_remediator(self, h: SimHarness) -> None:
        """Fresh explain engine + remediation controller over the current
        component set, re-armed with the chaos policy if this run has the
        remediator armed (harness-built ones start disabled)."""
        from grove_tpu.controller.remediate import RemediationController
        from grove_tpu.observability.explain import ExplainEngine

        h.explain = ExplainEngine(h.scheduler)
        h.remediator = RemediationController(
            h.store,
            h.cluster,
            h.scheduler,
            h.drainer,
            h.disruption,
            h.autoscaler,
            h.explain,
        )
        if self.remediator_armed:
            self._arm_remediator(h)

    def _arm_remediator(self, h: SimHarness) -> None:
        """Chaos-speed remediation policy: tight cooldown (the whole run
        is ~1 virtual minute), fragmentation trigger live, effects
        measured against the ready_fraction budget."""
        h.remediator.enable(
            effect_slo="ready_fraction",
            effect_window=10.0,
            cooldown=5.0,
            frag_threshold=0.6,
        )

    # -- invariants -------------------------------------------------------

    def _check_invariants(self, rel_now: float) -> None:
        try:
            self._check_invariants_inner(rel_now)
        finally:
            self._flight_record_violations(rel_now)

    def _flight_record_violations(self, rel_now: float) -> None:
        """Dump a flight-recorder bundle when this tick's invariant sweep
        grew the violation list (the test hook injects one synthetic,
        clearly-labeled violation so the dump path itself is exercised
        without breaking a real invariant)."""
        violations = self.report.invariant_violations
        if (
            self.inject_invariant_failure_at is not None
            and not self._injected_failure_done
            and rel_now >= self.inject_invariant_failure_at
        ):
            self._injected_failure_done = True
            violations.append(
                f"t={rel_now:.0f}s: INJECTED invariant failure"
                " (flight-recorder test hook, not a real breach)"
            )
        n_seen = getattr(self, "_violations_recorded", 0)
        if len(violations) > n_seen:
            self._violations_recorded = len(violations)
            from grove_tpu.observability.flightrec import FLIGHTREC

            if FLIGHTREC.enabled:
                bundle = FLIGHTREC.trigger(
                    "chaos-invariant", violations[n_seen]
                )
                if bundle is not None:
                    self.report.flight_bundles.append(bundle)

    def _check_invariants_inner(self, rel_now: float) -> None:
        h = self.harness
        violations = self.report.invariant_violations
        # 1. no binding to a Lost node
        lost = {n.name for n in h.cluster.nodes if n.state == NODE_LOST}
        for (ns, pod_name), node in sorted(h.cluster.bindings.items()):
            if node in lost:
                violations.append(
                    f"t={rel_now:.0f}s: pod {ns}/{pod_name} still bound to "
                    f"lost node {node}"
                )
        # 2. no scheduled gang below its floor past the grace window
        now = h.clock.now()
        slack = self.lost_after + 4 * self.tick_seconds
        for gang in h.store.scan("PodGang"):
            key = (gang.metadata.namespace, gang.metadata.name)
            cond = get_condition(
                gang.status.conditions, COND_PODGANG_SCHEDULED
            )
            if cond is None or not cond.is_true():
                self._breach_since.pop(key, None)
                continue
            below = any(
                sum(
                    1
                    for ref in group.pod_references
                    if (ref.namespace, ref.name) in h.cluster.bindings
                )
                < group.min_replicas
                for group in gang.spec.pod_groups
            )
            if not below:
                self._breach_since.pop(key, None)
                continue
            since = self._breach_since.setdefault(key, now)
            if now - since > slack:
                violations.append(
                    f"t={rel_now:.0f}s: scheduled gang {key[0]}/{key[1]} "
                    f"below MinReplicas for {now - since:.0f}s "
                    f"(> grace {slack:.0f}s)"
                )
        # 3a. incremental quota accounting equals a full recount (the
        # tick-boundary exactness check shared with the sanitizer)
        for problem in accountant_drift(
            h.scheduler.quota.accountant, h.store
        ):
            violations.append(f"t={rel_now:.0f}s: {problem}")
        # 3b. no node is committed beyond its capacity
        used = h.cluster._used_by_node()
        for node in h.cluster.nodes:
            for r, v in used.get(node.name, {}).items():
                if v > node.capacity.get(r, 0.0) + 1e-6:
                    violations.append(
                        f"t={rel_now:.0f}s: node {node.name} overcommitted "
                        f"on {r}: {v} > {node.capacity.get(r, 0.0)}"
                    )
        # 4. no disruptionBudget ever exceeded (voluntary disruptions only)
        for pcs in h.store.scan("PodCliqueSet"):
            budget = pcs.spec.template.disruption_budget
            if budget is None:
                continue
            key = (pcs.metadata.namespace, pcs.metadata.name)
            disrupted = h.disruption.voluntarily_disrupted_gangs(key)
            cap = budget.max_unavailable_gangs or 0
            if disrupted > cap:
                violations.append(
                    f"t={rel_now:.0f}s: PCS {key[0]}/{key[1]} has "
                    f"{disrupted} voluntarily-disrupted gang(s), budget "
                    f"allows {cap}"
                )
        # 5. no stranded hold: every monitor-held gang keeps a scheduled
        # release (a hold with no delayed workqueue entry waits forever —
        # same check the sanitizer reruns at teardown)
        for problem in stranded_holds(h.node_monitor):
            violations.append(f"t={rel_now:.0f}s: {problem}")
        # 7. no phantom binding after a recovery: every binding the
        # scheduler charges capacity for must be backed by a committed
        # pod actually scheduled to that node (a recovery that resurrected
        # leader memory without store backing would overcommit silently)
        if self.report.recoveries:
            from grove_tpu.api.pod import is_scheduled

            for (ns, pod_name), node in sorted(h.cluster.bindings.items()):
                pod = h.store.get("Pod", ns, pod_name, readonly=True)
                if pod is None or not is_scheduled(pod) or (
                    pod.status.node_name != node
                ):
                    violations.append(
                        f"t={rel_now:.0f}s: phantom binding after recovery:"
                        f" pod {ns}/{pod_name} charged to {node} without a"
                        " matching committed binding"
                    )

    def _remediation_tick(self, h: SimHarness) -> int:
        """One observatory round + one policy round, in harness order:
        sample → judge burns → remediate on THIS tick's verdicts."""
        from grove_tpu.observability.slo import SLO
        from grove_tpu.observability.timeseries import TIMESERIES

        TIMESERIES.sample(h.clock.now())
        SLO.evaluate(h.clock.now())
        return self._guarded(h.remediator.tick)

    def _guarded(self, fn) -> int:
        """Run one control-plane component; a transient store error models
        that component's process crash-looping (it retries next tick)."""
        try:
            return fn() or 0
        except GroveError:
            self.report.scheduler_errors += 1
            return 1  # counted as work: the loop must keep ticking

    # -- run ---------------------------------------------------------------

    def run(self, max_ticks: int = 400) -> ChaosReport:
        h = self.harness
        rng = random.Random(self.seed)
        report = self.report
        losses_before = METRICS.counters.get("node_lost_total", 0)
        flaps_before = METRICS.counters.get("node_flaps_total", 0)
        requeues_before = METRICS.counters.get("gang_requeues_total", 0)
        drains_before = METRICS.counters.get("gang_drains_total", 0)
        drains_done_before = METRICS.counters.get(
            "node_drains_completed_total", 0
        )
        wcrashes_before = METRICS.counters.get("cp_worker_crashes_total", 0)
        degraded_before = METRICS.counters.get("node_degraded_total", 0)
        recovered_before = METRICS.counters.get("node_recovered_total", 0)

        # fault-free twin FIRST (same workload, converged, untouched): the
        # convergence target the chaotic run must reproduce
        twin = self._build_harness()
        twin.converge(max_ticks=120)
        twin_sig = resource_signature(twin.store)
        # building a SimHarness re-points the process-global EVENTS/TRACER
        # clocks ("newest harness wins"); the chaotic run is the one whose
        # event timestamps must stay live — point them back
        from grove_tpu.observability.events import EVENTS
        from grove_tpu.observability.tracing import TRACER

        EVENTS.clock = h.clock
        TRACER.clock = h.clock
        if self.flight_recorder:
            # arm the postmortem rings for the CHAOTIC run only (the twin
            # above is the reference, not the subject); every invariant
            # violation below ships its bundle via _flight_record_violations
            from grove_tpu.observability.flightrec import FLIGHTREC

            import os as _os

            FLIGHTREC.enable(
                num_shards=getattr(h.store, "num_shards", 1),
                clock=h.clock,
                out_dir=_os.environ.get("GROVE_TPU_FLIGHTREC_DIR") or None,
            )

        h.converge(max_ticks=120)  # steady state before the first fault
        if self.remediator_armed:
            # arm the detect→act loop for the CHAOTIC run only, from the
            # steady state on: observatory sampling + burn judging run in
            # the manual tick loop below, remediation actions flow through
            # the same broker/drainer/autoscaler the invariants police
            from grove_tpu.observability.ledger import LEDGER
            from grove_tpu.observability.slo import SLO
            from grove_tpu.observability.timeseries import TIMESERIES
            from grove_tpu.sim.traffic import default_slos

            report.remediator_armed = True
            TIMESERIES.reset()
            SLO.reset()
            LEDGER.reset()
            TIMESERIES.enable(clock=h.clock)
            SLO.enable()
            for text in default_slos():
                SLO.add(text)
            LEDGER.enable(clock=h.clock)
            self._arm_remediator(h)
        t0 = h.clock.now()
        faults = self.build_schedule(rng)
        i = 0
        idle_ticks = 0
        for _tick in range(max_ticks):
            # refetch every tick: a controlplane_crash fault swaps the
            # WHOLE harness (store included) for the recovered one
            h = self.harness
            rel = h.clock.now() - t0
            while i < len(faults) and faults[i].at <= rel:
                self._apply_fault(faults[i])
                i += 1
                h = self.harness
            work = self._guarded(h.engine.drain)
            work += self._guarded(h.autoscaler.tick)
            work += self._guarded(h.node_monitor.tick)
            work += self._guarded(h.drainer.tick)
            bound = self._guarded(h.schedule)
            started = self._guarded(h.cluster.kubelet_tick)
            work += self._guarded(h.engine.drain)
            if self.remediator_armed:
                work += self._remediation_tick(h)
            if h.durability is not None:
                # group commit at the tick boundary (the sim committer)
                h.durability.pump()
            self._check_invariants(rel)
            report.ticks += 1
            if i >= len(faults) and not work and not bound and not started:
                idle_ticks += 1
                wakes = [
                    w
                    for w in (
                        h.engine.next_wakeup(),
                        h.autoscaler.next_deadline(),
                        h.node_monitor.next_deadline(),
                        h.drainer.next_deadline(),
                        h.remediator.next_deadline(),
                    )
                    if w is not None
                ]
                wake = min(wakes) if wakes else None
                if wake is not None and wake - h.clock.now() <= 120.0:
                    h.clock.advance(max(wake - h.clock.now(), 0.0))
                    continue
                if idle_ticks >= 2:
                    break
            else:
                idle_ticks = 0
            # never jump past the next scheduled fault
            step = self.tick_seconds
            if i < len(faults):
                step = min(step, max(faults[i].at - rel, 1e-3))
            h.clock.advance(step)

        report.node_losses = int(
            METRICS.counters.get("node_lost_total", 0) - losses_before
        )
        report.flaps = int(
            METRICS.counters.get("node_flaps_total", 0) - flaps_before
        )
        report.requeues = int(
            METRICS.counters.get("gang_requeues_total", 0) - requeues_before
        )
        report.drain_evictions = int(
            METRICS.counters.get("gang_drains_total", 0) - drains_before
        )
        report.drains_completed = int(
            METRICS.counters.get("node_drains_completed_total", 0)
            - drains_done_before
        )
        report.worker_crashes = int(
            METRICS.counters.get("cp_worker_crashes_total", 0)
            - wcrashes_before
        )
        report.failslow_degraded = int(
            METRICS.counters.get("node_degraded_total", 0) - degraded_before
        )
        report.failslow_recovered = int(
            METRICS.counters.get("node_recovered_total", 0)
            - recovered_before
        )
        report.rescues = self._archived_rescues + list(h.node_monitor.rescues)
        report.pin_verified_rescues = sum(
            1 for r in report.rescues if r.get("rejoined_domain")
        )

        # convergence: every gang Running, every pod Ready, every node back
        pods = h.store.list("Pod")
        gangs = h.store.scan("PodGang")
        unhealthy = {
            n.name for n in h.cluster.nodes if n.state != NODE_READY
        }
        report.converged = (
            bool(pods)
            and all(is_ready(p) for p in pods)
            and all(g.status.phase == PHASE_RUNNING for g in gangs)
            and not any(
                p.status.node_name in unhealthy for p in pods
            )
        )
        report.signature_matches_fault_free = (
            resource_signature(h.store) == twin_sig
        )
        # sanitizer teardown sweep (GROVE_TPU_SANITIZE=1): lock-order
        # inversions, leaked spans, stranded holds, accountant drift, and
        # the store's byte-compare guard — recorded as invariant
        # violations so the smoke's verdict covers them
        if sanitize.active():
            report.invariant_violations.extend(
                f"sanitizer: {p}" for p in sanitize.harness_problems(h)
            )
        if self.remediator_armed:
            # tally the causal chains, then disarm the process-global
            # layers (same discipline as the flight recorder below)
            from grove_tpu.observability.ledger import LEDGER
            from grove_tpu.observability.slo import SLO
            from grove_tpu.observability.timeseries import TIMESERIES

            report.remediations_executed = len(
                LEDGER.entries(outcome="executed")
            )
            report.remediations_skipped = len(
                LEDGER.entries(outcome="skipped")
            )
            h.remediator.disable()
            LEDGER.disable()
            SLO.disable()
            TIMESERIES.disable()
        if self.flight_recorder:
            # disarm the process-global recorder (dumped bundles stay on
            # disk; the report carries their paths) so later runs/tests in
            # this process aren't silently recording
            from grove_tpu.observability.flightrec import FLIGHTREC

            FLIGHTREC.disable()
        if h.durability is not None:
            h.durability.close()
        if self._own_durability_dir:
            import shutil

            shutil.rmtree(self.durability_dir, ignore_errors=True)
        return report


def run_chaos(
    seed: int = 1234,
    num_nodes: int = 16,
    n_each: int = 2,
    max_ticks: int = 400,
    controlplane_crash: bool = False,
    remediator: bool = False,
    failslow: bool = False,
) -> ChaosReport:
    """One seeded end-to-end chaos run (the `make chaos-smoke` core)."""
    return ChaosRunner(
        seed=seed,
        num_nodes=num_nodes,
        n_each=n_each,
        controlplane_crash=controlplane_crash,
        remediator=remediator,
        failslow=failslow,
    ).run(max_ticks=max_ticks)


def chaos_artifact(seed: int = 1234) -> dict:
    """Compact chaos block for the integrated bench artifact."""
    report = run_chaos(seed=seed)
    doc = report.as_dict()
    doc.pop("faults", None)
    doc.pop("invariant_violations", None)
    doc["invariant_violation_count"] = len(report.invariant_violations)
    return doc


# -- federation chaos (docs/federation.md "cluster_crash") -------------------


@dataclass
class FederationChaosReport:
    """Verdict of one seeded federation chaos run: a whole REGION is
    killed mid-traffic and later restored, with the router's re-route
    machinery under the per-tick invariants below."""

    seed: int
    regions: int = 0
    ticks: int = 0
    faults: List[dict] = field(default_factory=list)
    applied: int = 0
    cluster_crashes: int = 0
    rejoins: int = 0
    reroutes: int = 0
    spillovers: int = 0
    stranded: int = 0
    invariant_checks: int = 0
    invariant_violations: List[str] = field(default_factory=list)
    converged: bool = False

    @property
    def ok(self) -> bool:
        return (
            not self.invariant_violations
            and self.converged
            and self.cluster_crashes >= 1
            and self.rejoins >= 1
            and self.reroutes >= 1
            and self.stranded == 0
        )

    def as_dict(self) -> dict:
        return {
            "seed": self.seed,
            "regions": self.regions,
            "ticks": self.ticks,
            "faults": self.faults,
            "applied": self.applied,
            "cluster_crashes": self.cluster_crashes,
            "rejoins": self.rejoins,
            "reroutes": self.reroutes,
            "spillovers": self.spillovers,
            "stranded": self.stranded,
            "invariant_checks": self.invariant_checks,
            "invariant_violations": self.invariant_violations,
            "converged": self.converged,
            "ok": self.ok,
        }


class FederationChaosRunner:
    """One seeded chaos run over a fresh FederationRouter.

    The fault schedule is the `cluster_crash` fault: a second traffic
    wave lands, the busiest region is killed while that wave is still
    converging (genuinely mid-traffic), the survivors absorb the
    re-routes under the ordinary broker/budget machinery, and a later
    `cluster_rejoin` restores the region with a fresh control plane
    (a post-rejoin wave homed there proves it serves again). Two
    federation-specific per-tick invariants ride on top of the
    single-cluster set (quota drift, disruption budgets):

    F1. no gang is placed in — and no placement record points at — a
        dead cluster (a Lost region's harness is gone entirely);
    F2. the global quota fold's root equals the sum of independent
        per-cluster usage recounts (the level-3 analogue of the
        accountant-vs-oracle exactness check).
    """

    def __init__(
        self,
        seed: int = 1234,
        regions: int = 3,
        num_nodes: int = 8,
        n_each: int = 2,
        spill_after: float = 5.0,
    ) -> None:
        from grove_tpu.federation import FederationRouter

        self.seed = seed
        self.n_each = n_each
        self.region_names = [f"region-{i}" for i in range(regions)]
        self.rng = random.Random(seed ^ 0xFEDE)
        self.router = FederationRouter(
            self.region_names,
            num_nodes=num_nodes,
            phase_offsets=[i * 200.0 for i in range(regions)],
            spill_after=spill_after,
        )
        self.report = FederationChaosReport(seed=seed, regions=regions)

    # -- invariants ------------------------------------------------------

    def _check_invariants(self, t0: float) -> None:
        router = self.router
        rep = self.report
        rep.invariant_checks += 1
        rel_now = router.clock.now() - t0
        violations = rep.invariant_violations
        states = {cl.region: cl for cl in router.clusters()}
        # F1: no placement in a dead cluster; Lost regions hold no
        # harness (nothing CAN be bound there), and every placement's
        # PCS actually lives in its recorded Ready region
        for (ns, name), region in sorted(router.placements().items()):
            cl = states.get(region)
            if cl is None or cl.state != "Ready" or cl.harness is None:
                violations.append(
                    f"t={rel_now:.0f}s: placement {ns}/{name} points at"
                    f" dead cluster {region}"
                )
                continue
            if cl.harness.store.get("PodCliqueSet", ns, name) is None:
                violations.append(
                    f"t={rel_now:.0f}s: placement {ns}/{name} missing"
                    f" from cluster {region}'s store"
                )
        for cl in router.clusters():
            if cl.state == "Lost" and cl.harness is not None:
                violations.append(
                    f"t={rel_now:.0f}s: lost cluster {cl.region} still"
                    " holds a live harness"
                )
        # F2: the global fold's root equals the sum of independent
        # per-cluster recounts (usage_oracle over each store's pods) —
        # and each cluster's own accountant has no local drift either
        from grove_tpu.quota.oracle import usage_oracle

        recount: dict = {}
        for cl in router.clusters():
            if cl.harness is None:
                continue
            h = cl.harness
            for problem in accountant_drift(
                h.scheduler.quota.accountant, h.store
            ):
                violations.append(
                    f"t={rel_now:.0f}s: [{cl.region}] {problem}"
                )
            oracle = usage_oracle(
                h.store.scan("Pod"),
                h.scheduler.quota.accountant.default_queue,
            )
            for q, usage in oracle.items():
                row = recount.setdefault(q, {})
                for r, v in usage.items():
                    row[r] = row.get(r, 0.0) + v
        global_usage = router.global_usage()
        for q in sorted(set(global_usage) | set(recount)):
            a = global_usage.get(q, {})
            b = recount.get(q, {})
            for r in sorted(set(a) | set(b)):
                if abs(a.get(r, 0.0) - b.get(r, 0.0)) > 1e-6:
                    violations.append(
                        f"t={rel_now:.0f}s: global fold queue {q}"
                        f" usage {r}: root {a.get(r, 0.0)} != sum of"
                        f" per-cluster recounts {b.get(r, 0.0)}"
                    )
        # per-cluster disruption budgets (chaos invariant 4, unchanged:
        # a crash re-route must never spend voluntary disruption)
        for cl in router.clusters():
            if cl.harness is None:
                continue
            h = cl.harness
            for pcs in h.store.scan("PodCliqueSet"):
                budget = pcs.spec.template.disruption_budget
                if budget is None:
                    continue
                key = (pcs.metadata.namespace, pcs.metadata.name)
                disrupted = h.disruption.voluntarily_disrupted_gangs(key)
                cap = budget.max_unavailable_gangs or 0
                if disrupted > cap:
                    violations.append(
                        f"t={rel_now:.0f}s: [{cl.region}] PCS"
                        f" {key[0]}/{key[1]} has {disrupted}"
                        f" voluntarily-disrupted gang(s), budget"
                        f" allows {cap}"
                    )

    def _all_scheduled(self) -> bool:
        for cl in self.router.clusters():
            if cl.harness is None:
                continue
            for gang in cl.harness.store.list("PodGang"):
                cond = get_condition(
                    gang.status.conditions, COND_PODGANG_SCHEDULED
                )
                if cond is None or not cond.is_true():
                    return False
        return True

    def _apply_wave(self, suffix: str, home: Optional[str] = None) -> None:
        from grove_tpu.api import names as namegen

        for pcs in chaos_workload(n_each=self.n_each):
            if suffix:
                pcs.metadata.name = f"{pcs.metadata.name}{suffix}"
            pcs.metadata.labels[namegen.LABEL_FEDERATION_HOME] = (
                home if home is not None else self.rng.choice(
                    self.region_names
                )
            )
            self.router.apply(pcs)
            self.report.applied += 1

    # -- the run ---------------------------------------------------------

    def run(self, max_ticks: int = 400) -> FederationChaosReport:
        router = self.router
        rep = self.report
        t0 = router.clock.now()
        budget = max_ticks
        # wave 1: steady state across seeded homes
        self._apply_wave("")
        rep.ticks += router.converge(max_ticks=min(60, budget))
        self._check_invariants(t0)
        # wave 2 lands, then the busiest region dies MID-convergence
        self._apply_wave("-w2")
        rep.ticks += router.converge(max_ticks=3, tick_seconds=1.0)
        counts = {name: 0 for name in self.region_names}
        for region in router.placements().values():
            counts[region] += 1
        victim = max(
            self.region_names, key=lambda name: (counts[name], name)
        )
        rep.faults.append(
            Fault(
                at=router.clock.now() - t0,
                kind="cluster_crash",
                target=victim,
                note=f"{counts[victim]} placements",
            ).as_dict()
        )
        crash = router.crash_cluster(victim)
        rep.cluster_crashes += 1
        rep.stranded += len(crash["stranded"])
        rep.ticks += router.converge(max_ticks=min(120, budget))
        self._check_invariants(t0)
        # late restart: fresh control plane, then traffic homed there
        rep.faults.append(
            Fault(
                at=router.clock.now() - t0,
                kind="cluster_rejoin",
                target=victim,
            ).as_dict()
        )
        router.rejoin_cluster(victim)
        rep.rejoins += 1
        rep.ticks += router.converge(max_ticks=40)
        self._check_invariants(t0)
        self._apply_wave("-late", home=victim)
        rep.ticks += router.converge(max_ticks=min(160, budget))
        self._check_invariants(t0)
        rep.reroutes = router.reroutes
        rep.spillovers = router.spillovers
        rep.converged = self._all_scheduled()
        return rep


def run_federation_chaos(
    seed: int = 1234,
    regions: int = 3,
    num_nodes: int = 8,
    n_each: int = 2,
    max_ticks: int = 400,
) -> FederationChaosReport:
    """One seeded federation chaos run (`chaos_smoke.py --federation`)."""
    return FederationChaosRunner(
        seed=seed, regions=regions, num_nodes=num_nodes, n_each=n_each
    ).run(max_ticks=max_ticks)


# -- partition chaos (docs/robustness.md "Gray failures") --------------------


@dataclass
class PartitionChaosReport:
    """Verdict of one seeded partition chaos run: a region becomes
    UNREACHABLE (its control plane stays alive and converging — the
    gray cousin of `cluster_crash`), pending work spills, the region
    heals, and the split-brain invariant F3 is policed every tick."""

    seed: int
    regions: int = 0
    ticks: int = 0
    faults: List[dict] = field(default_factory=list)
    applied: int = 0
    partitions: int = 0
    heals: int = 0
    partition_spills: int = 0
    placements_kept: int = 0
    placements_in_partition: int = 0
    invariant_checks: int = 0
    invariant_violations: List[str] = field(default_factory=list)
    converged: bool = False

    @property
    def ok(self) -> bool:
        return (
            not self.invariant_violations
            and self.converged
            and self.partitions >= 1
            and self.heals >= 1
            and self.partition_spills >= 1
            # every gang Scheduled inside the partition kept its
            # placement across the heal (partition ≠ crash: nothing
            # fails over that was already placed)
            and self.placements_in_partition >= 1
            and self.placements_kept == self.placements_in_partition
        )

    def as_dict(self) -> dict:
        return {
            "seed": self.seed,
            "regions": self.regions,
            "ticks": self.ticks,
            "faults": self.faults,
            "applied": self.applied,
            "partitions": self.partitions,
            "heals": self.heals,
            "partition_spills": self.partition_spills,
            "placements_kept": self.placements_kept,
            "placements_in_partition": self.placements_in_partition,
            "invariant_checks": self.invariant_checks,
            "invariant_violations": self.invariant_violations,
            "converged": self.converged,
            "ok": self.ok,
        }


class PartitionChaosRunner:
    """One seeded chaos run exercising `cluster_partition` — the fault
    `cluster_crash` is NOT: the region's control plane keeps running
    (its harness converges on the shared clock the whole time), only
    the router's view of it goes dark. A second traffic wave is caught
    mid-convergence by the partition, so the victim region holds BOTH
    Scheduled gangs (which must stay bound — partition ≠ crash) and
    still-pending gangs (which the router spills after the suspicion
    timeout). On heal, the router deletes its own spilled copies from
    the rejoined region and the split-brain invariant must have held
    throughout:

    F3. no PodGang is ever Scheduled in two clusters across a
        partition/heal cycle — checked per tick by scanning EVERY
        harness (including the partitioned one; it is alive, that is
        the point) for PCSes whose gangs are Scheduled in more than
        one region at once.

    The federation F1 invariant ("placements point at Ready clusters")
    deliberately does NOT ride along: a placement staying in a
    Partitioned region is the CORRECT outcome here, not a violation.
    """

    def __init__(
        self,
        seed: int = 1234,
        regions: int = 3,
        num_nodes: int = 8,
        n_each: int = 2,
        spill_after: float = 5.0,
        partition_suspect_after: float = 5.0,
    ) -> None:
        from grove_tpu.federation import FederationRouter

        self.seed = seed
        self.n_each = n_each
        self.region_names = [f"region-{i}" for i in range(regions)]
        self.rng = random.Random(seed ^ 0x9A47)
        self.router = FederationRouter(
            self.region_names,
            num_nodes=num_nodes,
            phase_offsets=[i * 200.0 for i in range(regions)],
            spill_after=spill_after,
            partition_suspect_after=partition_suspect_after,
        )
        self.report = PartitionChaosReport(seed=seed, regions=regions)

    # -- invariants ------------------------------------------------------

    def _scheduled_regions(self) -> Dict[Tuple[str, str], Set[str]]:
        """PCS key -> regions where at least one of its gangs is
        currently Scheduled, over EVERY live harness (reachable or
        not — the partitioned control plane is alive and counts)."""
        from grove_tpu.api import names as namegen

        where: Dict[Tuple[str, str], Set[str]] = {}
        for cl in self.router.clusters():
            if cl.harness is None:
                continue
            for gang in cl.harness.store.scan("PodGang"):
                cond = get_condition(
                    gang.status.conditions, COND_PODGANG_SCHEDULED
                )
                if cond is None or not cond.is_true():
                    continue
                pcs_name = gang.metadata.labels.get(namegen.LABEL_PART_OF)
                if not pcs_name:
                    continue
                where.setdefault(
                    (gang.metadata.namespace, pcs_name), set()
                ).add(cl.region)
        return where

    def _check_invariants(self, t0: float) -> None:
        router = self.router
        rep = self.report
        rep.invariant_checks += 1
        rel_now = router.clock.now() - t0
        violations = rep.invariant_violations
        # F3: split-brain — a PCS with Scheduled gangs in two regions
        for key, regions in sorted(self._scheduled_regions().items()):
            if len(regions) > 1:
                violations.append(
                    f"t={rel_now:.0f}s: F3 split-brain — PCS"
                    f" {key[0]}/{key[1]} Scheduled in"
                    f" {sorted(regions)}"
                )
        # the global quota fold only folds reachable Ready regions —
        # it must equal the sum of recounts over exactly that set
        from grove_tpu.quota.oracle import usage_oracle

        recount: dict = {}
        for cl in router.clusters():
            if (
                cl.harness is None
                or cl.state != "Ready"
                or not cl.reachable
            ):
                continue
            oracle = usage_oracle(
                cl.harness.store.scan("Pod"),
                cl.harness.scheduler.quota.accountant.default_queue,
            )
            for q, usage in oracle.items():
                row = recount.setdefault(q, {})
                for r, v in usage.items():
                    row[r] = row.get(r, 0.0) + v
        global_usage = router.global_usage()
        for q in sorted(set(global_usage) | set(recount)):
            a = global_usage.get(q, {})
            b = recount.get(q, {})
            for r in sorted(set(a) | set(b)):
                if abs(a.get(r, 0.0) - b.get(r, 0.0)) > 1e-6:
                    violations.append(
                        f"t={rel_now:.0f}s: global fold queue {q}"
                        f" usage {r}: root {a.get(r, 0.0)} != sum over"
                        f" reachable clusters {b.get(r, 0.0)}"
                    )

    def _all_scheduled(self) -> bool:
        for cl in self.router.clusters():
            if cl.harness is None:
                continue
            for gang in cl.harness.store.list("PodGang"):
                cond = get_condition(
                    gang.status.conditions, COND_PODGANG_SCHEDULED
                )
                if cond is None or not cond.is_true():
                    return False
        return True

    def _apply_wave(self, suffix: str, home: Optional[str] = None) -> None:
        from grove_tpu.api import names as namegen

        for pcs in chaos_workload(n_each=self.n_each):
            if suffix:
                pcs.metadata.name = f"{pcs.metadata.name}{suffix}"
            pcs.metadata.labels[namegen.LABEL_FEDERATION_HOME] = (
                home if home is not None else self.rng.choice(
                    self.region_names
                )
            )
            self.router.apply(pcs)
            self.report.applied += 1

    # -- the run ---------------------------------------------------------

    def run(self, max_ticks: int = 400) -> PartitionChaosReport:
        router = self.router
        rep = self.report
        t0 = router.clock.now()
        budget = max_ticks
        # wave 1: steady state across seeded homes
        self._apply_wave("")
        rep.ticks += router.converge(max_ticks=min(60, budget))
        self._check_invariants(t0)
        # the busiest wave-1 region is the victim; wave 2 is homed
        # there and the partition lands in the same instant — before a
        # single converge tick — so the victim holds wave-1 gangs
        # Scheduled AND wave-2 gangs still pending (the split the spill
        # walk must honor: pending spills, Scheduled never moves)
        counts = {name: 0 for name in self.region_names}
        for region in router.placements().values():
            counts[region] += 1
        victim = max(
            self.region_names, key=lambda name: (counts[name], name)
        )
        self._apply_wave("-w2", home=victim)
        bound_before = {
            key: regions
            for key, regions in self._scheduled_regions().items()
            if victim in regions
        }
        rep.placements_in_partition = len(bound_before)
        rep.faults.append(
            Fault(
                at=router.clock.now() - t0,
                kind="cluster_partition",
                target=victim,
                note=(
                    f"{counts[victim]} placements,"
                    f" {len(bound_before)} Scheduled inside"
                ),
            ).as_dict()
        )
        assert router.partition_cluster(victim)
        # converge in short slices so the per-tick F3 scan brackets the
        # suspicion flip, the spill walk, and the fenced dwell
        for _ in range(6):
            rep.ticks += router.converge(max_ticks=10)
            self._check_invariants(t0)
        # heal: reachable again, stale spilled copies deleted, fence up
        rep.faults.append(
            Fault(
                at=router.clock.now() - t0,
                kind="cluster_heal",
                target=victim,
            ).as_dict()
        )
        assert router.heal_cluster(victim)
        rep.ticks += router.converge(max_ticks=min(120, budget))
        self._check_invariants(t0)
        # a late wave homed at the healed region proves it serves again
        self._apply_wave("-late", home=victim)
        rep.ticks += router.converge(max_ticks=min(160, budget))
        self._check_invariants(t0)
        after = self._scheduled_regions()
        rep.placements_kept = sum(
            1
            for key, regions in bound_before.items()
            if victim in after.get(key, set())
        )
        row = next(
            cl for cl in router.clusters() if cl.region == victim
        )
        rep.partitions = row.partitions
        rep.partition_spills = router.partition_spills
        rep.heals = 1 if row.reachable and row.state == "Ready" else 0
        rep.converged = self._all_scheduled()
        return rep


def run_partition_chaos(
    seed: int = 1234,
    regions: int = 3,
    num_nodes: int = 8,
    n_each: int = 2,
    max_ticks: int = 400,
) -> PartitionChaosReport:
    """One seeded partition chaos run (`chaos_smoke.py --partition`)."""
    return PartitionChaosRunner(
        seed=seed, regions=regions, num_nodes=num_nodes, n_each=n_each
    ).run(max_ticks=max_ticks)
