"""Simulated cluster: nodes, a binding scheduler, and a kubelet.

Plays the roles external to the reference operator: the KAI scheduler (binds
ungated pods to nodes — here the placement decision will be delegated to the
TPU solver) and the kubelets (pods start containers and become Ready, honoring
the grove-initc startup-ordering waiter). The e2e analogue of the reference's
k3d harness (SURVEY §4.3), driven on virtual time.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import zlib

from grove_tpu.api.meta import (
    Condition,
    clone_status,
    deep_copy,
    get_condition,
    set_condition,
)
from grove_tpu.api.pod import (
    COND_POD_READY,
    COND_POD_SCHEDULED,
    POD_PENDING,
    POD_RUNNING,
    ContainerStatus,
    Pod,
    is_ready,
    is_scheduled,
    is_terminating,
)
from grove_tpu.initc.waiter import is_ready_to_start
from grove_tpu.runtime.store import Store, commit_status


# node lifecycle states (docs/robustness.md): Ready nodes heartbeat and
# accept placements; NotReady nodes missed heartbeats but are inside the
# grace window (pods stay bound, nothing new lands); Lost nodes exceeded
# the grace window — the node-health monitor fails their pods and drives
# gang rescue / requeue (controller/nodehealth.py)
NODE_READY = "Ready"
NODE_NOT_READY = "NotReady"
NODE_LOST = "Lost"
# gray failure (docs/robustness.md "Gray failures"): the node heartbeats
# — late but inside the grace window — and its pods keep running, yet the
# monitor's suspicion score says it is fail-slow. Degraded masks the node
# from NEW placements (same `schedulable` predicate every solve path
# consumes) without evicting anything; only the remediation controller
# may drain it, behind a what-if-proven flip and the disruption budget.
NODE_DEGRADED = "Degraded"


@dataclass
class Node:
    name: str
    capacity: Dict[str, float] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)  # topology keys
    cordoned: bool = False
    # health lifecycle (maintained by NodeHealthMonitor from heartbeats)
    state: str = NODE_READY
    # virtual timestamp of the last kubelet heartbeat; a crashed node's
    # kubelet stops ticking, so this freezes and the monitor's grace-period
    # math drives Ready → NotReady → Lost
    last_heartbeat: float = 0.0
    # the node's kubelet process is down (crash_node): no heartbeats, no
    # container starts. Restart (restart_node) resumes both.
    crashed: bool = False

    @property
    def schedulable(self) -> bool:
        """Eligible as a placement target: not cordoned AND healthy. This is
        the single predicate every solve path masks nodes with — NotReady,
        Lost and Degraded (fail-slow) nodes leave the dense tensors exactly
        like cordoned ones."""
        return not self.cordoned and self.state == NODE_READY


@dataclass
class SimCluster:
    store: Store
    nodes: List[Node] = field(default_factory=list)
    # (namespace, pod name) -> node name
    bindings: Dict[tuple, str] = field(default_factory=dict)
    # sticky history surviving deletion: reservation-reuse hints rebind
    # recreated pods (stable names) to their previous node when it still fits
    last_node: Dict[tuple, str] = field(default_factory=dict)
    start_delay: float = 0.0  # container start latency (virtual seconds)

    def __post_init__(self) -> None:
        # epoch of out-of-band rewrites of `bindings` (rebuild_bindings on
        # failover/cold restart): incremental consumers folding the watch
        # stream (solver/deltastate.py) resync their mirrors when it moves
        self.bindings_epoch = 0
        # kubelet working set: (ns, name) of pods that exist and are not
        # Ready — maintained from watch events so kubelet_tick iterates
        # O(not-ready) instead of rescanning the whole pod population each
        # tick. None until first use (a SimCluster may be attached to a
        # store that already holds pods — failover tests); the first tick
        # builds it with one full scan.
        self._not_ready = None
        self._deleted_since_gc = True  # force the first gc pass
        # per-pod-uid resource-request memo: requests are immutable for a
        # pod's lifetime (gate removal clones the spec but never touches
        # requests), and node accounting re-derives them per tick
        self._requests_by_uid: Dict[str, Dict[str, float]] = {}
        # fail-slow injection registry (docs/robustness.md "Gray
        # failures"): node name -> (seed, lag_min, lag_max, start_penalty).
        # A registered node's kubelet heartbeats LATE by a seeded,
        # virtual-time-pure lag (GL001: crc32 of (seed, node, tick) — no
        # wall clock, no unseeded RNG) and starts containers only after a
        # scheduling-age penalty. Private state: only inject_failslow /
        # heal_failslow write it (grovelint GL022 `grayfail-state`).
        self._failslow: Dict[str, tuple] = {}
        # in-memory Store only: its events fire synchronously at commit, so
        # the set is always exact. HttpStore events arrive on watch threads
        # and LAG live reads — there kubelet_tick keeps the full scan.
        if isinstance(self.store, Store):
            self.store.subscribe_system(self._track_pod_event)

    def _track_pod_event(self, ev) -> None:
        if ev.kind != "Pod":
            return
        if ev.type == "Deleted":
            # stale bindings can only appear through deletions (recreated
            # pods reuse names); _gc_bindings skips until one happens
            self._deleted_since_gc = True
            # recreated pods get fresh uids — drop the dead memo entry so
            # churn (evictions, rolling updates) doesn't grow it unbounded
            self._requests_by_uid.pop(ev.obj.metadata.uid, None)
        if self._not_ready is None:
            return
        key = (ev.obj.metadata.namespace, ev.obj.metadata.name)
        if ev.type == "Deleted" or is_ready(ev.obj):
            self._not_ready.discard(key)
        else:
            self._not_ready.add(key)

    def _not_ready_pods(self, namespace: Optional[str]):
        """Readonly views of the not-Ready working set (lazy first build)."""
        if not isinstance(self.store, Store):
            yield from self.store.scan("Pod", namespace)
            return
        if self._not_ready is None:
            self._not_ready = {
                (p.metadata.namespace, p.metadata.name)
                for p in self.store.scan("Pod")
                if not is_ready(p)
            }
        for ns, name in list(self._not_ready):
            if namespace is not None and ns != namespace:
                continue
            pod = self.store.get("Pod", ns, name, readonly=True)
            if pod is not None:
                yield pod

    def rebuild_bindings(self) -> int:
        """Reconstruct the in-memory binding map from persisted pod status
        (`status.node_name`) — the restart/failover path: a fresh scheduler
        (operator restart against an external apiserver, or a standby that
        just took the leader lease) must account capacity for pods bound by
        its predecessor, or node_free() over-commits occupied nodes."""
        n = 0
        for pod in self.store.scan("Pod"):
            if is_terminating(pod) or not is_scheduled(pod):
                continue
            node = pod.status.node_name
            if node:
                key = (pod.metadata.namespace, pod.metadata.name)
                self.bindings[key] = node
                self.last_node.setdefault(key, node)
                n += 1
        # out-of-band binding-map rewrite (no store events fire for it):
        # bump the epoch so the scheduler's delta-solve state rebuilds its
        # binding mirror instead of trusting a pre-failover fold
        self.bindings_epoch += 1
        return n

    def _gc_bindings(self) -> None:
        """Drop bindings whose pod is gone or no longer carries the binding
        (deleted-and-recreated pods reuse stable names). Skipped entirely
        while no pod deletion happened since the last pass — bindings only
        go stale through deletions, and this runs O(bindings) per
        scheduling round otherwise."""
        if isinstance(self.store, Store) and not self._deleted_since_gc:
            return
        self._deleted_since_gc = False
        stale = []
        for (ns, name), _node in self.bindings.items():
            pod = self.store.get("Pod", ns, name, readonly=True)
            if pod is None or not is_scheduled(pod):
                stale.append((ns, name))
        for key in stale:
            del self.bindings[key]

    # -- capacity --------------------------------------------------------

    def _pod_requests(self, pod) -> Dict[str, float]:
        uid = pod.metadata.uid
        reqs = self._requests_by_uid.get(uid)
        if reqs is None:
            reqs = self._requests_by_uid[uid] = pod.spec.total_requests()
        return reqs

    def pod_requests(self, pod) -> Dict[str, float]:
        """Memoized ``total_requests`` per pod uid (specs are immutable
        once committed) — shared with the delta state's row recounts so
        both sides sum the SAME dict objects."""
        return self._pod_requests(pod)

    def _used_by_node(self) -> Dict[str, Dict[str, float]]:
        """Committed resource usage per node in ONE pass over bindings —
        node_free per node is O(bindings), so mapping every node that way
        was O(nodes × bindings) per scheduling round (the quadratic term at
        5k nodes / 47k bound pods)."""
        used: Dict[str, Dict[str, float]] = {}
        live_uids = set()
        for (ns, pod_name), node_name in self.bindings.items():
            pod = self.store.get("Pod", ns, pod_name, readonly=True)
            if pod is None or is_terminating(pod):
                continue
            live_uids.add(pod.metadata.uid)
            u = used.setdefault(node_name, {})
            for k, v in self._pod_requests(pod).items():
                u[k] = u.get(k, 0.0) + v
        if not isinstance(self.store, Store) and len(self._requests_by_uid) > (
            64 + 2 * len(live_uids)
        ):
            # HttpStore has no Deleted-event subscription to evict dead
            # uids; prune to the live set whenever the memo doubles it
            self._requests_by_uid = {
                u: r for u, r in self._requests_by_uid.items() if u in live_uids
            }
        return used

    def node_free_all(self, nodes: List[Node]) -> Dict[str, Dict[str, float]]:
        """Free capacity for every given node from one usage pass."""
        used = self._used_by_node()
        out: Dict[str, Dict[str, float]] = {}
        for node in nodes:
            free = dict(node.capacity)
            for k, v in used.get(node.name, {}).items():
                free[k] = free.get(k, 0.0) - v
            out[node.name] = free
        return out

    def node_free(self, node: Node) -> Dict[str, float]:
        free = dict(node.capacity)
        for (ns, pod_name), node_name in self.bindings.items():
            if node_name != node.name:
                continue
            pod = self.store.get("Pod", ns, pod_name, readonly=True)
            if pod is None or is_terminating(pod):
                continue
            for k, v in self._pod_requests(pod).items():
                free[k] = free.get(k, 0.0) - v
        return free

    def fits(self, node: Node, pod: Pod) -> bool:
        free = self.node_free(node)
        return all(free.get(k, 0.0) >= v for k, v in pod.spec.total_requests().items())

    # -- scheduler (simple binder; TPU solver slots in here) -------------

    def schedule_pending(self, namespace: Optional[str] = None) -> int:
        """Bind every ungated, unscheduled pod (all namespaces by default)
        to the first node that fits (placeholder first-fit; the solver-backed
        gang scheduler replaces this for topology-aware placement)."""
        bound = 0
        self._gc_bindings()
        for pod in self.store.list("Pod", namespace):
            if (
                pod.spec.scheduling_gates
                or is_scheduled(pod)
                or is_terminating(pod)
            ):
                continue
            for node in self.nodes:
                if not node.schedulable or not self.fits(node, pod):
                    continue
                self.bind(pod, node.name)
                bound += 1
                break
        return bound

    def bind(self, pod: Pod, node_name: str) -> None:
        # readonly view + copy-on-write status commit: only the (small) pod
        # STATUS is copied; metadata/spec are shared with the committed
        # object — no whole-pod pickling on the per-pod bind path
        view = self.store.get(
            "Pod", pod.metadata.namespace, pod.metadata.name, readonly=True
        )
        if view is None:
            return
        st = clone_status(view.status)
        st.node_name = node_name
        set_condition(
            st.conditions,
            Condition(type=COND_POD_SCHEDULED, status="True", reason="Bound"),
            self.store.clock.now(),
        )
        # commit FIRST, record the binding only on success: a transient
        # store outage (chaos error injector, real apiserver hiccup) must
        # not leave a phantom binding charging capacity for a pod that was
        # never actually marked scheduled — the next round re-places it
        commit_status(self.store, view, st)
        key = (view.metadata.namespace, view.metadata.name)
        self.bindings[key] = node_name
        self.last_node[key] = node_name

    # -- kubelet ---------------------------------------------------------

    def heartbeat_tick(self) -> None:
        """One kubelet heartbeat round: every node whose kubelet is alive
        reports in. Crashed nodes stay silent — their last_heartbeat
        freezes and the node-health monitor's grace-period math takes over
        (virtual-time jumps between ticks therefore never fake a cluster-
        wide heartbeat loss: a node only ages while actually crashed)."""
        now = self.store.clock.now()
        for node in self.nodes:
            if not node.crashed:
                node.last_heartbeat = now
        if self._failslow:
            # fail-slow nodes heartbeat LATE: the report that lands this
            # tick was produced `lag` seconds ago. The lag stays inside the
            # monitor's NotReady grace window by default, so the binary
            # lifecycle never fires — only the suspicion EWMA sees it.
            for name in self._failslow:
                node = self.node(name)
                if node is not None and not node.crashed:
                    node.last_heartbeat = now - self.failslow_lag(name, now)

    def kubelet_tick(self, namespace: Optional[str] = None) -> int:
        """Advance scheduled pods (all namespaces by default) toward Ready:
        run the init waiter, then start containers and flip Ready. Returns
        pods transitioned."""
        self.heartbeat_tick()
        # a dead kubelet starts nothing: pods bound to crashed or Lost
        # nodes freeze until the monitor fails them or the node restarts
        dead_nodes = {
            n.name for n in self.nodes if n.crashed or n.state == NODE_LOST
        }
        progressed = 0
        # Two-phase: decide against the tick-start state, then apply — so a
        # dependent pod never starts in the same tick its parent became Ready
        # (real kubelets are independent processes; the init waiter observes
        # parent readiness with at least one tick of delay).
        to_start = []
        # readonly iteration over the event-maintained not-Ready working
        # set: readiness and the init-waiter check run against the
        # zero-copy view; only pods that actually TRANSITION build a
        # private status for the copy-on-write commit (waiter-blocked pods
        # in a startup cascade stay free)
        for view in self._not_ready_pods(namespace):
            if not is_scheduled(view) or is_ready(view) or is_terminating(view):
                continue
            if dead_nodes and view.status.node_name in dead_nodes:
                continue
            if self._failslow:
                fs = self._failslow.get(view.status.node_name)
                if fs is not None:
                    # a fail-slow kubelet is alive but drags its feet: a
                    # pod bound there starts only after `start_penalty`
                    # virtual seconds of scheduling age — this is the
                    # attainment drag the grayfail smoke measures, and why
                    # masking the node (Degraded) visibly helps
                    cond = get_condition(
                        view.status.conditions, COND_POD_SCHEDULED
                    )
                    now = self.store.clock.now()
                    if (
                        cond is not None
                        and now - cond.last_transition_time < fs[3]
                    ):
                        continue
            waiter_cfg = view.spec.extra.get("groveInitWaiter")
            waiter_clears = bool(waiter_cfg) and not view.status.init_waiter_done
            if waiter_clears and not is_ready_to_start(
                self.store, view.metadata.namespace, waiter_cfg
            ):
                continue
            to_start.append((view, waiter_clears))
        for view, waiter_clears in to_start:
            st = clone_status(view.status)
            if waiter_clears:
                st.init_waiter_done = True
            st.phase = POD_RUNNING
            st.container_statuses = [
                ContainerStatus(name=c.name, ready=True, started=True)
                for c in view.spec.containers
            ]
            set_condition(
                st.conditions,
                Condition(type=COND_POD_READY, status="True", reason="Started"),
                self.store.clock.now(),
            )
            if commit_status(self.store, view, st) is not None:
                progressed += 1
        return progressed

    # -- node lifecycle (docs/robustness.md) -----------------------------

    def node(self, node_name: str) -> Optional[Node]:
        return next((n for n in self.nodes if n.name == node_name), None)

    def crash_node(self, node_name: str) -> bool:
        """Kill the node's kubelet: heartbeats stop, containers freeze. The
        node stays Ready (and keeps its pods bound) until the node-health
        monitor's grace period expires — the realistic failure path, unlike
        `fail_node`'s immediate cordon-and-evict."""
        node = self.node(node_name)
        if node is None:
            return False
        node.crashed = True
        return True

    def restart_node(self, node_name: str) -> bool:
        """Bring the node's kubelet back: heartbeats resume (fresh from this
        instant) and the monitor flips the node back to Ready on its next
        tick. A restart inside the grace window is a harmless flap."""
        node = self.node(node_name)
        if node is None:
            return False
        node.crashed = False
        node.last_heartbeat = self.store.clock.now()
        return True

    def inject_failslow(
        self,
        node_name: str,
        seed: int,
        lag_min: float = 3.0,
        lag_max: float = 8.0,
        start_penalty: float = 120.0,
    ) -> bool:
        """Arm the fail-slow (gray) fault on a node: heartbeats arrive
        `lag_min..lag_max` seconds late (seeded per-tick draw, below the
        monitor's 10s NotReady grace by default — the BINARY detector never
        fires) and bound pods start only after `start_penalty` seconds of
        scheduling age. Nothing crashes; the node looks alive everywhere
        except to the suspicion EWMA."""
        if self.node(node_name) is None:
            return False
        self._failslow[node_name] = (seed, lag_min, lag_max, start_penalty)
        return True

    def heal_failslow(self, node_name: str) -> bool:
        """Clear the fail-slow fault: heartbeats arrive on time again from
        the next tick; the monitor's hysteresis flips Degraded → Ready once
        the suspicion score decays below the recovery threshold."""
        return self._failslow.pop(node_name, None) is not None

    def failslow_lag(self, node_name: str, now: float) -> float:
        """The seeded heartbeat lag for a fail-slow node at virtual time
        `now` — a PURE function of (seed, node, tick): crc32, not random
        or hash(), so replays and the suspicion-oracle test (NumPy EWMA
        over this exact trace) see identical values. 0.0 when the node is
        not registered."""
        fs = self._failslow.get(node_name)
        if fs is None:
            return 0.0
        seed, lag_min, lag_max, _penalty = fs
        u = (
            zlib.crc32(f"{seed}:{node_name}:{int(now)}".encode()) & 0xFFFF
        ) / float(1 << 16)
        return lag_min + (lag_max - lag_min) * u

    def failslow_spec(self, node_name: str):
        """(seed, lag_min, lag_max, start_penalty) of an armed fail-slow
        fault, or None — the re-injection handle for harness swaps
        (leader failover / control-plane crash rebuild a SimCluster; the
        kubelet-side fault must survive, it is node state, not leader
        memory)."""
        return self._failslow.get(node_name)

    def failslow_names(self) -> set:
        """Nodes currently under the fail-slow fault (chaos invariants +
        the grayfail smoke read this; nothing outside this module writes
        the registry — GL022)."""
        return set(self._failslow)

    def unschedulable_names(self) -> set:
        """Names of nodes no solve may target (cordoned or unhealthy) —
        the set recovery-pin resolution avoids pinning to."""
        return {n.name for n in self.nodes if not n.schedulable}

    def fail_node(self, node_name: str) -> int:
        """Node loss: cordon the node and evict (delete) every pod bound to
        it — the node-controller behavior after a node goes NotReady. The
        PCLQ controllers recreate the pods gated; the scheduler's recovery
        delta-solve places them on surviving nodes (honoring gang/group
        recovery pins). Returns the number of pods evicted."""
        node = next((n for n in self.nodes if n.name == node_name), None)
        if node is None:
            return 0
        node.cordoned = True
        self._gc_bindings()  # stale entries must not count as evictions
        victims = [
            (ns, pod_name)
            for (ns, pod_name), bound in self.bindings.items()
            if bound == node_name
        ]
        evicted = 0
        for ns, pod_name in victims:
            if self.store.get("Pod", ns, pod_name) is not None:
                self.store.delete("Pod", ns, pod_name)
                evicted += 1
        return evicted

    def fail_pod(self, namespace: str, name: str, exit_code: int = 1) -> None:
        """Crash a pod's containers (fault injection for breach tests)."""
        view = self.store.get("Pod", namespace, name, readonly=True)
        if view is None:
            return
        st = deep_copy(view.status)
        st.phase = POD_PENDING
        for cs in st.container_statuses:
            cs.ready = False
            cs.exit_code = exit_code
            cs.restart_count += 1
        if not st.container_statuses:
            st.container_statuses = [
                ContainerStatus(name=c.name, started=True, exit_code=exit_code)
                for c in view.spec.containers
            ]
        set_condition(
            st.conditions,
            Condition(type=COND_POD_READY, status="False", reason="CrashLoop"),
            self.store.clock.now(),
        )
        commit_status(self.store, view, st)


def make_nodes(
    count: int,
    capacity: Optional[Dict[str, float]] = None,
    hosts_per_ici_block: int = 4,
    blocks_per_slice: int = 4,
) -> List[Node]:
    """Synthetic TPU-ish topology: hosts grouped into ici-blocks into slices."""
    capacity = capacity or {"cpu": 8.0, "memory": 32 * 2**30, "tpu": 4.0}
    nodes = []
    for i in range(count):
        block = i // hosts_per_ici_block
        slice_ = block // blocks_per_slice
        nodes.append(
            Node(
                name=f"node-{i}",
                capacity=dict(capacity),
                labels={
                    "kubernetes.io/hostname": f"node-{i}",
                    "cloud.google.com/gke-tpu-ici-block": f"block-{block}",
                    "cloud.google.com/gke-tpu-slice": f"slice-{slice_}",
                    "cloud.google.com/gke-cluster": "cluster-0",
                    "topology.kubernetes.io/zone": "zone-a",
                },
            )
        )
    return nodes
