"""Multi-tenant contended scenario (quota subsystem, docs/quota.md).

The shared driver behind ``make quota-smoke`` (scripts/quota_smoke.py), the
bench's ``"quota"`` artifact block, and tests/test_quota.py: N tenant
queues with deserved shares that sum to the cluster's capacity, each tenant
submitting more gangs than its share covers — so fair-share ordering and
cross-queue reclaim must drive every queue to within ±1 gang of deserved.

The scenario deliberately STAGGERS arrival (the first tenant converges
alone and monopolizes the cluster) so convergence REQUIRES reclaim, not
just fair admission ordering from an empty cluster.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from grove_tpu.api import names as namegen
from grove_tpu.api.load import load_podcliquesets
from grove_tpu.api.meta import ObjectMeta
from grove_tpu.api.types import PodCliqueSet, Queue, QueueSpec

_TENANT_YAML = """
apiVersion: grove.io/v1alpha1
kind: PodCliqueSet
metadata:
  name: placeholder
spec:
  replicas: 1
  template:
    cliques:
      - name: worker
        spec:
          roleName: role-worker
          replicas: 1
          podSpec:
            containers:
              - name: worker
                image: busybox:stable
                resources:
                  requests:
                    cpu: 1
"""


def tenant_queue(
    name: str,
    deserved_cpu: float,
    ceiling_cpu: Optional[float] = None,
) -> Queue:
    spec = QueueSpec(deserved={"cpu": float(deserved_cpu)})
    if ceiling_cpu is not None:
        spec.ceiling = {"cpu": float(ceiling_cpu)}
    return Queue(metadata=ObjectMeta(name=name), spec=spec)


def tenant_pcs(tenant: str, index: int, namespace: Optional[str] = None) -> PodCliqueSet:
    """One 1-pod / 1-cpu gang for `tenant`, queue-labeled, in the tenant's
    own namespace (exercises the cross-namespace event attribution the
    QuotaReclaim tests pin)."""
    from grove_tpu.api.meta import deep_copy

    pcs = deep_copy(_TENANT_BASE)
    pcs.metadata.name = f"{tenant}-{index:03d}"
    pcs.metadata.namespace = namespace or tenant
    pcs.metadata.labels[namegen.LABEL_QUEUE] = tenant
    return pcs


_TENANT_BASE = load_podcliquesets(_TENANT_YAML)[0]


def build_contended_harness(
    tenants: Sequence[Tuple[str, float, int]] = (
        ("team-a", 6.0, 12),
        ("team-b", 4.0, 12),
        ("team-c", 2.0, 12),
    ),
    node_cpu: float = 2.0,
    stagger: bool = True,
):
    """(harness, tenants): cluster capacity == sum of deserved shares; each
    tenant submits `gangs` 1-cpu gangs. With ``stagger`` the first tenant
    converges alone first (and hogs the cluster), forcing reclaim."""
    from grove_tpu.sim.cluster import Node
    from grove_tpu.sim.harness import SimHarness

    total_cpu = sum(d for _, d, _ in tenants)
    n_nodes = max(1, int(round(total_cpu / node_cpu)))
    harness = SimHarness(num_nodes=1)
    harness.cluster.nodes = [
        Node(
            name=f"node-{i}",
            capacity={"cpu": node_cpu},
            labels={"kubernetes.io/hostname": f"node-{i}"},
        )
        for i in range(n_nodes)
    ]
    for name, deserved, _ in tenants:
        harness.apply_queue(tenant_queue(name, deserved))
    # pre-compile the ordering scan for this workload's padded shape so the
    # measured order_seconds reflect steady-state cost, not one XLA compile
    harness.scheduler.quota.warm(
        len(tenants) + 1, max(g for _, _, g in tenants)
    )
    first, rest = tenants[0], tenants[1:]
    for i in range(first[2]):
        harness.apply(tenant_pcs(first[0], i))
    if stagger:
        harness.converge(max_ticks=120)
    for name, _, gangs in rest:
        for i in range(gangs):
            harness.apply(tenant_pcs(name, i))
    return harness, list(tenants)


_EXPLAIN_GANG_YAML = """
apiVersion: grove.io/v1alpha1
kind: PodCliqueSet
metadata:
  name: placeholder
spec:
  replicas: 1
  template:
    cliques:
      - name: w
        spec:
          roleName: role-w
          replicas: 1
          podSpec:
            containers:
              - name: w
                image: busybox:stable
                resources:
                  requests:
                    cpu: 1
"""


def _explain_pcs(
    name: str,
    queue: str,
    cpu: float,
    replicas: int = 1,
    pack_domain: Optional[str] = None,
    spread_domain: Optional[str] = None,
    spread_min: int = 2,
):
    """One parameterized gang for the explain scenario: `replicas` pods of
    `cpu` each, optional gang-level pack/spread constraint."""
    from grove_tpu.api.types import (
        SPREAD_DO_NOT_SCHEDULE,
        TopologyConstraint,
    )

    pcs = load_podcliquesets(_EXPLAIN_GANG_YAML)[0]
    pcs.metadata.name = name
    pcs.metadata.labels[namegen.LABEL_QUEUE] = queue
    clique = pcs.spec.template.cliques[0]
    clique.spec.replicas = replicas
    for c in clique.spec.pod_spec.containers:
        c.requests = {"cpu": float(cpu)}
    if pack_domain or spread_domain:
        pcs.spec.template.topology_constraint = TopologyConstraint(
            pack_domain=pack_domain,
            spread_domain=spread_domain,
            spread_min_domains=spread_min if spread_domain else None,
            spread_when_unsatisfiable=(
                SPREAD_DO_NOT_SCHEDULE if spread_domain else None
            ),
        )
    return pcs


def build_explain_scenario():
    """The contended scenario behind ``make explain-smoke``, the bench
    "explain" block, and the explain truthfulness tests
    (docs/observability.md "Admission explain"): a fragmented 2-block
    cluster where, simultaneously,

    - ``frag``   (queue team-a) is FRAGMENTATION-blocked: 4x1 cpu packed
      inside one ici-block, while each block holds only 3 free cpu
      (aggregate free 6 covers the floor — no contiguous domain does);
    - ``capped-1`` (queue team-b) FITS NOW (2x1 cpu, unconstrained);
    - ``capped-2`` (queue team-b) is QUOTA-blocked at team-b's ceiling;
    - draining the ``bridge`` gang's block-0 node (gang-whole eviction
      frees its block-1 pod too) flips ``frag`` to fits-now — the what-if
      a real drain then confirms.

    Returns (harness, refs) with refs naming every actor:
    {frag, fits, capped, bridge, bridge_node, filler_queue}.
    """
    from grove_tpu.sim.cluster import make_nodes
    from grove_tpu.sim.harness import SimHarness

    harness = SimHarness(num_nodes=1)
    # 8 nodes x 4 cpu: block-0 = node-0..3 (slice-0), block-1 = node-4..7
    # (slice-1) — cpu-only capacity keeps every number legible
    harness.cluster.nodes = make_nodes(
        8,
        capacity={"cpu": 4.0},
        hosts_per_ici_block=4,
        blocks_per_slice=1,
    )
    # tenant-z: infrastructure filler queue, deserved far below its usage
    # so its re-pended gangs always order LAST (never steal the capacity
    # a what-if frees for team-a/team-b)
    harness.apply_queue(tenant_queue("tenant-z", 10.0))
    harness.apply_queue(tenant_queue("team-a", 4.0))
    harness.apply_queue(tenant_queue("team-b", 2.0, ceiling_cpu=2.0))
    harness.scheduler.quota.warm(4, 8)
    # fill: one 3-cpu pod per node (exactly one fits a 4-cpu node) — every
    # node keeps 1 cpu free
    for i in range(8):
        harness.apply(_explain_pcs(f"fill-{i}", "tenant-z", 3.0))
    # bridge: 2x1 cpu spread HARD across slices — one pod lands in each
    # block, so a gang-whole drain of its block-0 node frees block-1 too
    harness.apply(
        _explain_pcs(
            "bridge", "tenant-z", 1.0, replicas=2,
            spread_domain="slice", spread_min=2,
        )
    )
    harness.converge(max_ticks=120)
    # the three explain subjects arrive AFTER the fillers converged; the
    # caller materializes their pods without solving (engine drains) so
    # all three verdicts are observable at once
    harness.apply(
        _explain_pcs("frag", "team-a", 1.0, replicas=4,
                     pack_domain="ici-block")
    )
    harness.apply(_explain_pcs("capped-1", "team-b", 1.0, replicas=2))
    harness.apply(_explain_pcs("capped-2", "team-b", 2.0))
    for _ in range(6):
        harness.engine.drain()
        harness.clock.advance(1.0)
    # the bridge gang's block-0 node (drain target for the flip)
    bridge_node = None
    for (ns, pod_name), node_name in harness.cluster.bindings.items():
        pod = harness.store.get("Pod", ns, pod_name, readonly=True)
        if pod is None:
            continue
        if (pod.metadata.labels.get(namegen.LABEL_PODGANG) or "").startswith(
            "bridge"
        ):
            node = harness.cluster.node(node_name)
            if (
                node is not None
                and node.labels.get("cloud.google.com/gke-tpu-ici-block")
                == "block-0"
            ):
                bridge_node = node_name
    refs = {
        "frag": _gang_name_of(harness, "frag"),
        "fits": _gang_name_of(harness, "capped-1"),
        "capped": _gang_name_of(harness, "capped-2"),
        "bridge": _gang_name_of(harness, "bridge"),
        "bridge_node": bridge_node,
        "filler_queue": "tenant-z",
    }
    return harness, refs


def _gang_name_of(harness, pcs_name: str) -> Optional[str]:
    for gang in harness.store.list("PodGang"):
        if gang.metadata.name.startswith(f"{pcs_name}-"):
            return gang.metadata.name
    return None


def metrics_baseline() -> Dict[str, float]:
    """Snapshot of the process-global counters the contended report deltas
    against (the bench runs other workloads in the same process first)."""
    from grove_tpu.observability.metrics import METRICS

    return {
        "order": METRICS.hist_sum.get("quota_order_seconds", 0.0),
        "solver": METRICS.hist_sum.get("gang_solve_seconds", 0.0),
        "reclaims": METRICS.counters.get("quota_reclaims_total", 0),
    }


def contended_report(harness, tenants, base: Optional[Dict] = None) -> Dict:
    """Per-queue achieved vs deserved (in gangs), reclaim count, and the
    ordering-overhead share of solver wall time (deltas vs `base`)."""
    from grove_tpu.observability.metrics import METRICS
    from grove_tpu.quota.manager import quota_snapshot

    base = base or {"order": 0.0, "solver": 0.0, "reclaims": 0}
    snap = {row["name"]: row for row in quota_snapshot(harness.store)}
    per_queue = {}
    converged = True
    for name, deserved_cpu, _ in tenants:
        achieved = snap.get(name, {}).get("admittedGangs", 0)
        deserved_gangs = deserved_cpu  # 1 cpu per gang in this scenario
        ok = abs(achieved - deserved_gangs) <= 1.0
        converged = converged and ok
        per_queue[name] = {
            "deserved_gangs": deserved_gangs,
            "achieved_gangs": achieved,
            "dominant_share": round(snap.get(name, {}).get("dominantShare", 0.0), 4),
            "within_one_gang": ok,
        }
    order_s = (
        METRICS.hist_sum.get("quota_order_seconds", 0.0) - base["order"]
    )
    solver_s = (
        METRICS.hist_sum.get("gang_solve_seconds", 0.0) - base["solver"]
    )
    return {
        "tenants": per_queue,
        "within_one_gang": converged,
        "reclaims": int(
            METRICS.counters.get("quota_reclaims_total", 0)
            - base["reclaims"]
        ),
        "order_seconds": round(order_s, 4),
        "solver_seconds": round(solver_s, 4),
        "order_overhead_ratio": round(order_s / solver_s, 4) if solver_s else 0.0,
    }


def run_contended(
    tenants: Sequence[Tuple[str, float, int]] = (
        ("team-a", 6.0, 12),
        ("team-b", 4.0, 12),
        ("team-c", 2.0, 12),
    ),
    max_ticks: int = 200,
) -> Tuple[object, Dict]:
    base = metrics_baseline()
    harness, tenants = build_contended_harness(tenants)
    harness.converge(max_ticks=max_ticks)
    return harness, contended_report(harness, tenants, base)


def single_queue_ab(n_sets: int = 24, num_nodes: int = 16) -> Dict:
    """A/B guard: the same workload with NO Queue CRs vs EVERYTHING in one
    queue must produce identical admissions (pod -> node bindings), pinning
    the single-queue bit-identical contract end to end."""
    import time as _time

    from grove_tpu.api.meta import deep_copy
    from grove_tpu.sim.harness import SimHarness

    def run(with_queue: bool):
        harness = SimHarness(num_nodes=num_nodes)
        if with_queue:
            harness.apply_queue(tenant_queue("everyone", 1e9))
        t0 = _time.perf_counter()
        for i in range(n_sets):
            pcs = deep_copy(_TENANT_BASE)
            pcs.metadata.name = f"svc-{i:04d}"
            if with_queue:
                pcs.metadata.labels[namegen.LABEL_QUEUE] = "everyone"
            harness.apply(pcs)
        harness.converge(max_ticks=60 + n_sets)
        wall = _time.perf_counter() - t0
        bindings = sorted(
            (ns, name, node)
            for (ns, name), node in harness.cluster.bindings.items()
        )
        return bindings, wall

    base_bindings, base_wall = run(False)
    quota_bindings, quota_wall = run(True)
    return {
        "identical_admissions": base_bindings == quota_bindings,
        "admitted_pods": len(base_bindings),
        "base_wall_s": round(base_wall, 3),
        "quota_wall_s": round(quota_wall, 3),
    }
