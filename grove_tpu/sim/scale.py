"""Sharded-control-plane scale scenario (docs/control-plane.md).

Two jobs:

- ``scale_artifact`` — the bench ``"scale"`` block: converge a
  multi-tenant population at the ROADMAP's 10× shape (100k nodes /
  ≥500k pods, shards on) and report µs/reconcile, the solver share, the
  level-2 fold-depth histogram, and the per-shard census. The shape
  scales down proportionally for smoke runs (``make cp-bench-smoke``
  must stay seconds, not hours).
- ``inert_ab`` — the S=1 guard rail: the SAME population applied to an
  unsharded and a sharded control plane must converge to byte-identical
  store content (canonical-uid wire dump), identical reconcile counts
  and identical admissions. Sharding is a routing change, never a
  semantic one.

Populations spread over ``n_tenants`` namespaces (set ``i`` lands in
``tenant-(i % n_tenants)``) because the keyspace map is per-namespace:
a single-namespace population degenerates every shard count to one hot
shard, which exercises nothing.
"""

from __future__ import annotations

import gc
import time
from typing import List, Optional, Tuple

from grove_tpu.api.load import load_podcliquesets
from grove_tpu.api.meta import deep_copy
from grove_tpu.api.pod import is_ready
from grove_tpu.observability.hostinfo import host_block
from grove_tpu.observability.metrics import METRICS
from grove_tpu.runtime.clock import VirtualClock
from grove_tpu.runtime.store import Store
from grove_tpu.sim.harness import SimHarness

# one clique × 8 replicas: the leanest gang shape that still runs the
# whole pipeline (PCS → PCLQ → PodGang → solve → bind → status). The
# scale run is a CONTROL-PLANE stress; 8 pods/set keeps the solver's
# chunk count (each chunk pays O(nodes) per wave — at 100k nodes the
# dominant term, measured 84% of wall at 4 pods/set) low enough that the
# 500k-pod converge stays tractable on CPU while the CP still folds
# every pod event
_SCALE_YAML = """
apiVersion: grove.io/v1alpha1
kind: PodCliqueSet
metadata:
  name: scale
spec:
  replicas: 1
  template:
    cliques:
      - name: serve
        spec:
          roleName: role-serve
          replicas: 8
          podSpec:
            containers:
              - name: serve
                image: busybox:stable
                resources:
                  requests:
                    cpu: 10m
"""

_BASE = load_podcliquesets(_SCALE_YAML)[0]


def tenant_namespaces(n_tenants: int) -> List[str]:
    return [f"tenant-{i:03d}" for i in range(n_tenants)]


def _populate(h: SimHarness, n_sets: int, tenants: List[str]) -> float:
    t0 = time.perf_counter()
    for i in range(n_sets):
        pcs = deep_copy(_BASE)
        pcs.metadata.name = f"svc-{i:06d}"
        pcs.metadata.namespace = tenants[i % len(tenants)]
        h.apply(pcs)
    return time.perf_counter() - t0


def _reconcile_count() -> int:
    return int(
        sum(
            v
            for k, v in METRICS.counters.items()
            if k.startswith("reconcile_total")
        )
    )


def _peak_rss_kb() -> int:
    """Process peak RSS in KB (ru_maxrss is KB on Linux). Monotone over
    the process lifetime — sampled after each phase, the per-phase rows
    show WHICH phase first pushed the high-water mark."""
    import resource

    return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)


def converge_population(
    n_sets: int,
    n_nodes: int,
    num_shards: int,
    n_tenants: int = 64,
    max_ticks: Optional[int] = None,
    frontier: bool = False,
    frontier_selfcheck: bool = False,
    glassbox: bool = False,
    workers: int = 0,
) -> Tuple[SimHarness, dict]:
    """Apply + converge one multi-tenant population on a fresh harness;
    returns (harness, report).

    GC discipline (the PR 8 delta-block measurement rule): the population
    is large, long-lived and acyclic, so cyclic full collections inside
    the measured window are multi-second pauses billed to arbitrary
    ticks. Freeze+disable covers BOTH measured phases (apply and
    converge), and the wall clock is read BEFORE the closing collect so
    the teardown collection never lands inside the window. Peak RSS is
    sampled after each phase.

    ``frontier=True`` attaches the partitioned solver frontier
    (solver/frontier.py) and reports its counters under ``"frontier"``;
    ``frontier_selfcheck`` arms the per-tick batched-vs-sequential A/B
    (the smoke's setting — measurement runs keep it off and report the
    overhead ledger as 0).

    ``workers>1`` arms the parallel control plane (runtime/workers.py,
    docs/control-plane.md §5): per-shard reconcile workers, serial-twin
    deterministic. 0 defers to the GROVE_TPU_CP_WORKERS env opt-in the
    engine already honors; the report's ``workers`` field records what
    actually ran, and armed runs add per-worker busy-share utilization.

    ``glassbox=True`` arms the wall-attribution profiler and the
    gang-journey tracer for the CONVERGE window (never the apply loop)
    and adds ``"attribution"`` / ``"admission_latency"`` /
    ``"critical_path"`` blocks: the per-(controller, shard, phase)
    ledger gated on ≥95% coverage of the independently timed converge
    wall, and the per-gang queue-wait/encode/solve/commit decomposition
    (docs/observability.md). Profiling overhead lands INSIDE the
    measured wall, so glass-box runs are not comparable to dark ones —
    the frontier/inert A/Bs always run dark."""
    tenants = tenant_namespaces(min(n_tenants, max(n_sets, 1)))
    store = Store(VirtualClock(), cache_lag=True, num_shards=num_shards)
    h = SimHarness(num_nodes=n_nodes, store=store)
    if workers > 0 and (
        h.engine.workers is None or h.engine.workers.workers != workers
    ):
        # an explicit worker count wins over whatever the env auto-armed
        # (enable_workers is a no-op once armed, so mismatches re-arm)
        h.engine.close()
        if workers > 1:
            h.engine.enable_workers(workers)
    if frontier:
        h.scheduler.enable_frontier()
        h.scheduler.frontier_selfcheck = frontier_selfcheck
    else:
        # PIN the global path: the harness env hook (GROVE_TPU_FRONTIER=1)
        # may have attached the frontier, and a paired A/B whose baseline
        # arm silently runs partitioned measures speedup ~1.0
        h.scheduler.frontier = None
        h.scheduler.frontier_selfcheck = False
    solver_s0 = METRICS.hist_sum.get("gang_solve_seconds", 0.0)
    reconciles0 = _reconcile_count()
    gc.collect()
    gc.freeze()
    gc.disable()
    try:
        t0 = time.perf_counter()
        applied_s = _populate(h, n_sets, tenants)
        rss_after_apply = _peak_rss_kb()
        if glassbox:
            from grove_tpu.observability.journey import JOURNEYS
            from grove_tpu.observability.profile import PROFILER

            PROFILER.enable()
            PROFILER.reset()
            JOURNEYS.enable()
            JOURNEYS.reset()
            JOURNEYS.clock = h.clock
        # window-align the busy-share utilization with the attribution
        # cross-check: both cover the CONVERGE only (the profiler arms at
        # converge start), so the two per-worker numbers are comparable
        busy0 = (
            h.engine.workers.busy_snapshot()
            if h.engine.workers is not None
            else None
        )
        t_conv0 = time.perf_counter()
        h.converge(max_ticks=max_ticks or (60 + 8 * n_sets))
        converge_wall = time.perf_counter() - t_conv0
        wall = time.perf_counter() - t0
        glass = None
        if glassbox:
            # freeze the ledger NOW: the report-building store reads below
            # must not leak into the attribution window (coverage is
            # attributed ÷ converge wall and both sides stop here)
            solver_glass = (
                METRICS.hist_sum.get("gang_solve_seconds", 0.0) - solver_s0
            )
            glass = glassbox_blocks(
                converge_wall,
                solver_glass,
                worker_of=(
                    h.engine.workers.worker_of
                    if h.engine.workers is not None
                    else None
                ),
            )
    finally:
        gc.enable()
        gc.unfreeze()
        gc.collect()
    rss_after_converge = _peak_rss_kb()
    pods = h.store.list("Pod")
    ready = bool(pods) and all(is_ready(p) for p in pods)
    reconciles = _reconcile_count() - reconciles0
    solver_s = METRICS.hist_sum.get("gang_solve_seconds", 0.0) - solver_s0
    cp_seconds = max(wall - solver_s - applied_s, 0.0)
    total, ready_n = h.store.pod_summary()
    report = {
        "sets": n_sets,
        "nodes": n_nodes,
        "shards": num_shards,
        "tenants": len(tenants),
        "pods": len(pods),
        "all_ready": ready,
        "wall_seconds": round(wall, 2),
        "apply_seconds": round(applied_s, 2),
        "solver_seconds": round(solver_s, 2),
        "solver_share": round(solver_s / wall, 4) if wall else 0.0,
        "control_plane_seconds": round(cp_seconds, 2),
        "reconciles": reconciles,
        "us_per_reconcile": round(1e6 * cp_seconds / max(reconciles, 1), 1),
        # the hierarchical-fold proof: pod summary off the level-2 tree
        # (equal to the flat fold — tests/test_shards.py) + nodes/level
        "pod_summary": {"total": total, "ready": ready_n},
        "fold_depth_histogram": h.store.fold_depth_histogram(),
        "shard_census": h.store.shard_census(),
        "peak_rss_kb": {
            "after_apply": rss_after_apply,
            "after_converge": rss_after_converge,
        },
        # the parallel control plane's footprint in this run (1 = the
        # serial drain; docs/control-plane.md §5)
        "workers": (
            h.engine.workers.workers if h.engine.workers is not None else 1
        ),
        # tail-honesty: the box that produced these numbers, with the
        # executor backend that actually ran (observability/hostinfo.py)
        "host": host_block(
            backend=(
                h.engine.workers.backend
                if h.engine.workers is not None
                else "serial"
            )
        ),
    }
    if h.engine.workers is not None:
        stats = h.engine.workers.stats()
        stats["utilization"] = h.engine.workers.utilization(
            converge_wall, since=busy0
        )
        report["parallel"] = stats
    if frontier and h.scheduler.frontier is not None:
        report["frontier"] = h.scheduler.frontier.stats()
    if glassbox and glass is not None:
        report.update(glass)
        if (
            h.engine.workers is not None
            and "by_worker" in report.get("attribution", {})
        ):
            report["parallel"]["attributed_utilization"] = report[
                "attribution"
            ]["by_worker"]
    return h, report


def glassbox_blocks(
    converge_wall: float, solver_s: float, worker_of=None
) -> dict:
    """Freeze the glass-box layer into bench blocks and disarm it.

    ``attribution``: the profiler roll-up with TWO coverage ratios —
    ``coverage`` (attributed ÷ the independently timed converge wall,
    solver included on both sides) and ``cp_coverage`` (the same with
    the solve-phase rows subtracted from both sides: the CP-only claim
    the acceptance gate reads). ``admission_latency``/``critical_path``:
    the journey decomposition and its top-down fold.

    ``worker_of`` (a shard → worker map, supplied when the parallel
    control plane ran): adds ``by_worker`` — every shard-scoped
    self-time row grouped onto its owning reconcile worker as a share
    of the converge wall, the scale block's per-worker utilization
    (docs/control-plane.md §5). Computed over the FULL row set, before
    the artifact keeps only the top sinks."""
    from grove_tpu.observability.journey import JOURNEYS
    from grove_tpu.observability.profile import PROFILER

    attribution = PROFILER.report(wall_seconds=converge_wall)
    if worker_of is not None:
        by_worker: dict = {}
        for ph in attribution["phases"]:
            shard = ph["shard"]
            if shard is None or shard < 0:
                continue
            w = worker_of(shard)
            by_worker[w] = by_worker.get(w, 0.0) + ph["total_s"]
        attribution["by_worker"] = {
            str(w): round(s / max(converge_wall, 1e-9), 4)
            for w, s in sorted(by_worker.items())
        }
    solve_attr = sum(
        ph["total_s"]
        for ph in attribution["phases"]
        if ph["phase"] == "solve"
    )
    cp_wall = converge_wall - solve_attr
    cp_attr = attribution["attributed_seconds"] - solve_attr
    attribution["cp_wall_seconds"] = round(cp_wall, 6)
    attribution["cp_attributed_seconds"] = round(cp_attr, 6)
    attribution["cp_coverage"] = (
        round(cp_attr / cp_wall, 4) if cp_wall > 0 else 0.0
    )
    attribution["solver_histogram_seconds"] = round(solver_s, 6)
    # the artifact keeps the top sinks; the full table stays queryable at
    # GET /debug/profile while the process lives
    attribution["phases"] = attribution["phases"][:24]
    blocks = {
        "attribution": attribution,
        "admission_latency": JOURNEYS.decomposition(),
        "critical_path": JOURNEYS.critical_path(),
    }
    PROFILER.disable()
    JOURNEYS.disable()
    return blocks


def _rv_normalized(dump: dict) -> dict:
    """Drop the per-object resourceVersion stamps: per-shard rv SEQUENCES
    legitimately differ from the single global sequence (the documented
    vector merge rule) — everything else must match byte-for-byte."""
    for doc in dump.values():
        doc.get("metadata", {}).pop("resourceVersion", None)
    return dump


def inert_ab(
    n_sets: int = 192, n_nodes: int = 64, num_shards: int = 5
) -> dict:
    """S=1 vs S=num_shards on the identical population: byte-identical
    committed content up to the documented rv renumbering (canonical-uid
    wire dump, Events excluded — their emission counts depend on dedup
    timing, not store routing; per-object resourceVersions normalized —
    per-shard sequences differ from the global one by construction,
    which is exactly the vector merge rule), equal reconcile counts,
    equal scalar resourceVersion (total commit count), equal admissions.

    A throwaway warmup converge runs first so neither side is billed the
    solver's XLA compile — the wall comparison is control-plane work.

    Both arms are PINNED serial (workers=1): this A/B's walls are
    compared across PRs, and an ambient GROVE_TPU_CP_WORKERS would
    otherwise arm only the sharded arm's engine — a different executor
    per arm, exactly what the comparison must exclude."""
    from grove_tpu.sim.recovery import store_dump

    _wh, _wr = converge_population(
        min(n_sets, 16), min(n_nodes, 16), num_shards=1, workers=1
    )
    _close_harness(_wh)
    h1, r1 = converge_population(n_sets, n_nodes, num_shards=1, workers=1)
    hs, rs = converge_population(
        n_sets, n_nodes, num_shards=num_shards, workers=1
    )
    dump1 = _rv_normalized(
        store_dump(h1.store, canonical_uids=True, include_events=False)
    )
    dumps = _rv_normalized(
        store_dump(hs.store, canonical_uids=True, include_events=False)
    )
    _close_harness(h1)
    _close_harness(hs)
    return {
        "sets": n_sets,
        "shards_b": num_shards,
        "identical_content": dump1 == dumps,
        "objects": len(dump1),
        "reconciles_s1": r1["reconciles"],
        "reconciles_sharded": rs["reconciles"],
        "identical_reconciles": r1["reconciles"] == rs["reconciles"],
        "all_ready_both": r1["all_ready"] and rs["all_ready"],
        "rv_scalar_s1": h1.store.resource_version,
        "rv_scalar_sharded": hs.store.resource_version,
        "identical_rv_scalar": (
            h1.store.resource_version == hs.store.resource_version
        ),
        "wall_s1": r1["wall_seconds"],
        "wall_sharded": rs["wall_seconds"],
    }


def census_spread_problems(census: List[dict], num_shards: int) -> List[str]:
    """Shard-count-aware census gate (scripts/scale_smoke.py): at S≥2 the
    population must actually spread over ≥2 shards (the smoke exercised
    routing, not one hot shard); at S=1 there is exactly one shard to
    land on — the run exercises the inert-A/B arm instead, and a spread
    demand would always trip. Returns the problem list (empty = ok)."""
    busy = [c for c in census if c["objects"] > 0]
    if num_shards <= 1:
        if len(busy) != 1:
            return [
                f"S=1 run landed objects on {len(busy)} shards — the"
                " unsharded store must have exactly one populated shard"
            ]
        return []
    if len(busy) < 2:
        return [
            f"population landed on {len(busy)} shard(s) — the smoke must"
            " exercise cross-shard routing"
        ]
    return []


def frontier_ab(
    n_sets: int = 512, n_nodes: int = 512, num_shards: int = 2
) -> dict:
    """Paired converge at one shape, global frontier vs partitioned
    frontier — the wall/solver A/B behind the scale block's ≥1.8×
    converge gate (docs/solver.md "Partitioned frontier"). Throwaway
    warmup converges absorb the solver's XLA compiles first — one per
    arm, AT THE MEASURED NODE COUNT: the global arm's chunk kernel
    compiles per (chunk, nodes) shape (the gang count only changes the
    chunk count), so a few-set warmup over the full node axis warms
    exactly the shapes the measured converge dispatches. The stacked
    arm's slab kernels are node-count-invariant; its batch-axis shape
    still differs between warmup and measurement (few partitions carry
    warmup gangs), so one pow2 batch-lane compile can land in the
    partitioned arm's wall — conservative against the speedup, noted
    rather than hidden."""
    # both arms pinned serial (workers=1) for the same reason as
    # inert_ab: the ≥1.8× wall gate compares against PR-10-era numbers,
    # so an ambient GROVE_TPU_CP_WORKERS must not change the executor
    _w1, _r1 = converge_population(
        min(n_sets, 16), n_nodes, num_shards=1, workers=1
    )
    _close_harness(_w1)
    _w2, _r2 = converge_population(
        min(n_sets, 16), n_nodes, num_shards=1, frontier=True, workers=1
    )
    _close_harness(_w2)
    _off_h, off = converge_population(n_sets, n_nodes, num_shards, workers=1)
    _close_harness(_off_h)
    del _off_h
    gc.collect()
    _on_h, on = converge_population(
        n_sets, n_nodes, num_shards, frontier=True, workers=1
    )
    _close_harness(_on_h)
    del _on_h
    gc.collect()
    return {
        "sets": n_sets,
        "nodes": n_nodes,
        "wall_off": off["wall_seconds"],
        "wall_on": on["wall_seconds"],
        "solver_off": off["solver_seconds"],
        "solver_on": on["solver_seconds"],
        "speedup_wall": round(
            off["wall_seconds"] / max(on["wall_seconds"], 1e-9), 2
        ),
        "speedup_solver": round(
            off["solver_seconds"] / max(on["solver_seconds"], 1e-9), 2
        ),
        "all_ready_both": off["all_ready"] and on["all_ready"],
        "frontier": on.get("frontier", {}),
    }


def scale_artifact(
    n_sets: int = 62_500,
    n_nodes: int = 100_000,
    num_shards: int = 8,
    ab_sets: int = 192,
    frontier_ab_shape: Tuple[int, int] = (512, 512),
    workers: int = 0,
    shape_1m: Optional[Tuple[int, int, int]] = None,
) -> dict:
    """The bench ``"scale"`` block: the big sharded converge (partitioned
    frontier ON — the PR 10 configuration; parallel control plane per
    ``workers``/GROVE_TPU_CP_WORKERS — the PR 15 configuration) + the
    small S=1 inert A/B + the paired frontier on/off A/B. Caller picks
    the shape (the integrated bench passes the full 100k-node shape only
    on full-size runs).

    ``shape_1m``: (sets, nodes, shards) of the ROADMAP's 1M-pod shape —
    when given, a second DARK converge runs it (workers + frontier on)
    and lands under ``"shape_1m"``; the gate is that the shape is
    benchable at all, so the row reports whatever wall it measures."""
    # glassbox=True: the headline converge ships its own wall-attribution
    # ledger ("attribution": per-(controller, shard, phase) with the
    # ≥95%-coverage claim, plus per-worker utilization when the parallel
    # control plane ran) and per-gang admission decomposition. The A/Bs
    # below stay dark so their walls are comparable across PRs.
    harness, report = converge_population(
        n_sets, n_nodes, num_shards, frontier=True, glassbox=True,
        workers=workers,
    )
    # release the big population before the A/B runs its twin harnesses
    # (engine.close() first: GC alone leaves the armed ParallelDrain's
    # worker threads alive for the process lifetime; the frontier's
    # device pool likewise)
    _close_harness(harness)
    del harness
    gc.collect()
    report["inert_ab"] = inert_ab(n_sets=ab_sets, num_shards=num_shards)
    report["frontier_ab"] = frontier_ab(
        n_sets=frontier_ab_shape[0],
        n_nodes=frontier_ab_shape[1],
        num_shards=num_shards,
    )
    # worker-process backend: the paired overlap+codec A/B at the PR-2
    # control-plane shape (docs/control-plane.md §5) — the ≥10%
    # µs/reconcile-reduction gate's evidence row, host-stamped
    from grove_tpu.sim.parallel import process_codec_ab

    gc.collect()
    report["process_ab"] = process_codec_ab()
    if shape_1m is not None:
        m_sets, m_nodes, m_shards = shape_1m
        gc.collect()
        m_harness, m_report = converge_population(
            m_sets, m_nodes, m_shards, frontier=True, workers=workers
        )
        _close_harness(m_harness)
        del m_harness
        gc.collect()
        report["shape_1m"] = m_report
    return report


def _close_harness(h: SimHarness) -> None:
    """Release a retired harness's thread pools (the parallel drain's
    workers, the frontier's device pool) — GC alone leaves executor
    threads alive until process exit."""
    h.engine.close()
    if h.scheduler is not None and h.scheduler.frontier is not None:
        h.scheduler.frontier.close()
