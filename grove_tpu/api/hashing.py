"""Deterministic object hashing for rolling-update triggers.

Equivalent of the reference's ComputeHash over all pod templates
(/root/reference/operator/internal/controller/podcliqueset/reconcilespec.go:110-123
and internal/utils/kubernetes object hashing): a generation hash of the PCS
template that, when changed, starts a rolling update; and a per-clique
pod-template hash stamped as the `grove.io/pod-template-hash` label.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import json
import threading
from typing import Any


def _normalize(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            f.name: _normalize(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
    if isinstance(obj, dict):
        return {str(k): _normalize(v) for k, v in sorted(obj.items())}
    if isinstance(obj, (list, tuple)):
        return [_normalize(v) for v in obj]
    return obj


def compute_hash(obj: Any) -> str:
    """Stable short hash of any dataclass/dict tree."""
    payload = json.dumps(_normalize(obj), sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def _clique_template_payload(clique_template, priority_class_name: str = ""):
    """The hashed view of one clique: mirrors the reference, which hashes a
    PodTemplateSpec carrying the clique's labels/annotations with the PCS
    template's priorityClassName overlaid (component/utils/podclique.go)."""
    return {
        "name": clique_template.name,
        "labels": dict(clique_template.labels),
        "annotations": dict(clique_template.annotations),
        "roleName": clique_template.spec.role_name,
        "priorityClassName": priority_class_name,
        "podSpec": _normalize(clique_template.spec.pod_spec),
    }


# Hash memoization. A CR's spec is immutable per (uid, generation) — the
# store bumps generation on every spec write — so template hashes can be
# cached on that key instead of re-normalizing the whole template tree on
# every reconcile (profiling: _normalize was a top-3 control-plane cost).
# Unsaved objects (no uid / generation 0, e.g. webhook-time) are never
# cached.
#
# Eviction is FIFO of the oldest QUARTER, not wholesale clear: each live CR
# holds ~5 keys (generation hash + one per clique) and superseded
# generations age out naturally, so insertion order approximates liveness.
# The round-3 wholesale clear at 8,192 caused cache THRASH at scale — a
# 2,000-set population holds ~10k live keys, so every clear forced every
# reconcile to re-normalize whole template trees (profiled: 12M _normalize
# calls, ~30% of the 2,000-set converge; the "+40% per-reconcile at 2x
# objects" growth was mostly this). Entries are ~100 bytes (tuple key +
# 16-char hash), so the full cap holds roughly 26 MB and covers ~50k live
# CRs; each eviction drops a ~6.5 MB quarter.
_HASH_CACHE: dict = {}
_HASH_CACHE_MAX = 262_144
_EVICT_LOCK = threading.Lock()


def _cached(key, compute):
    if key is None:
        return compute()
    h = _HASH_CACHE.get(key)
    if h is None:
        if len(_HASH_CACHE) >= _HASH_CACHE_MAX:
            # dicts iterate in insertion order: drop the oldest quarter.
            # Concurrent reconcile threads (Engine.drain_concurrent) may
            # race here — the lock keeps the snapshot-and-delete atomic,
            # and pop(None) tolerates a key another thread already evicted.
            with _EVICT_LOCK:
                if len(_HASH_CACHE) >= _HASH_CACHE_MAX:
                    for stale in list(
                        itertools.islice(
                            iter(_HASH_CACHE), _HASH_CACHE_MAX // 4
                        )
                    ):
                        _HASH_CACHE.pop(stale, None)
        h = compute()
        # Insert under the same lock as eviction: the eviction snapshot
        # iterates the dict, and an unlocked concurrent insert is only safe
        # by the grace of CPython's GIL (free-threaded builds would raise
        # "dictionary changed size during iteration"). Uncontended in the
        # warm path, which never reaches here.
        with _EVICT_LOCK:
            _HASH_CACHE[key] = h
    return h


def _gen_key(owner, scope: str):
    meta = owner.metadata
    if meta.uid and meta.generation:
        return (meta.uid, meta.generation, scope)
    return None


def compute_pcs_generation_hash(pcs) -> str:
    """Hash of every clique's pod template (not replica counts — scaling is
    not an update); changing it starts the rolling update flow
    (reconcilespec.go:72-123)."""

    def compute():
        pcn = pcs.spec.template.priority_class_name
        parts = [
            _clique_template_payload(c, pcn) for c in pcs.spec.template.cliques
        ]
        return compute_hash({"cliques": parts})

    return _cached(_gen_key(pcs, "pcs-generation"), compute)


def compute_pod_template_hash(clique_template, priority_class_name: str = "") -> str:
    return compute_hash(_clique_template_payload(clique_template, priority_class_name))


def pod_template_hash_for(pcs, clique_name: str):
    """Cached per-(uid, generation, clique) pod-template hash; None when the
    PCS template has no such clique."""

    def compute():
        tmpl = pcs.spec.template.clique_template(clique_name)
        if tmpl is None:
            return None
        return compute_pod_template_hash(
            tmpl, pcs.spec.template.priority_class_name
        )

    return _cached(_gen_key(pcs, f"clique:{clique_name}"), compute)
