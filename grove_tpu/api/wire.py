"""Wire codec: camelCase JSON/YAML documents ⇄ typed API objects.

The decode half of `api/serialize.py`'s export: a reflective dataclass
decoder keyed on type hints, with the handful of format quirks the reference
wire format carries (nested container `resources`, `cliqueStartupType`,
`podCliqueScalingGroups`, quantity/duration strings). Together they give the
real-cluster mode (grove_tpu.cluster) a lossless object round trip, while
still accepting reference-format user manifests unchanged
(/root/reference/operator/samples/).

Also holds the kind registry (group/version/plural) mirroring the CRDs the
reference embeds (/root/reference/operator/api/core/v1alpha1/crds/,
/root/reference/scheduler/api/core/v1alpha1/crds/).
"""

from __future__ import annotations

import dataclasses
import functools
import typing
from typing import Any, Dict, Optional

from grove_tpu.api.meta import (
    NamespacedName,
    ObjectMeta,
    parse_quantity,
)
from grove_tpu.api.pod import Pod
from grove_tpu.api.topology import ClusterTopology
from grove_tpu.api.types import (
    Container,
    GenericObject,
    PodClique,
    PodCliqueScalingGroup,
    PodCliqueSet,
    PodCliqueSetTemplateSpec,
    PodGang,
    Queue,
    parse_duration,
)

# ---------------------------------------------------------------------------
# Kind registry
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class KindInfo:
    kind: str
    cls: type
    group: str  # "" = core
    version: str
    plural: str
    namespaced: bool = True

    @property
    def api_version(self) -> str:
        return f"{self.group}/{self.version}" if self.group else self.version


_KINDS = [
    KindInfo("PodCliqueSet", PodCliqueSet, "grove.io", "v1alpha1", "podcliquesets"),
    KindInfo("PodClique", PodClique, "grove.io", "v1alpha1", "podcliques"),
    KindInfo(
        "PodCliqueScalingGroup",
        PodCliqueScalingGroup,
        "grove.io",
        "v1alpha1",
        "podcliquescalinggroups",
    ),
    KindInfo(
        "ClusterTopology",
        ClusterTopology,
        "grove.io",
        "v1alpha1",
        "clustertopologies",
        namespaced=False,
    ),
    KindInfo(
        "PodGang", PodGang, "scheduler.grove.io", "v1alpha1", "podgangs"
    ),
    # multi-tenant quota queue (docs/quota.md) — cluster-scoped like
    # ClusterTopology; lives in the scheduler group (fair-share ordering
    # and reclaim are scheduler-side semantics)
    KindInfo(
        "Queue",
        Queue,
        "scheduler.grove.io",
        "v1alpha1",
        "queues",
        namespaced=False,
    ),
    KindInfo("Pod", Pod, "", "v1", "pods"),
    # generic child kinds the operator materializes (sim-shaped spec dicts)
    KindInfo("Service", GenericObject, "", "v1", "services"),
    KindInfo("ServiceAccount", GenericObject, "", "v1", "serviceaccounts"),
    KindInfo("Secret", GenericObject, "", "v1", "secrets"),
    KindInfo("Event", GenericObject, "", "v1", "events"),
    KindInfo(
        "Role", GenericObject, "rbac.authorization.k8s.io", "v1", "roles"
    ),
    KindInfo(
        "RoleBinding",
        GenericObject,
        "rbac.authorization.k8s.io",
        "v1",
        "rolebindings",
    ),
    KindInfo(
        "HorizontalPodAutoscaler",
        GenericObject,
        "autoscaling",
        "v2",
        "horizontalpodautoscalers",
    ),
    # leader-election lease (coordination.k8s.io/v1, manager.go:84-98)
    KindInfo("Lease", GenericObject, "coordination.k8s.io", "v1", "leases"),
    # persisted node-drain intent (grove_tpu/disruption/drain.py): stored —
    # not controller memory — so a leader failover resumes in-flight drains
    KindInfo(
        "NodeDrain",
        GenericObject,
        "scheduler.grove.io",
        "v1alpha1",
        "nodedrains",
        namespaced=False,
    ),
]

KIND_REGISTRY: Dict[str, KindInfo] = {k.kind: k for k in _KINDS}
PLURAL_REGISTRY: Dict[str, KindInfo] = {k.plural: k for k in _KINDS}


# ---------------------------------------------------------------------------
# Reflective decoder
# ---------------------------------------------------------------------------


def _snake(name: str) -> str:
    out = []
    for ch in name:
        if ch.isupper():
            out.append("_")
            out.append(ch.lower())
        else:
            out.append(ch)
    return "".join(out)


def _camel(name: str) -> str:
    head, *rest = name.split("_")
    return head + "".join(w.capitalize() for w in rest)


# wire key → field name aliases where the reference format diverges from
# plain camelization (reference podcliqueset.go:123-156)
_FIELD_ALIASES: Dict[type, Dict[str, str]] = {
    PodCliqueSetTemplateSpec: {
        "cliqueStartupType": "startup_type",
        "podCliqueScalingGroups": "pod_clique_scaling_group_configs",
        # our own export camelizes the field name — accept it back
        "startupType": "startup_type",
        "podCliqueScalingGroupConfigs": "pod_clique_scaling_group_configs",
    },
}


def _coerce_scalar(hint: type, value: Any, quantity: bool = False) -> Any:
    if hint is float:
        if isinstance(value, str):
            # resource maps carry quantity strings ("200m" = 0.2 cpu);
            # scalar float fields carry durations ("4h") — the two notations
            # collide on the m/h suffixes, so context decides
            if quantity:
                return parse_quantity(value)
            try:
                return parse_duration(value)
            except ValueError:
                return parse_quantity(value)
        return float(value)
    if hint is int:
        return int(value)
    if hint is bool:
        return bool(value)
    if hint is str:
        return str(value)
    return value


# -- per-class decode plans (the process-boundary codec shave) --------------
#
# `typing.get_type_hints` re-evaluates every stringified annotation (PEP 563)
# through `_eval_type` on EVERY call — profiled at >75% of decode wall on the
# worker-process boundary, where the coordinator decodes each worker commit
# envelope and every worker decodes the sync stream (docs/control-plane.md
# §5). Hints, field tables and Optional-unwrapped per-field hints are all
# pure functions of the class object, so they memoize exactly once.
#
# NO_MEMO restores the pre-shave reflective path (fresh get_type_hints /
# fields walk per decode). It exists ONLY so the bench's paired codec A/B
# (sim/parallel.py process_codec_ab) can measure the shave honestly inside
# one process — same build, same population, toggled per arm. Decoded
# output is identical either way (pinned by the A/B's content check).
NO_MEMO = False


@functools.lru_cache(maxsize=None)
def _class_hints(cls: type) -> Dict[str, Any]:
    return typing.get_type_hints(cls)


@functools.lru_cache(maxsize=None)
def _class_fields(cls: type) -> Dict[str, Any]:
    return {f.name: f for f in dataclasses.fields(cls)}


@functools.lru_cache(maxsize=None)
def _field_hint(cls: type, fname: str) -> Any:
    """The field's hint with Optional[X] pre-unwrapped to X — the per-value
    decoder then skips the Union branch entirely on the hot path."""
    hint = _class_hints(cls)[fname]
    if typing.get_origin(hint) is typing.Union:
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        return args[0] if args else Any
    return hint


def _decode_value(hint: Any, value: Any) -> Any:
    if value is None:
        return None
    origin = typing.get_origin(hint)
    if origin is typing.Union:  # Optional[X]
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        return _decode_value(args[0], value) if args else value
    if origin in (list, typing.List):
        (item_hint,) = typing.get_args(hint) or (Any,)
        return [_decode_value(item_hint, v) for v in value]
    if origin in (dict, typing.Dict):
        args = typing.get_args(hint)
        val_hint = args[1] if len(args) == 2 else Any
        if val_hint in (float, int):
            return {
                k: _coerce_scalar(val_hint, v, quantity=True)
                for k, v in value.items()
            }
        return dict(value)
    if dataclasses.is_dataclass(hint):
        return decode_dataclass(hint, value)
    if hint in (float, int, bool, str):
        return _coerce_scalar(hint, value)
    return value


def decode_dataclass(cls: type, doc: Dict[str, Any]):
    """Wire dict → dataclass instance (inverse of serialize.to_dict)."""
    if not isinstance(doc, dict):
        raise ValueError(f"expected object for {cls.__name__}, got {doc!r}")
    doc = dict(doc)
    if cls is Container and "resources" in doc:
        # reference container format nests requests/limits under `resources`
        res = doc.pop("resources") or {}
        doc.setdefault("requests", res.get("requests") or {})
        doc.setdefault("limits", res.get("limits") or {})
    if NO_MEMO:  # pre-shave reference path (bench codec A/B only)
        hints = typing.get_type_hints(cls)
        fields = {f.name: f for f in dataclasses.fields(cls)}
    else:
        hints = None
        fields = _class_fields(cls)
    aliases = _FIELD_ALIASES.get(cls, {})
    kwargs: Dict[str, Any] = {}
    leftovers: Dict[str, Any] = {}
    for key, value in doc.items():
        fname = aliases.get(key) or (
            key if key in fields else _snake(key)
        )
        if fname in fields:
            hint = hints[fname] if hints is not None else _field_hint(cls, fname)
            kwargs[fname] = _decode_value(hint, value)
        else:
            leftovers[key] = value
    # unmodeled keys pass through into `extra` when the type carries one
    # (Container/PodSpec — keeps template hashing change-sensitive)
    if leftovers and "extra" in fields:
        extra = dict(kwargs.get("extra") or {})
        for k, v in leftovers.items():
            extra.setdefault(k, v)
        kwargs["extra"] = extra
    return cls(**kwargs)


def _decode_metadata(doc: Dict[str, Any]) -> ObjectMeta:
    meta = decode_dataclass(ObjectMeta, doc or {})
    if not meta.namespace:
        meta.namespace = "default"
    return meta


def decode_object(doc: Dict[str, Any]):
    """Full CR document (apiVersion/kind/metadata/spec/status) → object."""
    kind = doc.get("kind")
    info = KIND_REGISTRY.get(kind or "")
    if info is None:
        raise ValueError(f"unsupported kind {kind!r}")
    cls = info.cls
    meta = _decode_metadata(doc.get("metadata") or {})
    if not info.namespaced:
        meta.namespace = ""
    if cls is GenericObject:
        return GenericObject(kind=kind, metadata=meta, spec=dict(doc.get("spec") or {}))
    hints = typing.get_type_hints(cls) if NO_MEMO else _class_hints(cls)
    kwargs: Dict[str, Any] = {"metadata": meta}
    if "spec" in hints and doc.get("spec") is not None:
        kwargs["spec"] = _decode_value(
            hints["spec"] if NO_MEMO else _field_hint(cls, "spec"),
            doc["spec"],
        )
    if "status" in hints and doc.get("status") is not None:
        kwargs["status"] = _decode_value(
            hints["status"] if NO_MEMO else _field_hint(cls, "status"),
            doc["status"],
        )
    obj = cls(**kwargs)
    return obj


def resolve_path_kind(group: str, version: str, plural: str) -> Optional[KindInfo]:
    info = PLURAL_REGISTRY.get(plural)
    if info is None:
        return None
    if info.group != group or info.version != version:
        return None
    return info
