"""Grove-TPU domain model: the operator API surface.

Dataclass re-host of the reference CRD types, preserving field semantics and
the camelCase YAML wire format so reference manifests load unchanged:
- PodCliqueSet:          /root/reference/operator/api/core/v1alpha1/podcliqueset.go
- PodClique:             /root/reference/operator/api/core/v1alpha1/podclique.go
- PodCliqueScalingGroup: /root/reference/operator/api/core/v1alpha1/scalinggroup.go
- PodGang (contract):    /root/reference/scheduler/api/core/v1alpha1/podgang.go

Architecture note: unlike the Go reference (whose types exist to be serialized
into etcd), these objects live in the in-memory store (grove_tpu.runtime.store)
and double as the host-side staging form the TPU placement encoder consumes
(grove_tpu.solver.encode) — hence plain dataclasses with cheap deep-copy, no
codegen clients.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from grove_tpu.api.meta import (
    Condition,
    NamespacedName,
    ObjectMeta,
    parse_resource_map,
)

# ---------------------------------------------------------------------------
# Constants / enums
# ---------------------------------------------------------------------------

API_GROUP = "grove.io"
SCHEDULER_API_GROUP = "scheduler.grove.io"

# CliqueStartupType — podcliqueset.go:243-255
STARTUP_ANY_ORDER = "CliqueStartupTypeAnyOrder"
STARTUP_IN_ORDER = "CliqueStartupTypeInOrder"
STARTUP_EXPLICIT = "CliqueStartupTypeExplicit"
STARTUP_TYPES = (STARTUP_ANY_ORDER, STARTUP_IN_ORDER, STARTUP_EXPLICIT)

# PodGangPhase — scheduler podgang.go:139-151 and operator podcliqueset.go:267-284
PHASE_PENDING = "Pending"
PHASE_STARTING = "Starting"
PHASE_RUNNING = "Running"

# Condition types
COND_POD_CLIQUE_SCHEDULED = "PodCliqueScheduled"
COND_MIN_AVAILABLE_BREACHED = "MinAvailableBreached"
COND_PODGANG_SCHEDULED = "Scheduled"
COND_PODGANG_READY = "Ready"
COND_PODGANG_UNHEALTHY = "Unhealthy"
COND_PODGANG_DISRUPTION_TARGET = "DisruptionTarget"

# Default gang-termination delay — podcliqueset.go:146-153 (4 hours)
DEFAULT_TERMINATION_DELAY_SECONDS = 4 * 60 * 60.0

# Scheduling gate applied to every grove-managed pod at creation
# (reference: podclique/components/pod/pod.go:68 "grove.io/podgang-pending-creation")
PODGANG_SCHEDULING_GATE = "grove.io/podgang-pending-creation"


# ---------------------------------------------------------------------------
# Pod template subset
# ---------------------------------------------------------------------------


@dataclass
class Container:
    name: str
    image: str = ""
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    requests: Dict[str, float] = field(default_factory=dict)
    limits: Dict[str, float] = field(default_factory=dict)
    env: List[Dict[str, Any]] = field(default_factory=list)
    # Unmodeled container fields (ports, volumeMounts, probes, …) pass through
    # so template hashing sees every user-visible change.
    extra: Dict[str, Any] = field(default_factory=dict)

    def env_value(self, name: str) -> Optional[str]:
        for e in self.env:
            if e.get("name") == name:
                return e.get("value")
        return None

    def set_env(self, name: str, value: str) -> None:
        for e in self.env:
            if e.get("name") == name:
                e["value"] = value
                return
        self.env.append({"name": name, "value": value})

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Container":
        res = d.get("resources") or {}
        known = {"name", "image", "command", "args", "resources", "env"}
        return Container(
            name=d["name"],
            image=d.get("image", ""),
            command=list(d.get("command") or []),
            args=list(d.get("args") or []),
            requests=parse_resource_map(res.get("requests")),
            limits=parse_resource_map(res.get("limits")),
            env=[dict(e) for e in d.get("env") or []],
            extra={k: v for k, v in d.items() if k not in known},
        )


@dataclass
class PodSpec:
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    node_selector: Dict[str, str] = field(default_factory=dict)
    tolerations: List[Dict[str, Any]] = field(default_factory=list)
    priority_class_name: str = ""
    scheduler_name: str = ""
    restart_policy: str = ""
    # Fields set by the operator on build (not by users):
    hostname: str = ""
    subdomain: str = ""
    scheduling_gates: List[str] = field(default_factory=list)
    service_account_name: str = ""
    extra: Dict[str, Any] = field(default_factory=dict)

    def total_requests(self) -> Dict[str, float]:
        """Aggregate resource requests across containers (scheduler's view)."""
        out: Dict[str, float] = {}
        for c in self.containers:
            for k, v in c.requests.items():
                out[k] = out.get(k, 0.0) + v
        return out

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PodSpec":
        known = {
            "containers",
            "initContainers",
            "nodeSelector",
            "tolerations",
            "priorityClassName",
            "schedulerName",
            "restartPolicy",
        }
        return PodSpec(
            containers=[Container.from_dict(c) for c in d.get("containers") or []],
            init_containers=[
                Container.from_dict(c) for c in d.get("initContainers") or []
            ],
            node_selector=dict(d.get("nodeSelector") or {}),
            tolerations=list(d.get("tolerations") or []),
            priority_class_name=d.get("priorityClassName", ""),
            scheduler_name=d.get("schedulerName", ""),
            restart_policy=d.get("restartPolicy", ""),
            extra={k: v for k, v in d.items() if k not in known},
        )


# ---------------------------------------------------------------------------
# Autoscaling
# ---------------------------------------------------------------------------


@dataclass
class AutoScalingConfig:
    """podclique.go:81-101 AutoScalingConfig / scalinggroup ScaleConfig."""

    max_replicas: int = 0
    min_replicas: Optional[int] = None
    metrics: List[Dict[str, Any]] = field(default_factory=list)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "AutoScalingConfig":
        return AutoScalingConfig(
            max_replicas=int(d.get("maxReplicas", 0)),
            min_replicas=(
                int(d["minReplicas"]) if d.get("minReplicas") is not None else None
            ),
            metrics=list(d.get("metrics") or []),
        )


# ---------------------------------------------------------------------------
# Topology constraints (operator-side, level *names*)
# ---------------------------------------------------------------------------


SPREAD_DO_NOT_SCHEDULE = "DoNotSchedule"
SPREAD_SCHEDULE_ANYWAY = "ScheduleAnyway"
SPREAD_UNSATISFIABLE_MODES = (SPREAD_DO_NOT_SCHEDULE, SPREAD_SCHEDULE_ANYWAY)


@dataclass
class TopologyConstraint:
    """podcliqueset.go:186-199 — packDomain holds a topology *level name*
    (e.g. 'ici-block'); the operator translates it into node-label topology
    keys on the PodGang (docs/designs/topology.md:541-616).

    spreadDomain extends the contract with topology SPREAD (the reference's
    2026 roadmap item, README.md "Topology Spread Constraints", unshipped
    there): balance the unit's pods across the domains of that level —
    fault-tolerance counterpart of packing. Composes with packDomain when
    spreadDomain is strictly narrower (pack the gang into one slice, spread
    its pods across the hosts inside it)."""

    pack_domain: Optional[str] = None
    spread_domain: Optional[str] = None
    # minimum distinct domains a placement must span (defaulted to 2)
    spread_min_domains: Optional[int] = None
    # DoNotSchedule (hard — reject placements below the floor) or
    # ScheduleAnyway (soft — spread shapes the PlacementScore only)
    spread_when_unsatisfiable: Optional[str] = None

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> Optional["TopologyConstraint"]:
        if not d:
            return None
        return TopologyConstraint(
            pack_domain=d.get("packDomain"),
            spread_domain=d.get("spreadDomain"),
            spread_min_domains=d.get("spreadMinDomains"),
            spread_when_unsatisfiable=d.get("spreadWhenUnsatisfiable"),
        )


# ---------------------------------------------------------------------------
# PodClique
# ---------------------------------------------------------------------------


@dataclass
class PodCliqueSpec:
    """podclique.go:53-79."""

    role_name: str = ""
    replicas: int = 1
    min_available: Optional[int] = None
    starts_after: List[str] = field(default_factory=list)
    pod_spec: PodSpec = field(default_factory=PodSpec)
    auto_scaling_config: Optional[AutoScalingConfig] = None

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PodCliqueSpec":
        asc = d.get("autoScalingConfig")
        return PodCliqueSpec(
            role_name=d.get("roleName", ""),
            replicas=int(d.get("replicas", 1)),
            min_available=(
                int(d["minAvailable"]) if d.get("minAvailable") is not None else None
            ),
            starts_after=list(d.get("startsAfter") or []),
            pod_spec=PodSpec.from_dict(d.get("podSpec") or {}),
            auto_scaling_config=AutoScalingConfig.from_dict(asc) if asc else None,
        )


@dataclass
class PodCliqueTemplateSpec:
    """podcliqueset.go:159-183."""

    name: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    topology_constraint: Optional[TopologyConstraint] = None
    spec: PodCliqueSpec = field(default_factory=PodCliqueSpec)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PodCliqueTemplateSpec":
        return PodCliqueTemplateSpec(
            name=d["name"],
            labels=dict(d.get("labels") or {}),
            annotations=dict(d.get("annotations") or {}),
            topology_constraint=TopologyConstraint.from_dict(
                d.get("topologyConstraint")
            ),
            spec=PodCliqueSpec.from_dict(d.get("spec") or {}),
        )


@dataclass
class PodCliqueStatus:
    """podclique.go:103-137."""

    observed_generation: Optional[int] = None
    replicas: int = 0
    ready_replicas: int = 0
    schedule_gated_replicas: int = 0
    scheduled_replicas: int = 0
    updated_replicas: int = 0
    conditions: List[Condition] = field(default_factory=list)
    selector: Optional[str] = None
    last_errors: List[Dict[str, Any]] = field(default_factory=list)


@dataclass
class PodClique:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodCliqueSpec = field(default_factory=PodCliqueSpec)
    status: PodCliqueStatus = field(default_factory=PodCliqueStatus)
    kind: str = "PodClique"


# ---------------------------------------------------------------------------
# PodCliqueScalingGroup
# ---------------------------------------------------------------------------


@dataclass
class PodCliqueScalingGroupConfig:
    """podcliqueset.go:201-233 (template-level config)."""

    name: str = ""
    clique_names: List[str] = field(default_factory=list)
    replicas: Optional[int] = None
    min_available: Optional[int] = None
    scale_config: Optional[AutoScalingConfig] = None
    topology_constraint: Optional[TopologyConstraint] = None

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PodCliqueScalingGroupConfig":
        sc = d.get("scaleConfig")
        return PodCliqueScalingGroupConfig(
            name=d["name"],
            clique_names=list(d.get("cliqueNames") or []),
            replicas=int(d["replicas"]) if d.get("replicas") is not None else None,
            min_available=(
                int(d["minAvailable"]) if d.get("minAvailable") is not None else None
            ),
            scale_config=AutoScalingConfig.from_dict(sc) if sc else None,
            topology_constraint=TopologyConstraint.from_dict(
                d.get("topologyConstraint")
            ),
        )


@dataclass
class PodCliqueScalingGroupSpec:
    """scalinggroup.go:50-71 (materialized CR spec)."""

    replicas: int = 1
    min_available: int = 1
    clique_names: List[str] = field(default_factory=list)


@dataclass
class PCSGRollingUpdateProgress:
    """scalinggroup.go:105-129."""

    update_started_at: float = 0.0
    update_ended_at: Optional[float] = None
    ready_replica_indices_selected_to_update: List[int] = field(default_factory=list)
    updated_replica_indices: List[int] = field(default_factory=list)


@dataclass
class PodCliqueScalingGroupStatus:
    """scalinggroup.go:73-103."""

    observed_generation: Optional[int] = None
    replicas: int = 0
    scheduled_replicas: int = 0
    available_replicas: int = 0
    updated_replicas: int = 0
    selector: Optional[str] = None
    conditions: List[Condition] = field(default_factory=list)
    rolling_update_progress: Optional[PCSGRollingUpdateProgress] = None
    last_errors: List[Dict[str, Any]] = field(default_factory=list)


@dataclass
class PodCliqueScalingGroup:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodCliqueScalingGroupSpec = field(default_factory=PodCliqueScalingGroupSpec)
    status: PodCliqueScalingGroupStatus = field(
        default_factory=PodCliqueScalingGroupStatus
    )
    kind: str = "PodCliqueScalingGroup"


# ---------------------------------------------------------------------------
# PodCliqueSet
# ---------------------------------------------------------------------------


@dataclass
class HeadlessServiceConfig:
    publish_not_ready_addresses: bool = True


@dataclass
class DisruptionBudget:
    """grove-tpu extension (docs/robustness.md "voluntary disruption"): a
    PodDisruptionBudget at GANG granularity, enforced by the
    DisruptionBroker (grove_tpu/disruption) against every VOLUNTARY
    disruptor — node drain, priority preemption, quota reclaim, rolling
    update. Involuntary failures (node loss) bypass it but still count
    toward the unavailable tally a voluntary request is checked against.

    ``max_unavailable_gangs``: how many of the set's gangs may be
    voluntarily unavailable at once (0 = block all voluntary disruption).
    ``quiet_window``: minimum virtual seconds between granted voluntary
    disruptions of this set (None = no pacing beyond the budget)."""

    max_unavailable_gangs: Optional[int] = None  # defaulted to 1
    quiet_window: Optional[float] = None  # seconds

    @staticmethod
    def from_dict(d: Optional[Dict[str, Any]]) -> Optional["DisruptionBudget"]:
        if d is None:
            return None
        qw = d.get("quietWindow")
        return DisruptionBudget(
            max_unavailable_gangs=(
                int(d["maxUnavailableGangs"])
                if d.get("maxUnavailableGangs") is not None
                else None
            ),
            quiet_window=parse_duration(qw) if qw is not None else None,
        )


@dataclass
class PodCliqueSetTemplateSpec:
    """podcliqueset.go:123-156."""

    cliques: List[PodCliqueTemplateSpec] = field(default_factory=list)
    startup_type: Optional[str] = None
    priority_class_name: str = ""
    headless_service_config: Optional[HeadlessServiceConfig] = None
    topology_constraint: Optional[TopologyConstraint] = None
    termination_delay: Optional[float] = None  # seconds
    disruption_budget: Optional[DisruptionBudget] = None
    pod_clique_scaling_group_configs: List[PodCliqueScalingGroupConfig] = field(
        default_factory=list
    )

    def clique_template(self, name: str) -> Optional[PodCliqueTemplateSpec]:
        for c in self.cliques:
            if c.name == name:
                return c
        return None

    def standalone_clique_templates(self) -> List[PodCliqueTemplateSpec]:
        """Cliques not owned by any scaling group."""
        in_sg = {
            n
            for cfg in self.pod_clique_scaling_group_configs
            for n in cfg.clique_names
        }
        return [c for c in self.cliques if c.name not in in_sg]

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PodCliqueSetTemplateSpec":
        hsc = d.get("headlessServiceConfig")
        td = d.get("terminationDelay")
        return PodCliqueSetTemplateSpec(
            cliques=[PodCliqueTemplateSpec.from_dict(c) for c in d.get("cliques") or []],
            startup_type=d.get("cliqueStartupType"),
            priority_class_name=d.get("priorityClassName", ""),
            headless_service_config=(
                HeadlessServiceConfig(
                    publish_not_ready_addresses=bool(
                        hsc.get("publishNotReadyAddresses", True)
                    )
                )
                if hsc
                else None
            ),
            topology_constraint=TopologyConstraint.from_dict(
                d.get("topologyConstraint")
            ),
            termination_delay=parse_duration(td) if td is not None else None,
            disruption_budget=DisruptionBudget.from_dict(
                d.get("disruptionBudget")
            ),
            pod_clique_scaling_group_configs=[
                PodCliqueScalingGroupConfig.from_dict(g)
                for g in d.get("podCliqueScalingGroups") or []
            ],
        )


@dataclass
class PodCliqueSetSpec:
    """podcliqueset.go:52-58."""

    replicas: int = 1
    template: PodCliqueSetTemplateSpec = field(
        default_factory=PodCliqueSetTemplateSpec
    )

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PodCliqueSetSpec":
        return PodCliqueSetSpec(
            replicas=int(d.get("replicas", 1)),
            template=PodCliqueSetTemplateSpec.from_dict(d.get("template") or {}),
        )


@dataclass
class PCSReplicaRollingUpdateProgress:
    """podcliqueset.go:110-119."""

    replica_index: int = 0
    update_started_at: float = 0.0


@dataclass
class PCSRollingUpdateProgress:
    """podcliqueset.go:93-108."""

    update_started_at: float = 0.0
    update_ended_at: Optional[float] = None
    updated_pod_clique_scaling_groups: List[str] = field(default_factory=list)
    updated_pod_cliques: List[str] = field(default_factory=list)
    currently_updating: Optional[PCSReplicaRollingUpdateProgress] = None


@dataclass
class PodGangStatusSummary:
    """operator-side PodGangStatus mirror in PCS status — podcliqueset.go:258-265."""

    name: str = ""
    phase: str = PHASE_PENDING
    conditions: List[Condition] = field(default_factory=list)


@dataclass
class PodCliqueSetStatus:
    """podcliqueset.go:61-91."""

    observed_generation: Optional[int] = None
    replicas: int = 0
    updated_replicas: int = 0
    available_replicas: int = 0
    selector: Optional[str] = None
    pod_gang_statuses: List[PodGangStatusSummary] = field(default_factory=list)
    current_generation_hash: Optional[str] = None
    rolling_update_progress: Optional[PCSRollingUpdateProgress] = None
    last_errors: List[Dict[str, Any]] = field(default_factory=list)


@dataclass
class PodCliqueSet:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodCliqueSetSpec = field(default_factory=PodCliqueSetSpec)
    status: PodCliqueSetStatus = field(default_factory=PodCliqueSetStatus)
    kind: str = "PodCliqueSet"

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "PodCliqueSet":
        meta = d.get("metadata") or {}
        return PodCliqueSet(
            metadata=ObjectMeta(
                name=meta.get("name", ""),
                namespace=meta.get("namespace", "default"),
                labels=dict(meta.get("labels") or {}),
                annotations=dict(meta.get("annotations") or {}),
            ),
            spec=PodCliqueSetSpec.from_dict(d.get("spec") or {}),
        )


# ---------------------------------------------------------------------------
# PodGang (scheduler contract)
# ---------------------------------------------------------------------------


@dataclass
class TopologyPackConstraint:
    """scheduler podgang.go:101-114 — required/preferred hold *topology keys*
    (node-label keys), already translated from level names by the operator."""

    required: Optional[str] = None
    preferred: Optional[str] = None


@dataclass
class TopologySpreadConstraint:
    """grove-tpu extension of the PodGang contract (no reference analogue —
    'Topology Spread Constraints' is an unshipped roadmap item there):
    balance the gang's pods across the domains of `topology_key`, spanning
    at least `min_domains` distinct domains when `when_unsatisfiable` is
    DoNotSchedule."""

    topology_key: str = ""
    min_domains: int = 2
    when_unsatisfiable: str = SPREAD_DO_NOT_SCHEDULE


@dataclass
class SchedTopologyConstraint:
    """scheduler podgang.go:95-99 (+ the spread extension)."""

    pack_constraint: Optional[TopologyPackConstraint] = None
    spread_constraint: Optional[TopologySpreadConstraint] = None


@dataclass
class PodGroup:
    """scheduler podgang.go:76-91."""

    name: str
    pod_references: List[NamespacedName] = field(default_factory=list)
    min_replicas: int = 0
    topology_constraint: Optional[SchedTopologyConstraint] = None


@dataclass
class TopologyConstraintGroupConfig:
    """scheduler podgang.go:117-126 — PCSG-level pack groups."""

    pod_group_names: List[str] = field(default_factory=list)
    topology_constraint: Optional[SchedTopologyConstraint] = None


@dataclass
class PodGangSpec:
    """scheduler podgang.go:50-74."""

    pod_groups: List[PodGroup] = field(default_factory=list)
    topology_constraint: Optional[SchedTopologyConstraint] = None
    topology_constraint_group_configs: List[TopologyConstraintGroupConfig] = field(
        default_factory=list
    )
    priority_class_name: str = ""
    reuse_reservation_ref: Optional[NamespacedName] = None


@dataclass
class PodGangStatus:
    """scheduler podgang.go:168-176."""

    phase: str = PHASE_PENDING
    conditions: List[Condition] = field(default_factory=list)
    placement_score: Optional[float] = None


@dataclass
class PodGang:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodGangSpec = field(default_factory=PodGangSpec)
    status: PodGangStatus = field(default_factory=PodGangStatus)
    kind: str = "PodGang"


# ---------------------------------------------------------------------------
# Queue (multi-tenant quota & fair-share — scheduler contract extension)
# ---------------------------------------------------------------------------

# The implicit root of the two-level queue tree; every tenant Queue's
# parent defaults to it. Not a CR — it exists only as the tree's anchor.
QUEUE_ROOT = "root"
# Queue gangs land in when their PodCliqueSet carries no queue label (and
# the implicit catch-all when no Queue CR of this name exists).
DEFAULT_QUEUE = "default"


@dataclass
class QueueSpec:
    """grove-tpu extension of the scheduler contract (docs/quota.md): a
    tenant capacity queue in a two-level tree (root → tenant queues),
    borrowing the deserved-share/ceiling semantics of capacity schedulers
    (Kueue ClusterQueue / KAI hierarchical queues — the feature set the
    reference delegates to the external KAI scheduler).

    ``deserved``: per-resource share the queue is entitled to; fair-share
    ordering ranks queues by dominant share usage/deserved, and a queue
    below its deserved share may RECLAIM capacity from queues above theirs.
    ``ceiling``: per-resource hard cap — gangs that would push usage past
    it are held pending (QueuePending) without consuming a solve slot."""

    parent: str = ""  # defaulted to QUEUE_ROOT (two-level tree only)
    deserved: Dict[str, float] = field(default_factory=dict)
    ceiling: Dict[str, float] = field(default_factory=dict)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "QueueSpec":
        return QueueSpec(
            parent=d.get("parent", ""),
            deserved=parse_resource_map(d.get("deserved")),
            ceiling=parse_resource_map(d.get("ceiling")),
        )


@dataclass
class QueueStatus:
    """Written by the gang scheduler each round (write-on-change)."""

    usage: Dict[str, float] = field(default_factory=dict)
    dominant_share: float = 0.0
    admitted_gangs: int = 0
    pending_gangs: int = 0
    conditions: List[Condition] = field(default_factory=list)


@dataclass
class Queue:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: QueueSpec = field(default_factory=QueueSpec)
    status: QueueStatus = field(default_factory=QueueStatus)
    kind: str = "Queue"


# ---------------------------------------------------------------------------
# Generic child resources (Service / HPA / RBAC / Secret)
# ---------------------------------------------------------------------------


@dataclass
class GenericObject:
    """Lightweight stand-in for child kinds the operator materializes but the
    sim doesn't interpret deeply (headless Service, HPA, ServiceAccount, Role,
    RoleBinding, SA-token Secret)."""

    kind: str
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: Dict[str, Any] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ms|h|m|s)")


def parse_duration(value: Any) -> float:
    """Parse a Go-style duration ('4h', '30m', '1h30m', '10s') into seconds."""
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip()
    if not s:
        raise ValueError("empty duration")
    mult = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 1e-3}
    total = 0.0
    pos = 0
    for m in _DURATION_RE.finditer(s):
        if m.start() != pos:
            raise ValueError(f"invalid duration: {value!r}")
        total += float(m.group(1)) * mult[m.group(2)]
        pos = m.end()
    if pos != len(s):
        raise ValueError(f"invalid duration: {value!r}")
    return total
