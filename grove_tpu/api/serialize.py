"""Object → YAML-able dict export (kubectl get -o yaml UX).

Round-trips the camelCase wire convention of the manifest format: snake_case
dataclass fields become camelCase keys; metadata/status included so operators
can inspect live state from the CLI.
"""

from __future__ import annotations

import dataclasses
from typing import Any


def _camel(name: str) -> str:
    head, *rest = name.split("_")
    return head + "".join(w.capitalize() for w in rest)


def to_dict(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            value = to_dict(getattr(obj, f.name))
            if value in (None, [], {}, ""):
                continue
            out[_camel(f.name)] = value
        return out
    if isinstance(obj, dict):
        return {k: to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    return obj


_API_VERSIONS = {
    "PodGang": "scheduler.grove.io/v1alpha1",
    "PodCliqueSet": "grove.io/v1alpha1",
    "PodClique": "grove.io/v1alpha1",
    "PodCliqueScalingGroup": "grove.io/v1alpha1",
    "ClusterTopology": "grove.io/v1alpha1",
    "Pod": "v1",
    "Service": "v1",
    "ServiceAccount": "v1",
    "Secret": "v1",
    "Event": "v1",
    "Role": "rbac.authorization.k8s.io/v1",
    "RoleBinding": "rbac.authorization.k8s.io/v1",
    "HorizontalPodAutoscaler": "autoscaling/v2",
    "Lease": "coordination.k8s.io/v1",
    "NodeDrain": "scheduler.grove.io/v1alpha1",
}


def export_object(obj) -> dict:
    doc = to_dict(obj)
    kind = doc.pop("kind", getattr(obj, "kind", ""))
    return {
        "apiVersion": _API_VERSIONS.get(kind, "grove.io/v1alpha1"),
        "kind": kind,
        **doc,
    }
