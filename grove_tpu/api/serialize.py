"""Object → YAML-able dict export (kubectl get -o yaml UX).

Round-trips the camelCase wire convention of the manifest format: snake_case
dataclass fields become camelCase keys; metadata/status included so operators
can inspect live state from the CLI.

The encoder is compiled per dataclass type (field list + camelCase names
resolved once, cached): the WAL serializes every store commit through this
module (grove_tpu/durability), which turned the naive
fields()-walk-per-object into measurable control-plane overhead.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Tuple

_EMPTY = (None, [], {}, "")

# exact-type fast sets: `type(x) in set` is one hash lookup vs a chain of
# isinstance calls per node (this function visits ~100 nodes per pod)
_SCALARS = frozenset((str, int, float, bool, type(None)))


def _camel(name: str) -> str:
    head, *rest = name.split("_")
    return head + "".join(w.capitalize() for w in rest)


# type -> [(field name, camelCase key)]; dataclass shapes are static, so
# the dataclasses.fields() walk and the camelization happen once per type
_FIELD_CACHE: Dict[type, List[Tuple[str, str]]] = {}


def _fields_of(cls: type) -> List[Tuple[str, str]]:
    cached = _FIELD_CACHE.get(cls)
    if cached is None:
        cached = _FIELD_CACHE[cls] = [
            (f.name, _camel(f.name)) for f in dataclasses.fields(cls)
        ]
    return cached


def to_dict(obj: Any) -> Any:
    t = obj.__class__
    if t in _SCALARS:
        return obj
    if t is dict:
        return {k: to_dict(v) for k, v in obj.items()}
    if t is list or t is tuple:
        return [to_dict(v) for v in obj]
    fields = _FIELD_CACHE.get(t)
    if fields is None:
        if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
            fields = _fields_of(t)
        elif isinstance(obj, dict):  # dict subclass
            return {k: to_dict(v) for k, v in obj.items()}
        elif isinstance(obj, (list, tuple)):  # sequence subclass
            return [to_dict(v) for v in obj]
        else:
            return obj
    out = {}
    for fname, key in fields:
        value = getattr(obj, fname)
        if value.__class__ in _SCALARS:
            # inlined leaf case (scalars dominate field counts); the drop
            # rule for scalars reduces to None/"" — 0/0.0/False survive
            # `value in (None, [], {}, "")` and must keep surviving here
            if value is None or value == "":
                continue
            out[key] = value
            continue
        value = to_dict(value)
        if value in _EMPTY:
            continue
        out[key] = value
    return out


_API_VERSIONS = {
    "PodGang": "scheduler.grove.io/v1alpha1",
    "PodCliqueSet": "grove.io/v1alpha1",
    "PodClique": "grove.io/v1alpha1",
    "PodCliqueScalingGroup": "grove.io/v1alpha1",
    "ClusterTopology": "grove.io/v1alpha1",
    "Pod": "v1",
    "Service": "v1",
    "ServiceAccount": "v1",
    "Secret": "v1",
    "Event": "v1",
    "Role": "rbac.authorization.k8s.io/v1",
    "RoleBinding": "rbac.authorization.k8s.io/v1",
    "HorizontalPodAutoscaler": "autoscaling/v2",
    "Lease": "coordination.k8s.io/v1",
    "NodeDrain": "scheduler.grove.io/v1alpha1",
}


def export_object(obj) -> dict:
    doc = to_dict(obj)
    kind = doc.pop("kind", getattr(obj, "kind", ""))
    return {
        "apiVersion": _API_VERSIONS.get(kind, "grove.io/v1alpha1"),
        "kind": kind,
        **doc,
    }


def export_object_shared(obj, memo: Dict[int, tuple]) -> dict:
    """export_object with an id-keyed memo over TOP-LEVEL subtrees
    (spec/status/metadata). The store's structural-sharing commits make
    sibling objects share subtree IDENTITY (e.g. every pod of a clique
    created from one desired-state template shares its spec object), so a
    batch exporter — the WAL's group-commit flush — serializes each
    shared subtree once per batch instead of once per object. The memo
    holds ``id -> (subtree ref, doc)``; keeping the ref pins the id for
    the memo's lifetime, and the caller must scope the memo to one batch
    whose objects it holds alive."""
    kind = getattr(obj, "kind", "")
    out = {
        "apiVersion": _API_VERSIONS.get(kind, "grove.io/v1alpha1"),
        "kind": kind,
    }
    for fname, key in _fields_of(type(obj)):
        if fname == "kind":
            continue
        value = getattr(obj, fname)
        if value.__class__ in _SCALARS:
            if value is None or value == "":
                continue
            out[key] = value
            continue
        cached = memo.get(id(value))
        if cached is None or cached[0] is not value:
            cached = (value, to_dict(value))
            memo[id(value)] = cached
        doc = cached[1]
        if doc in _EMPTY:
            continue
        out[key] = doc
    return out
