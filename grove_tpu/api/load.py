"""YAML loading for reference-format manifests.

Accepts the exact CR format of the reference samples
(/root/reference/operator/samples/simple/simple1.yaml etc.), so a Grove user
can apply their manifests unchanged.
"""

from __future__ import annotations

from typing import List

import yaml

from grove_tpu.api.types import PodCliqueSet


def load_podcliquesets(text: str) -> List[PodCliqueSet]:
    out: List[PodCliqueSet] = []
    for doc in yaml.safe_load_all(text):
        if not doc:
            continue
        kind = doc.get("kind")
        if kind != "PodCliqueSet":
            raise ValueError(f"unsupported kind {kind!r}")
        out.append(PodCliqueSet.from_dict(doc))
    return out


def load_podcliqueset_file(path: str) -> PodCliqueSet:
    with open(path) as f:
        sets = load_podcliquesets(f.read())
    if len(sets) != 1:
        raise ValueError(f"{path}: expected exactly one PodCliqueSet, got {len(sets)}")
    return sets[0]


def load_manifest_objects(text: str) -> list:
    """Multi-doc manifest → typed objects for ANY wire-registered kind.

    PodCliqueSet keeps the hand-written ``from_dict`` path (the compat
    contract with reference-format manifests); every other kind —
    ClusterTopology, PodGang, ... — decodes through the wire kind
    registry. Offline consumers (CLI validate/apply, tests) share this so
    mixed-kind manifests behave identically everywhere.
    """
    from grove_tpu.api.wire import decode_object

    out = []
    for doc in yaml.safe_load_all(text):
        if not doc:
            continue
        if doc.get("kind") == "PodCliqueSet":
            out.append(PodCliqueSet.from_dict(doc))
        else:
            out.append(decode_object(doc))
    return out
