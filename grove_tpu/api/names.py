"""Deterministic naming + label vocabulary.

Exact parity with the reference so both frameworks agree on child-resource
identity (required for the oracle comparison harness):
- labels:  /root/reference/operator/api/common/constants.go:20-95
- namegen: /root/reference/operator/api/common/namegen.go:27-125
"""

from __future__ import annotations

from typing import Dict

from grove_tpu.api.types import API_GROUP

# --- label keys (constants.go) ---------------------------------------------

LABEL_APP_NAME = "app.kubernetes.io/name"
LABEL_MANAGED_BY = "app.kubernetes.io/managed-by"
LABEL_PART_OF = "app.kubernetes.io/part-of"
LABEL_MANAGED_BY_VALUE = "grove-operator"
LABEL_COMPONENT = "app.kubernetes.io/component"
LABEL_PODCLIQUE = "grove.io/podclique"
LABEL_PODGANG = "grove.io/podgang"
LABEL_BASE_PODGANG = "grove.io/base-podgang"
LABEL_PCS_REPLICA_INDEX = "grove.io/podcliqueset-replica-index"
LABEL_PCSG = "grove.io/podcliquescalinggroup"
LABEL_PCSG_REPLICA_INDEX = "grove.io/podcliquescalinggroup-replica-index"
LABEL_POD_TEMPLATE_HASH = "grove.io/pod-template-hash"
LABEL_POD_INDEX = "grove.io/pod-index"
# tenant queue assignment (quota subsystem, docs/quota.md): set by users on
# the PodCliqueSet, propagated by the operator to PodCliques (and through
# them to Pods) and PodGangs so the scheduler and the usage accountant can
# attribute every gang/pod to its queue without extra lookups
LABEL_QUEUE = "scheduler.grove.io/queue"
# home-cluster affinity (federation tier, docs/federation.md): set by
# users on the PodCliqueSet; the FederationRouter places the workload in
# this region unless it is Lost or its explain verdict blocks admission
LABEL_FEDERATION_HOME = "federation.grove.io/home"

# component values set against LABEL_COMPONENT
COMPONENT_HEADLESS_SERVICE = "pcs-headless-service"
COMPONENT_POD_ROLE = "pod-role"
COMPONENT_POD_ROLE_BINDING = "pod-role-binding"
COMPONENT_POD_SERVICE_ACCOUNT = "pod-service-account"
COMPONENT_SA_TOKEN_SECRET = "pod-sa-token-secret"
COMPONENT_PCSG = "pcs-podcliquescalinggroup"
COMPONENT_HPA = "pcs-hpa"
COMPONENT_PODGANG = "podgang"
COMPONENT_PCS_PODCLIQUE = "pcs-podclique"
COMPONENT_PCSG_PODCLIQUE = "pcsg-podclique"
COMPONENT_POD = "pcs-pod"


def default_labels(pcs_name: str) -> Dict[str, str]:
    """constants.go:90-95 GetDefaultLabelsForPodCliqueSetManagedResources."""
    return {LABEL_MANAGED_BY: LABEL_MANAGED_BY_VALUE, LABEL_PART_OF: pcs_name}


# --- namegen (namegen.go) ---------------------------------------------------


def headless_service_name(pcs_name: str, pcs_replica: int) -> str:
    return f"{pcs_name}-{pcs_replica}"


def headless_service_address(pcs_name: str, pcs_replica: int, namespace: str) -> str:
    return f"{headless_service_name(pcs_name, pcs_replica)}.{namespace}.svc.cluster.local"


def pod_role_name(pcs_name: str) -> str:
    return f"{API_GROUP}:pcs:{pcs_name}"


def pod_role_binding_name(pcs_name: str) -> str:
    return f"{API_GROUP}:pcs:{pcs_name}"


def pod_service_account_name(pcs_name: str) -> str:
    return pcs_name


def initc_sa_token_secret_name(pcs_name: str) -> str:
    return f"{pcs_name}-initc-sa-token-secret"


def podclique_name(owner_name: str, owner_replica: int, clique_template_name: str) -> str:
    """namegen.go:97-100 — owner is the PCS (standalone) or the PCSG (member)."""
    return f"{owner_name}-{owner_replica}-{clique_template_name}"


def pcsg_name(pcs_name: str, pcs_replica: int, sg_template_name: str) -> str:
    return f"{pcs_name}-{pcs_replica}-{sg_template_name}"


def base_podgang_name(pcs_name: str, pcs_replica: int) -> str:
    return f"{pcs_name}-{pcs_replica}"


def scaled_podgang_name(pcsg_fqn: str, scaled_index: int) -> str:
    """namegen.go:86-92 CreatePodGangNameFromPCSGFQN — scaled_index is 0-based
    for PCSG replicas >= minAvailable."""
    return f"{pcsg_fqn}-{scaled_index}"


def podgang_name_for_pcsg_replica(
    pcs_name: str,
    pcs_replica: int,
    pcsg_fqn: str,
    pcsg_replica: int,
    pcsg_min_available: int,
) -> str:
    """namegen.go:100-118: PCSG replicas 0..minAvailable-1 belong to the base
    PodGang of the PCS replica; replicas >= minAvailable each get their own
    scaled PodGang with 0-based index."""
    if pcsg_replica < pcsg_min_available:
        return base_podgang_name(pcs_name, pcs_replica)
    return scaled_podgang_name(pcsg_fqn, pcsg_replica - pcsg_min_available)


def pod_name(pclq_name: str, pod_index: int) -> str:
    """Stable pod hostname `<pclq>-<idx>` (index-allocator backed —
    reference internal/index/tracker.go)."""
    return f"{pclq_name}-{pod_index}"


def hpa_name(target_name: str) -> str:
    return target_name


def extract_sg_name_from_pcsg_fqn(pcsg_fqn: str, pcs_name: str, pcs_replica: int) -> str:
    """namegen.go:120-125."""
    prefix = f"{pcs_name}-{pcs_replica}-"
    return pcsg_fqn[len(prefix):]
