"""Object metadata, conditions, and resource-quantity primitives.

TPU-native re-host of the apimachinery subset the reference relies on
(metav1.ObjectMeta / metav1.Condition / resource.Quantity). Semantics follow
the reference's usage, not the k8s implementation:
- reference types: /root/reference/operator/api/core/v1alpha1/podcliqueset.go
- conditions usage: /root/reference/operator/internal/controller/podclique/reconcilestatus.go

All timestamps are float unix seconds supplied by an injectable clock so the
simulator can run virtual time (the reference gets wall time from the informer
cache; we need determinism for the 10k-gang stress sim).
"""

from __future__ import annotations

import copy
import itertools
import pickle
import re
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# ---------------------------------------------------------------------------
# Resource quantities
# ---------------------------------------------------------------------------

_QTY_RE = re.compile(r"^([+-]?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)([a-zA-Z]*)$")

_SUFFIX = {
    "": 1.0,
    "n": 1e-9,
    "u": 1e-6,
    "m": 1e-3,
    "k": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "Ki": 2.0**10,
    "Mi": 2.0**20,
    "Gi": 2.0**30,
    "Ti": 2.0**40,
    "Pi": 2.0**50,
}


def parse_quantity(value: Any) -> float:
    """Parse a k8s-style resource quantity ('10m', '4Gi', 2, '2') into a float.

    Mirrors the subset of resource.Quantity the reference samples use
    (/root/reference/operator/samples/simple/simple1.yaml requests cpu '10m').
    """
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip()
    m = _QTY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity: {value!r}")
    num, suffix = m.groups()
    if suffix not in _SUFFIX:
        raise ValueError(f"invalid quantity suffix: {value!r}")
    return float(num) * _SUFFIX[suffix]


def parse_resource_map(raw: Optional[Dict[str, Any]]) -> Dict[str, float]:
    return {k: parse_quantity(v) for k, v in (raw or {}).items()}


# ---------------------------------------------------------------------------
# Conditions
# ---------------------------------------------------------------------------


@dataclass
class Condition:
    """metav1.Condition equivalent (type/status/reason/message/lastTransitionTime)."""

    type: str
    status: str  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0

    def is_true(self) -> bool:
        return self.status == "True"


def get_condition(conditions: List[Condition], ctype: str) -> Optional[Condition]:
    for c in conditions:
        if c.type == ctype:
            return c
    return None


def set_condition(conditions: List[Condition], new: Condition, now: float) -> bool:
    """Upsert, bumping last_transition_time only on status change.

    Mirrors apimachinery meta.SetStatusCondition, which the reference uses for
    MinAvailableBreached / PodCliqueScheduled breach-age computation
    (gangterminate.go computes breach duration from lastTransitionTime).
    Returns True if the condition changed.
    """
    existing = get_condition(conditions, new.type)
    if existing is None:
        new.last_transition_time = now
        conditions.append(new)
        return True
    changed = (
        existing.status != new.status
        or existing.reason != new.reason
        or existing.message != new.message
    )
    if existing.status != new.status:
        existing.last_transition_time = now
    existing.status = new.status
    existing.reason = new.reason
    existing.message = new.message
    return changed


# ---------------------------------------------------------------------------
# ObjectMeta
# ---------------------------------------------------------------------------

_uid_counter = itertools.count(1)
# Store-incarnation token: purely sequential uids repeat across apiserver
# restarts, so an operator surviving a restart (HttpStore reconnect) could
# see a RE-created object reuse a (uid, generation) pair and serve a stale
# cached template hash (api/hashing.py keys on exactly that pair — wrong
# pod-template-hash labels, missed rolling updates). The random token makes
# uids unique per store incarnation, like k8s's uuid-based object UIDs.
_UID_TOKEN = uuid.uuid4().hex[:8]


def next_uid() -> str:
    return f"uid-{_UID_TOKEN}-{next(_uid_counter)}"


def reset_uid_namespace() -> None:
    """Restart the uid sequence under a FRESH incarnation token.

    The only sanctioned way to reset `_uid_counter`: resetting the
    counter alone re-creates (uid, generation) pairs, and every
    process-global memo keyed on them (api/hashing.py's template-hash
    cache) would serve another incarnation's stale value — observed as a
    wrong currentGenerationHash in a later harness when the cache was
    warm enough that the colliding entry survived eviction. Rotating the
    token keeps restarted sequences disjoint, exactly like a store
    restart does."""
    global _uid_counter, _UID_TOKEN
    _uid_counter = itertools.count(1)
    _UID_TOKEN = uuid.uuid4().hex[:8]


@dataclass
class OwnerReference:
    kind: str
    name: str
    uid: str = ""
    controller: bool = True


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    generation: int = 0
    resource_version: int = 0
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None
    finalizers: List[str] = field(default_factory=list)
    owner_references: List[OwnerReference] = field(default_factory=list)

    def controller_owner(self) -> Optional[OwnerReference]:
        for ref in self.owner_references:
            if ref.controller:
                return ref
        return None


@dataclass(frozen=True, order=True)
class NamespacedName:
    """scheduler/api/core/v1alpha1/podgang.go:129-137 equivalent."""

    namespace: str
    name: str

    def __str__(self) -> str:  # pragma: no cover - debugging aid
        return f"{self.namespace}/{self.name}"


def clone_status(status):
    """Cheap private clone of a status object for a condition-writing flow:
    shallow copy plus PRIVATE Condition copies. Safe because status flows
    only REPLACE fields by assignment or call set_condition (which mutates
    Condition objects and appends to the conditions list) — a flow that
    mutates any OTHER nested status field in place (e.g. container
    statuses) must use deep_copy instead. An order of magnitude cheaper
    than the pickled deep copy on the per-reconcile status hot path."""
    st = copy.copy(status)
    st.conditions = [copy.copy(c) for c in status.conditions]
    return st


def deep_copy(obj):
    """Deep-copy an API object. pickle round-trip is several times faster
    than copy.deepcopy for plain dataclass trees (the store copies on every
    read/write, so this is the control plane's hottest function)."""
    try:
        return pickle.loads(pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))
    except Exception:
        return copy.deepcopy(obj)
