"""kubectl-tree-style resource rendering, store-agnostic.

Works over any store exposing ``list(kind, namespace, label_selector)`` —
the in-memory sim store and the live-apiserver HTTP client alike — so the
same tree the quickstart shows (pcs > pclq/pcsg > pg > pod; reference
README.md:26) renders for both tiers.
"""

from __future__ import annotations

import io

from grove_tpu.api import names as namegen


def render_tree(store, namespace: str = "default") -> str:
    out = io.StringIO()
    for pcs in store.list("PodCliqueSet", namespace):
        out.write(f"pcs/{pcs.metadata.name}\n")
        sel = namegen.default_labels(pcs.metadata.name)
        for pcsg in store.list("PodCliqueScalingGroup", namespace, sel):
            st = pcsg.status
            out.write(
                f"  pcsg/{pcsg.metadata.name} replicas={pcsg.spec.replicas}"
                f" scheduled={st.scheduled_replicas}"
                f" available={st.available_replicas}\n"
            )
        for pclq in store.list("PodClique", namespace, sel):
            st = pclq.status
            out.write(
                f"  pclq/{pclq.metadata.name} replicas={st.replicas}"
                f" ready={st.ready_replicas} scheduled={st.scheduled_replicas}\n"
            )
        for pg in store.list("PodGang", namespace, sel):
            groups = ", ".join(
                f"{g.name}(min={g.min_replicas},pods={len(g.pod_references)})"
                for g in pg.spec.pod_groups
            )
            out.write(f"  pg/{pg.metadata.name} [{groups}]\n")
        for pod in store.list("Pod", namespace, sel):
            gates = "gated" if pod.spec.scheduling_gates else "ungated"
            node = pod.status.node_name or "-"
            out.write(
                f"    pod/{pod.metadata.name} {pod.status.phase} {gates}"
                f" node={node}\n"
            )
    return out.getvalue()
