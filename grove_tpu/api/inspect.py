"""kubectl-tree-style resource rendering, store-agnostic.

Works over any store exposing ``list(kind, namespace, label_selector)`` —
the in-memory sim store and the live-apiserver HTTP client alike — so the
same tree the quickstart shows (pcs > pclq/pcsg > pg > pod; reference
README.md:26) renders for both tiers.
"""

from __future__ import annotations

import io

from grove_tpu.api import names as namegen


def render_describe(store, kind: str, namespace: str, name: str) -> str:
    """kubectl-describe-style single-object view: metadata, spec highlights,
    status counters + conditions + typed lastErrors, and the Events whose
    message names the object (events are materialized as store objects —
    controller/common.py record_event)."""
    obj = store.get(kind, namespace, name)
    if obj is None:
        return ""
    out = io.StringIO()
    out.write(f"Name:       {obj.metadata.name}\n")
    out.write(f"Namespace:  {obj.metadata.namespace}\n")
    out.write(f"Kind:       {obj.kind}\n")
    if obj.metadata.labels:
        labels = ", ".join(
            f"{k}={v}" for k, v in sorted(obj.metadata.labels.items())
        )
        out.write(f"Labels:     {labels}\n")
    out.write(f"Generation: {obj.metadata.generation}\n")
    spec = getattr(obj, "spec", None)
    if spec is not None and hasattr(spec, "replicas"):
        out.write(f"Replicas:   {spec.replicas}\n")
    status = getattr(obj, "status", None)
    if status is not None:
        for field in (
            "phase",
            "replicas",
            "ready_replicas",
            "scheduled_replicas",
            "available_replicas",
            "updated_replicas",
            "placement_score",
        ):
            val = getattr(status, field, None)
            if val is not None:
                label = field.replace("_", " ").title().replace(" ", "")
                out.write(f"Status.{label}: {val}\n")
        conds = getattr(status, "conditions", None) or []
        if conds:
            out.write("Conditions:\n")
            for c in conds:
                out.write(
                    f"  {c.type}={c.status}"
                    f" reason={getattr(c, 'reason', '') or '-'}"
                    f" message={getattr(c, 'message', '') or '-'}\n"
                )
        last_errors = getattr(status, "last_errors", None) or []
        if last_errors:
            out.write("LastErrors:\n")
            for err in last_errors:
                out.write(
                    f"  {getattr(err, 'code', '?')}"
                    f" op={getattr(err, 'operation', '-')}"
                    f" {getattr(err, 'description', '')}\n"
                )
    # events live in the default namespace regardless of the object's (the
    # ring buffer is cluster-scoped); match the message on a word boundary so
    # `simple1` never inherits `simple10`'s events (children like
    # `simple1-0-...` still match their own names when described directly)
    import re

    word = re.compile(rf"\b{re.escape(name)}\b")
    events = [
        e
        for e in store.list("Event", None)
        if word.search(str(e.spec.get("message", "")))
    ]
    # store listing is lexicographic by name (evt-10 < evt-2): order
    # chronologically before truncating to the newest 20 (the numeric name
    # suffix breaks ties within one virtual-clock instant)
    def _event_order(e):
        suffix = e.metadata.name.rsplit("-", 1)[-1]
        return (
            e.spec.get("timestamp", 0),
            int(suffix) if suffix.isdigit() else 0,
        )

    events.sort(key=_event_order)
    if events:
        out.write("Events:\n")
        for e in events[-20:]:
            out.write(
                f"  t={e.spec.get('timestamp', 0):.0f}s"
                f" {e.spec.get('involvedKind', '?')}"
                f" {e.spec.get('reason', '?')}: {e.spec.get('message', '')}\n"
            )
    return out.getvalue()


def render_tree(store, namespace: str = "default") -> str:
    out = io.StringIO()
    for pcs in store.list("PodCliqueSet", namespace):
        out.write(f"pcs/{pcs.metadata.name}\n")
        sel = namegen.default_labels(pcs.metadata.name)
        for pcsg in store.list("PodCliqueScalingGroup", namespace, sel):
            st = pcsg.status
            out.write(
                f"  pcsg/{pcsg.metadata.name} replicas={pcsg.spec.replicas}"
                f" scheduled={st.scheduled_replicas}"
                f" available={st.available_replicas}\n"
            )
        for pclq in store.list("PodClique", namespace, sel):
            st = pclq.status
            out.write(
                f"  pclq/{pclq.metadata.name} replicas={st.replicas}"
                f" ready={st.ready_replicas} scheduled={st.scheduled_replicas}\n"
            )
        for pg in store.list("PodGang", namespace, sel):
            groups = ", ".join(
                f"{g.name}(min={g.min_replicas},pods={len(g.pod_references)})"
                for g in pg.spec.pod_groups
            )
            out.write(f"  pg/{pg.metadata.name} [{groups}]\n")
        for pod in store.list("Pod", namespace, sel):
            gates = "gated" if pod.spec.scheduling_gates else "ungated"
            node = pod.status.node_name or "-"
            out.write(
                f"    pod/{pod.metadata.name} {pod.status.phase} {gates}"
                f" node={node}\n"
            )
    return out.getvalue()
