"""Horizontal autoscaler: evaluates HPA objects against observed utilization.

Plays the role of the kube HPA controller for the two Grove scale targets
(reference components/hpa/hpa.go creates `autoscaling/v2` HPAs against the
CRs' scale subresources; the kube controller then drives .spec.replicas):
- PodClique (standalone autoscaled cliques)
- PodCliqueScalingGroup (group-scaled cliques — scaling it out materializes
  scaled PodGangs, the hierarchical-gang path)

Semantics follow the HPA v2 utilization algorithm:
    desired = ceil(current * observed / target)
clamped to [minReplicas, maxReplicas], with a stabilization window on
scale-down. Metrics come from a pluggable provider; the sim provider reports
per-target utilization injected by tests / scenarios.
"""

from __future__ import annotations

import math
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, Optional, Protocol, Tuple

from grove_tpu.observability.metrics import METRICS
from grove_tpu.runtime.store import Store

DEFAULT_SCALE_DOWN_STABILIZATION = 300.0  # seconds (kube default)

# one scale decision, as logged: (vt, kind, namespace, name, from, to)
ScaleEvent = Tuple[float, str, str, str, int, int]


class MetricsProvider(Protocol):
    def utilization(self, kind: str, namespace: str, name: str) -> Optional[float]:
        """Average utilization (%) across the target's pods, None if unknown."""
        ...


@dataclass
class StaticMetricsProvider:
    """Sim/test provider: utilization set explicitly per target."""

    values: Dict[str, float] = field(default_factory=dict)

    def set(self, kind: str, namespace: str, name: str, value: float) -> None:
        self.values[f"{kind}/{namespace}/{name}"] = value

    def utilization(self, kind: str, namespace: str, name: str) -> Optional[float]:
        return self.values.get(f"{kind}/{namespace}/{name}")


class HorizontalAutoscaler:
    def __init__(
        self,
        store: Store,
        provider: MetricsProvider,
        scale_down_stabilization: float = DEFAULT_SCALE_DOWN_STABILIZATION,
    ) -> None:
        self.store = store
        self.provider = provider
        self.scale_down_stabilization = scale_down_stabilization
        # target key -> (proposed lower replicas, since)
        self._scale_down_candidates: Dict[str, tuple] = {}
        # bounded decision log, stamped with the DECISION's virtual time —
        # scale-up latency (decision → replicas Ready) is only measurable
        # if the decision instant survives the converge that absorbs it
        # (sim/traffic.py and the serving SLO objectives consume this)
        self.scale_log: Deque[ScaleEvent] = deque(maxlen=4096)

    def tick(self, namespace: Optional[str] = None) -> int:
        """Evaluate every HPA once (all namespaces by default); returns the
        number of scale changes."""
        changes = 0
        # readonly scan: evaluation only reads the HPA spec; the target is
        # re-fetched mutably inside _apply_scale when a scale actually fires
        for hpa in self.store.scan("HorizontalPodAutoscaler", namespace):
            if self._evaluate(hpa.metadata.namespace, hpa):
                changes += 1
        return changes

    def next_deadline(self) -> Optional[float]:
        """Earliest pending scale-down stabilization deadline (None if no
        scale-down is held) — lets a virtual-time driver jump to it."""
        if not self._scale_down_candidates:
            return None
        return min(
            since + self.scale_down_stabilization
            for _, since in self._scale_down_candidates.values()
        )

    def scale_target(
        self, kind: str, namespace: str, name: str, replicas: int
    ) -> bool:
        """Direct scale request from a policy controller (the remediator's
        preemptive scale-up ahead of a forecast peak): same mechanics as
        an HPA decision — re-get, write ``spec.replicas``, log, count — so
        the decision log and the hpa_* metrics see one unified stream.
        Returns False when the target is absent, terminating, or already
        at the requested size."""
        view = self.store.get(kind, namespace, name, readonly=True)
        if view is None or view.metadata.deletion_timestamp is not None:
            return False
        if int(view.spec.replicas) == int(replicas):
            return False
        key = f"{kind}/{namespace}/{name}"
        self._scale_down_candidates.pop(key, None)
        return self._apply_scale(view, int(replicas), key)

    # -- core ------------------------------------------------------------

    def _evaluate(self, namespace: str, hpa) -> bool:
        spec = hpa.spec
        kind = spec.get("targetKind")
        name = spec.get("targetName")
        target_util = self._target_utilization(spec)
        if kind is None or name is None or target_util is None:
            return False
        observed = self.provider.utilization(kind, namespace, name)
        if observed is None:
            return False
        obj = self.store.get(kind, namespace, name, readonly=True)
        if obj is None or obj.metadata.deletion_timestamp is not None:
            return False
        current = obj.spec.replicas
        desired = math.ceil(current * observed / max(target_util, 1e-9))
        lo = int(spec.get("minReplicas") or 1)
        hi = int(spec.get("maxReplicas") or current)
        desired = max(lo, min(hi, desired))
        key = f"{kind}/{namespace}/{name}"

        if desired == current:
            self._scale_down_candidates.pop(key, None)
            return False
        if desired > current:
            self._scale_down_candidates.pop(key, None)
            return self._apply_scale(obj, desired, key)

        # scale-down: hold for the stabilization window, track the HIGHEST
        # proposed value within the window (kube semantics)
        now = self.store.clock.now()
        proposed, since = self._scale_down_candidates.get(key, (desired, now))
        proposed = max(proposed, desired)
        self._scale_down_candidates[key] = (proposed, since)
        if now - since < self.scale_down_stabilization:
            return False
        self._scale_down_candidates.pop(key, None)
        return self._apply_scale(obj, proposed, key)

    @staticmethod
    def _target_utilization(spec) -> Optional[float]:
        for metric in spec.get("metrics") or []:
            resource = metric.get("resource") or {}
            target = resource.get("target") or {}
            if target.get("averageUtilization") is not None:
                return float(target["averageUtilization"])
        return None

    def _apply_scale(self, view, desired: int, key: str) -> bool:
        # `view` is a readonly store view — re-get a private copy to write
        obj = self.store.get(
            view.kind, view.metadata.namespace, view.metadata.name
        )
        if obj is None or obj.metadata.deletion_timestamp is not None:
            return False
        previous = int(obj.spec.replicas)
        obj.spec.replicas = desired
        self.store.update(obj)  # generation bump → controllers reconcile
        self.scale_log.append(
            (
                self.store.clock.now(),
                obj.kind,
                obj.metadata.namespace,
                obj.metadata.name,
                previous,
                desired,
            )
        )
        METRICS.inc(f"hpa_scale_total/{key}")
        METRICS.set(f"hpa_replicas/{key}", desired)
        return True


