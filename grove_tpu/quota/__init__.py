"""Multi-tenant quota & fair-share queueing (docs/quota.md).

The queue/quota semantics the reference delegates to the external KAI
scheduler — hierarchical capacity queues, deserved-share fair ordering, and
cross-queue reclaim — implemented in front of the gang solver:

- ``api/types.py::Queue``: a cluster-scoped tenant queue in a two-level
  tree (root → tenant queues) with per-resource ``deserved``/``ceiling``.
- ``accountant``: incremental per-queue usage vectors folded from pod
  watch deltas (the ``runtime/aggregate.py`` pattern).
- ``ordering``: the vectorized fair-share ordering pass — dense
  queues × resources tensors through a ``lax.scan`` producing the gang
  solve order (DRF-style dominant-share argmin per step).
- ``oracle``: the pure-Python reference implementation the vectorized pass
  is equivalence-tested against (mirrors ``solver/oracle.py``'s role).
- ``manager``: ties it together for the scheduler — queue tree lookup,
  ceiling holds, ordering, status/gauges, and the reclaim predicate.
"""

from grove_tpu.quota.accountant import QuotaAccountant
from grove_tpu.quota.manager import QuotaManager, quota_snapshot

__all__ = ["QuotaAccountant", "QuotaManager", "quota_snapshot"]
