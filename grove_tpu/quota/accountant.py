"""Incremental per-queue usage accounting from pod watch deltas.

The quota analogue of ``runtime/aggregate.py``: the fair-share ordering
pass needs per-queue usage vectors every scheduling round, and a full pod
rescan per round is O(pods) at stress scale. This accountant folds each
committed pod mutation into per-queue resource totals at event time, so a
round reads its usage in O(queues).

A pod contributes its ``spec.total_requests()`` to its queue (the
``scheduler.grove.io/queue`` label the operator propagates from the
PodCliqueSet; unlabeled pods land in the default queue) while it is BOUND
and not terminating — exactly the capacity the cluster's node accounting
charges, so queue shares and node free-capacity always agree about who is
using what.

Exactness contract: equal to a full rescan of the same store view
(``quota/oracle.py::usage_oracle``) up to float-accumulation order;
``tests/test_quota.py`` replays randomized event storms against both.
Rows are garbage-collected by live-pod count, so a drained queue drops its
row (and any accumulated float residue) entirely.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from grove_tpu.api import names as namegen
from grove_tpu.api.pod import is_scheduled, is_terminating
from grove_tpu.api.types import DEFAULT_QUEUE


def pod_quota_features(
    pod, default_queue: str = DEFAULT_QUEUE
) -> Optional[Tuple[str, Dict[str, float]]]:
    """(queue, requests) the pod charges against its queue, or None while
    it holds no capacity (unbound, terminating, or deleted)."""
    if pod.metadata.deletion_timestamp is not None:
        return None
    if not is_scheduled(pod) or is_terminating(pod):
        return None
    queue = pod.metadata.labels.get(namegen.LABEL_QUEUE) or default_queue
    return queue, pod.spec.total_requests()


class QuotaAccountant:
    """Per-queue usage rows folded from watch deltas. One instance mirrors
    one store view (the committed view — the scheduler binds/evicts against
    committed state, so its quota decisions must read the same view)."""

    __slots__ = ("_usage", "_pods", "default_queue", "_built")

    def __init__(self, default_queue: str = DEFAULT_QUEUE) -> None:
        self._usage: Dict[str, Dict[str, float]] = {}
        self._pods: Dict[str, int] = {}  # live bound pods per queue (row GC)
        self.default_queue = default_queue
        # lazy first build: an accountant attached to a store that already
        # holds bound pods (operator failover) rebuilds on first read
        self._built = False

    # -- reads -----------------------------------------------------------

    def usage(self, queue: str) -> Dict[str, float]:
        """READ-ONLY view of one queue's usage vector."""
        return self._usage.get(queue, {})

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Copy of every queue's usage vector (status/endpoint exports)."""
        return {q: dict(v) for q, v in self._usage.items()}

    def pod_count(self, queue: str) -> int:
        return self._pods.get(queue, 0)

    # -- maintenance -----------------------------------------------------

    def _fold(self, pod, sign: int) -> None:
        feats = pod_quota_features(pod, self.default_queue)
        if feats is None:
            return
        queue, requests = feats
        row = self._usage.get(queue)
        if row is None:
            row = self._usage[queue] = {}
        for r, v in requests.items():
            row[r] = row.get(r, 0.0) + sign * v
        n = self._pods.get(queue, 0) + sign
        if n > 0:
            self._pods[queue] = n
        else:
            # count-based row GC: a drained queue drops its row AND any
            # float residue the +/- accumulation left behind
            self._pods.pop(queue, None)
            self._usage.pop(queue, None)

    def apply(self, type_: str, obj, old=None) -> None:
        """Fold one committed-view mutation (Store watch callback shape)."""
        if getattr(obj, "kind", None) != "Pod" or not self._built:
            return
        if type_ == "Deleted":
            self._fold(old if old is not None else obj, -1)
            return
        if old is not None:
            self._fold(old, -1)
        self._fold(obj, +1)

    def on_event(self, ev) -> None:
        """Store.subscribe_system adapter."""
        self.apply(ev.type, ev.obj, ev.old)

    def rebuild(self, pods) -> None:
        """Recompute from scratch (initial attach / full resync)."""
        self._usage.clear()
        self._pods.clear()
        self._built = True
        for pod in pods:
            self._fold(pod, +1)

    def ensure_built(self, store) -> None:
        if not self._built:
            self.rebuild(store.scan("Pod"))
