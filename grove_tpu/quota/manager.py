"""QuotaManager: the scheduler-facing face of the quota subsystem.

Owns the incremental usage accountant, resolves the queue tree, enforces
ceilings, and runs the vectorized fair-share ordering pass that replaces
the gang scheduler's flat ``(-priority, name)`` sort. Also exports the
authoritative full-scan snapshot behind ``GET /queues`` / ``cli queues``.

Single-queue guarantee (pinned by tests/test_quota.py): with no Queue CRs
the ordering path is byte-identical to the flat global priority sort, and
with every gang in ONE queue the fair-share pass degenerates to the same
order (one queue's internal order IS the flat order) — quota only changes
behavior when there are actual tenants to arbitrate between.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from grove_tpu.api.meta import get_condition
from grove_tpu.api.types import COND_PODGANG_SCHEDULED, DEFAULT_QUEUE
from grove_tpu.quota.accountant import QuotaAccountant
from grove_tpu.quota.oracle import (
    dominant_share,
    dominant_share_of,
    usage_oracle,
)
from grove_tpu.quota.ordering import fair_order

_EPS = 1e-9


def _flat_key(spec: dict):
    """The pre-quota global order (scheduler kernel admits in input order;
    ties broken by name for determinism) — the guard-rail contract."""
    return (-spec["priority"], spec["name"])


def spec_demand(spec: dict) -> Dict[str, float]:
    """Aggregate resource demand a gang charges its queue when admitted:
    per-group per-pod demand x full pod count (what binding will consume)."""
    out: Dict[str, float] = {}
    for group in spec["groups"]:
        for r, v in group["demand"].items():
            out[r] = out.get(r, 0.0) + v * group["count"]
    return out


def _pow2(n: int, floor: int = 1) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


class QuotaManager:
    def __init__(self, store, default_queue: str = DEFAULT_QUEUE) -> None:
        self.store = store
        self.default_queue = default_queue
        self.accountant = QuotaAccountant(default_queue)
        # in-memory Store: fold usage incrementally from commit-time events;
        # HttpStore (cluster mode): no synchronous events — rebuild per round
        sub = getattr(store, "subscribe_system", None)
        self._incremental = sub is not None
        if self._incremental:
            # sharded stores (docs/control-plane.md): ride the per-shard
            # fan-out — a pod's events never straddle shards (its
            # namespace pins its shard), so the per-queue fold stays exact
            per_shard = getattr(store, "subscribe_system_per_shard", None)
            if per_shard is not None and getattr(store, "num_shards", 1) > 1:
                per_shard(self.accountant.on_event)
            else:
                sub(self.accountant.on_event)
        # last ordering pass's per-queue rows (status writes / gauges)
        self.last_rows: List[dict] = []
        # sticky tensor padding (StickyGroupPad ethos): queue churn and
        # draining buckets must not force per-shape recompiles of the
        # ordering scan — pads grow to the widest shape seen, never shrink
        self._pad_q = 1
        self._pad_g = 1
        self._pad_r = 1

    # -- queue tree reads -------------------------------------------------

    def queue_crs(self) -> Dict[str, object]:
        """name -> readonly Queue CR view."""
        return {q.metadata.name: q for q in self.store.scan("Queue")}

    def active(self) -> bool:
        for _ in self.store.scan("Queue"):
            return True
        return False

    def _usage_snapshot(self) -> Dict[str, Dict[str, float]]:
        if self._incremental:
            self.accountant.ensure_built(self.store)
        else:
            self.accountant.rebuild(self.store.scan("Pod"))
        return self.accountant.snapshot()

    def queue_shares(
        self, queue_crs: Optional[Dict[str, object]] = None
    ) -> Dict[str, float]:
        """Current dominant share per queue (usage-holding and CR-defined
        queues both present; zero-deserved queues use the BIG-multiplier
        convention of quota/ordering.py)."""
        crs = queue_crs if queue_crs is not None else self.queue_crs()
        usage = self._usage_snapshot()
        return {
            name: dominant_share_of(
                usage.get(name, {}),
                crs[name].spec.deserved if name in crs else {},
            )
            for name in sorted(set(crs) | set(usage))
        }

    # -- the ordering pass ------------------------------------------------

    def warm(self, n_queues: int, n_gangs: int, n_resources: int = 1) -> None:
        """Pre-compile the ordering scan for the padded shape this workload
        will hit, so compile time lands outside measured rounds (benches /
        smokes call this before converging)."""
        self._pad_q = max(self._pad_q, _pow2(max(n_queues, 1)))
        self._pad_g = max(self._pad_g, _pow2(max(n_gangs, 1)))
        self._pad_r = max(self._pad_r, _pow2(max(n_resources, 1)))
        fair_order(
            np.zeros((self._pad_q, self._pad_r), np.float32),
            np.zeros((self._pad_q, self._pad_r), np.float32),
            np.zeros((self._pad_q, self._pad_g, self._pad_r), np.float32),
            np.ones((self._pad_q,), np.int32),
        )

    def order_specs(
        self,
        gang_specs: List[dict],
        crs: Optional[Dict[str, object]] = None,
        usage: Optional[Dict[str, Dict[str, float]]] = None,
        record_rows: bool = True,
    ) -> Tuple[List[dict], List[Tuple[dict, str]]]:
        """Produce the gang solve order. Returns (ordered_specs, held) where
        held is [(spec, reason)] — gangs excluded from this round's solve
        because their queue is at its ceiling (QueuePending).

        ``crs``/``usage`` override the live queue tree and usage snapshot
        (the admission explain engine's what-if trials order against a
        hypothetical tree through this ONE implementation, so the
        hypothetical and real orders can never diverge); None reads live.

        With no Queue CRs this is EXACTLY the flat global priority sort
        (guard rail: byte-identical order, zero quota overhead beyond one
        empty scan)."""
        if crs is None:
            crs = self.queue_crs()
        if not crs:
            if record_rows:
                self.last_rows = []
            return sorted(gang_specs, key=_flat_key), []

        if usage is None:
            usage = self._usage_snapshot()
        # bucket pending gangs per queue, queue-local flat order inside
        buckets: Dict[str, List[dict]] = {}
        for spec in gang_specs:
            buckets.setdefault(spec["queue"], []).append(spec)
        for bucket in buckets.values():
            bucket.sort(key=_flat_key)

        # ceiling holds (best-effort FIFO: a gang that would cross the cap
        # is held; smaller gangs behind it may still pass)
        held: List[Tuple[dict, str]] = []
        for name, bucket in buckets.items():
            cr = crs.get(name)
            ceiling = cr.spec.ceiling if cr is not None else {}
            if not ceiling:
                continue
            cum = dict(usage.get(name, {}))
            kept = []
            for spec in bucket:
                demand = spec_demand(spec)
                over = [
                    r
                    for r, cap in ceiling.items()
                    if cum.get(r, 0.0) + demand.get(r, 0.0) > cap + _EPS
                ]
                if over:
                    held.append(
                        (
                            spec,
                            f"queue {name} at ceiling for "
                            f"{'/'.join(sorted(over))}",
                        )
                    )
                    continue
                kept.append(spec)
                for r, v in demand.items():
                    cum[r] = cum.get(r, 0.0) + v
            buckets[name] = kept

        # dense tensors: queues sorted by name (argmin tie-break = name),
        # resources sorted by name; shapes padded to powers of two so the
        # ordering kernel's compile cache stays monotone-few
        names = sorted(set(crs) | set(buckets))
        # resource set = deserved ∪ pending demand ∪ HELD USAGE: a queue
        # holding capacity in a resource nobody deserves or demands right
        # now must still pay the zero-deserved usage*BIG penalty for it, or
        # it would order as if lightly loaded (and the status share would
        # disagree with GET /queues' union rule)
        resources = sorted(
            {r for cr in crs.values() for r in cr.spec.deserved}
            | {
                r
                for bucket in buckets.values()
                for spec in bucket
                for r in spec_demand(spec)
            }
            | {r for name in names for r in usage.get(name, {})}
        ) or ["cpu"]
        self._pad_q = q_dim = max(self._pad_q, _pow2(len(names)))
        self._pad_r = r_dim = max(self._pad_r, _pow2(len(resources)))
        self._pad_g = g_dim = max(
            self._pad_g,
            _pow2(max((len(b) for b in buckets.values()), default=0)),
        )
        deserved = np.zeros((q_dim, r_dim), np.float32)
        usage_t = np.zeros((q_dim, r_dim), np.float32)
        demand_t = np.zeros((q_dim, g_dim, r_dim), np.float32)
        counts = np.zeros((q_dim,), np.int32)
        r_index = {r: i for i, r in enumerate(resources)}
        demands_by_q: Dict[str, List[Dict[str, float]]] = {}
        for qi, name in enumerate(names):
            cr = crs.get(name)
            if cr is not None:
                for r, v in cr.spec.deserved.items():
                    deserved[qi, r_index[r]] = v
            for r, v in usage.get(name, {}).items():
                usage_t[qi, r_index[r]] = v
            bucket = buckets.get(name, [])
            counts[qi] = len(bucket)
            demands_by_q[name] = []
            for gi, spec in enumerate(bucket):
                demand = spec_demand(spec)
                demands_by_q[name].append(demand)
                for r, v in demand.items():
                    demand_t[qi, gi, r_index[r]] = v

        order = fair_order(deserved, usage_t, demand_t, counts)
        ordered = [buckets[names[qi]][slot] for qi, slot in order]

        # per-queue rows for status writes / gauges (pre-round shares);
        # `pending` counts ceiling-held gangs too — they are still waiting,
        # and the CR status / gauge must agree with GET /queues
        held_by_queue: Dict[str, int] = {}
        for spec, _reason in held:
            held_by_queue[spec["queue"]] = (
                held_by_queue.get(spec["queue"], 0) + 1
            )
        shares = dominant_share(
            usage_t[: len(names)], deserved[: len(names)]
        )
        rows = [
            {
                "name": name,
                "cr": crs.get(name),
                "dominant_share": float(shares[qi]),
                "usage": dict(usage.get(name, {})),
                "pending": int(counts[qi]) + held_by_queue.get(name, 0),
            }
            for qi, name in enumerate(names)
        ]
        if record_rows:
            # read-only replay callers (the explain engine, which may run
            # concurrently with a real round in threaded cluster mode)
            # must not clobber the rows the round's status writer reads
            self.last_rows = rows
        return ordered, held


def quota_snapshot(store, default_queue: str = DEFAULT_QUEUE) -> List[dict]:
    """Authoritative full-scan per-queue summary (apiserver ``GET /queues``
    and ``cli queues``): deserved/ceiling from the CRs, usage from the pod
    population, gang counts from PodGang conditions. Includes implicit
    queues (usage or gangs without a Queue CR)."""
    from grove_tpu.api import names as namegen

    crs = {q.metadata.name: q for q in store.scan("Queue")}
    usage = usage_oracle(store.scan("Pod"), default_queue)
    admitted: Dict[str, int] = {}
    pending: Dict[str, int] = {}
    for gang in store.scan("PodGang"):
        queue = gang.metadata.labels.get(namegen.LABEL_QUEUE) or default_queue
        cond = get_condition(gang.status.conditions, COND_PODGANG_SCHEDULED)
        if cond is not None and cond.is_true():
            admitted[queue] = admitted.get(queue, 0) + 1
        else:
            pending[queue] = pending.get(queue, 0) + 1
    out = []
    for name in sorted(set(crs) | set(usage) | set(admitted) | set(pending)):
        cr = crs.get(name)
        deserved = dict(cr.spec.deserved) if cr is not None else {}
        share = dominant_share_of(usage.get(name, {}), deserved)
        out.append(
            {
                "name": name,
                "parent": cr.spec.parent if cr is not None else "",
                "defined": cr is not None,
                "deserved": deserved,
                "ceiling": dict(cr.spec.ceiling) if cr is not None else {},
                "usage": dict(usage.get(name, {})),
                "dominantShare": share,
                "admittedGangs": admitted.get(name, 0),
                "pendingGangs": pending.get(name, 0),
            }
        )
    return out
