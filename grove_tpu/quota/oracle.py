"""Reference oracle: plain-NumPy fair-share ordering + usage accounting.

The quota counterpart of ``solver/oracle.py``: written for clarity, looped
exactly as the vectorized pass's math (``quota/ordering.py``), restricted to
the same IEEE float32 elementwise ops so the two are BIT-IDENTICAL —
``tests/test_quota.py`` replays ~200 randomized queue trees / usage states
(ties, zero-deserved queues, drained queues) against both.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from grove_tpu.quota.ordering import BIG


def dominant_share(usage: np.ndarray, deserved: np.ndarray) -> np.ndarray:
    """[Q] dominant shares from [Q, R] float32 tensors — the shared share
    formula (usage/deserved where entitled, usage*BIG where zero-deserved)."""
    usage = np.asarray(usage, np.float32)
    deserved = np.asarray(deserved, np.float32)
    safe = np.where(deserved > 0, deserved, np.float32(1.0))
    share = np.where(deserved > 0, usage / safe, usage * BIG)
    if share.ndim == 2 and share.shape[1]:
        return share.max(axis=1)
    return np.zeros((share.shape[0],), np.float32)


def dominant_share_of(
    usage: Dict[str, float], deserved: Dict[str, float]
) -> float:
    """One queue's dominant share from resource dicts — the SINGLE home for
    the dict→tensor conversion (ordering rows, CR status, /queues endpoint,
    and the reclaim budget checks must never diverge on the resource-set
    rule: the union of usage and deserved keys)."""
    resources = sorted(set(usage) | set(deserved))
    if not resources:
        return 0.0
    u = np.array([[usage.get(r, 0.0) for r in resources]], np.float32)
    d = np.array([[deserved.get(r, 0.0) for r in resources]], np.float32)
    return float(dominant_share(u, d)[0])


def fair_order_oracle(
    deserved: np.ndarray,  # [Q, R]
    usage: np.ndarray,  # [Q, R]
    demand: np.ndarray,  # [Q, G, R]
    counts: np.ndarray,  # [Q]
) -> np.ndarray:
    """Sequential-greedy ordering, one queue pick per step. Returns the
    same [T, 2] int32 (queue, slot) rows as ``ordering.fair_order``."""
    deserved = np.asarray(deserved, np.float32)
    u = np.array(usage, np.float32, copy=True)
    demand = np.asarray(demand, np.float32)
    counts = np.asarray(counts, np.int64)
    q_dim = deserved.shape[0]
    taken = np.zeros((q_dim,), np.int64)
    out: List[Tuple[int, int]] = []
    total = int(counts.sum())
    for _ in range(total):
        dom = dominant_share(u, deserved)
        active = taken < counts
        if not active.any():
            break
        key = np.where(active, dom, np.float32(np.inf))
        q = int(np.argmin(key))
        slot = int(taken[q])
        out.append((q, slot))
        if demand.ndim == 3 and demand.shape[2]:
            u[q] = u[q] + demand[q, slot]  # charge ONLY the picked queue
        taken[q] += 1
    return np.array(out, dtype=np.int32).reshape(-1, 2)


def usage_oracle(pods, default_queue: str) -> Dict[str, Dict[str, float]]:
    """Full-rescan per-queue usage — what the incremental accountant must
    always equal (modulo float-accumulation order): every bound,
    non-terminating pod contributes its resource requests to its queue."""
    from grove_tpu.api import names as namegen
    from grove_tpu.api.pod import is_scheduled, is_terminating

    out: Dict[str, Dict[str, float]] = {}
    for pod in pods:
        if not is_scheduled(pod) or is_terminating(pod):
            continue
        queue = pod.metadata.labels.get(namegen.LABEL_QUEUE) or default_queue
        acc = out.setdefault(queue, {})
        for r, v in pod.spec.total_requests().items():
            acc[r] = acc.get(r, 0.0) + v
    return out
