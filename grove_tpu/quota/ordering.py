"""Vectorized fair-share ordering: dense queue tensors through a lax.scan.

DRF-style dominant-share ordering (PAPERS.md: datacenter fair sharing) over
the two-level queue tree, shaped to compose with the vmap-batched packing
kernel: all queue state lives in dense ``[Q, R]`` float32 tensors, each scan
step picks the queue with the lowest dominant share and emits its next
pending gang, charging that gang's demand before the next step.

The step function is deliberately restricted to elementwise IEEE float32
ops (where / divide / max / add) plus first-occurrence ``argmin`` so the
pure-NumPy oracle (``quota/oracle.py``) reproduces it BIT-IDENTICALLY —
``tests/test_quota.py`` pins the two against each other across randomized
queue trees, including share ties and zero-deserved queues.

Semantics of one step, given usage U[Q,R], deserved D[Q,R], per-queue gang
demand demand[Q,G,R] (queue-local priority order) and counts[Q]:

    share[q,r] = U[q,r]/D[q,r]  where D>0, else U[q,r]*BIG  (zero-deserved
                 queues order behind every queue with entitlement the
                 moment they hold any usage; at zero usage they tie at 0)
    dom[q]     = max_r share[q,r]
    pick       = argmin over active queues of dom (ties -> lowest queue
                 index; queues are pre-sorted by name, so ties break by
                 queue name deterministically)
    emit (pick, taken[pick]); U += demand[pick, taken[pick]]

Steps after every queue drains emit (-1, -1); callers trim by counts.sum().
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

# float32-safe "worse than any entitled share" multiplier for zero-deserved
# queues; overflow to inf is fine and identical in numpy and XLA
BIG = np.float32(1e18)


@lru_cache(maxsize=32)
def _compiled(q_dim: int, g_dim: int, r_dim: int):
    """jitted scan for one (Q, G, R) shape; the manager pads shapes so the
    compile cache stays monotone-few (StickyGroupPad ethos)."""
    import jax
    import jax.numpy as jnp

    t_dim = q_dim * g_dim

    @jax.jit
    def run(deserved, usage, demand, counts):
        def step(carry, _):
            u, taken = carry
            safe = jnp.where(deserved > 0, deserved, jnp.float32(1.0))
            share = jnp.where(deserved > 0, u / safe, u * jnp.float32(BIG))
            dom = share.max(axis=1)
            active = taken < counts
            key = jnp.where(active, dom, jnp.inf)
            q = jnp.argmin(key)
            ok = active.any()
            slot = taken[q]
            out = jnp.where(
                ok,
                jnp.stack([q.astype(jnp.int32), slot]),
                jnp.full((2,), -1, jnp.int32),
            )
            # charge the emitted gang's demand to ITS queue's row only
            u = u.at[q].add(jnp.where(ok, demand[q, slot], jnp.float32(0.0)))
            taken = taken.at[q].add(jnp.where(ok, 1, 0))
            return (u, taken), out

        (_, _), order = jax.lax.scan(
            step,
            (usage, jnp.zeros((q_dim,), jnp.int32)),
            None,
            length=t_dim,
        )
        return order

    return run


def fair_order(
    deserved: np.ndarray,  # [Q, R] float32
    usage: np.ndarray,  # [Q, R] float32
    demand: np.ndarray,  # [Q, G, R] float32, queue-local priority order
    counts: np.ndarray,  # [Q] int32 pending gangs per queue
) -> np.ndarray:
    """Vectorized ordering pass. Returns [T, 2] int32 (queue, slot) rows,
    T = counts.sum(), in solve order."""
    q_dim = deserved.shape[0]
    r_dim = deserved.shape[1] if deserved.ndim == 2 else 0
    g_dim = demand.shape[1] if demand.ndim == 3 else 0
    total = int(counts.sum())
    if total == 0 or q_dim == 0:
        return np.zeros((0, 2), dtype=np.int32)
    if r_dim == 0:
        # degenerate: no resources anywhere -> every share is 0, ordering
        # degrades to deterministic queue-index round-robin via zero tensors
        r_dim = 1
        deserved = np.zeros((q_dim, 1), np.float32)
        usage = np.zeros((q_dim, 1), np.float32)
        demand = np.zeros((q_dim, max(g_dim, 1), 1), np.float32)
        g_dim = demand.shape[1]
    run = _compiled(q_dim, g_dim, r_dim)
    order = np.asarray(
        run(
            np.asarray(deserved, np.float32),
            np.asarray(usage, np.float32),
            np.asarray(demand, np.float32),
            np.asarray(counts, np.int32),
        )
    )
    return order[:total]
