"""Operator configuration: versioned file API with defaulting + validation.

Re-host of /root/reference/operator/api/config/ (types.go:52-200, defaults.go,
validation/validation.go): one YAML file configures the whole operator —
per-controller concurrency, leader election, server endpoints, logging,
the authorizer, and the cluster-topology reference.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List

import yaml

LOG_LEVELS = ("debug", "info", "error")
LOG_FORMATS = ("json", "text")


@dataclass
class ControllerConfig:
    """types.go:149-178 — per-controller ConcurrentSyncs."""

    concurrent_syncs: int = 1


@dataclass
class ControllersConfiguration:
    pod_clique_set: ControllerConfig = field(default_factory=ControllerConfig)
    pod_clique: ControllerConfig = field(default_factory=ControllerConfig)
    pod_clique_scaling_group: ControllerConfig = field(
        default_factory=ControllerConfig
    )


@dataclass
class LeaderElectionConfig:
    enabled: bool = False
    lease_duration: float = 15.0
    renew_deadline: float = 10.0
    retry_period: float = 2.0
    resource_name: str = "grove-tpu-leader-election"


@dataclass
class ServerConfig:
    webhook_port: int = 9443
    metrics_port: int = 8080
    health_probe_port: int = 8081
    profiling_enabled: bool = False


@dataclass
class AuthorizerConfig:
    """types.go:180-190 — config-gated admission guard for managed children."""

    enabled: bool = False
    exempt_service_accounts: List[str] = field(default_factory=list)


@dataclass
class ClusterTopologyConfig:
    """types.go:192-200."""

    enabled: bool = False
    name: str = "default"


@dataclass
class SolverConfig:
    """TPU placement-engine knobs (no reference analogue — the solver is the
    piece the reference delegates to KAI)."""

    chunk_size: int = 128
    max_waves: int = 16
    priority_classes: Dict[str, int] = field(default_factory=dict)
    # route packing solves through a gRPC gang-solver sidecar (host:port;
    # empty -> solve in-process). BASELINE north-star boundary.
    sidecar_address: str = ""


@dataclass
class OperatorConfiguration:
    log_level: str = "info"
    log_format: str = "json"
    leader_election: LeaderElectionConfig = field(
        default_factory=LeaderElectionConfig
    )
    server: ServerConfig = field(default_factory=ServerConfig)
    controllers: ControllersConfiguration = field(
        default_factory=ControllersConfiguration
    )
    authorizer: AuthorizerConfig = field(default_factory=AuthorizerConfig)
    cluster_topology: ClusterTopologyConfig = field(
        default_factory=ClusterTopologyConfig
    )
    solver: SolverConfig = field(default_factory=SolverConfig)


def _controller(d: Dict[str, Any]) -> ControllerConfig:
    return ControllerConfig(concurrent_syncs=int(d.get("concurrentSyncs", 1)))


def load_operator_configuration(text: str) -> OperatorConfiguration:
    """Parse + default + validate (the reference pipeline in cmd/main.go)."""
    raw = yaml.safe_load(text) or {}
    cfg = OperatorConfiguration()
    cfg.log_level = raw.get("logLevel", cfg.log_level)
    cfg.log_format = raw.get("logFormat", cfg.log_format)
    le = raw.get("leaderElection") or {}
    cfg.leader_election = LeaderElectionConfig(
        enabled=bool(le.get("enabled", False)),
        lease_duration=float(le.get("leaseDuration", 15.0)),
        renew_deadline=float(le.get("renewDeadline", 10.0)),
        retry_period=float(le.get("retryPeriod", 2.0)),
        resource_name=le.get("resourceName", "grove-tpu-leader-election"),
    )
    srv = raw.get("server") or {}
    cfg.server = ServerConfig(
        webhook_port=int(srv.get("webhookPort", 9443)),
        metrics_port=int(srv.get("metricsPort", 8080)),
        health_probe_port=int(srv.get("healthProbePort", 8081)),
        profiling_enabled=bool(srv.get("profilingEnabled", False)),
    )
    ctrl = raw.get("controllers") or {}
    cfg.controllers = ControllersConfiguration(
        pod_clique_set=_controller(ctrl.get("podCliqueSet") or {}),
        pod_clique=_controller(ctrl.get("podClique") or {}),
        pod_clique_scaling_group=_controller(
            ctrl.get("podCliqueScalingGroup") or {}
        ),
    )
    auth = raw.get("authorizer") or {}
    cfg.authorizer = AuthorizerConfig(
        enabled=bool(auth.get("enabled", False)),
        exempt_service_accounts=list(auth.get("exemptServiceAccounts") or []),
    )
    topo = raw.get("clusterTopology") or {}
    cfg.cluster_topology = ClusterTopologyConfig(
        enabled=bool(topo.get("enabled", False)),
        name=topo.get("name", "default"),
    )
    solver = raw.get("solver") or {}
    cfg.solver = SolverConfig(
        chunk_size=int(solver.get("chunkSize", 128)),
        max_waves=int(solver.get("maxWaves", 16)),
        priority_classes=dict(solver.get("priorityClasses") or {}),
        sidecar_address=str(solver.get("sidecarAddress", "")),
    )
    validate_operator_configuration(cfg)
    return cfg


def load_operator_configuration_file(path: str) -> OperatorConfiguration:
    with open(path) as f:
        return load_operator_configuration(f.read())


def validate_operator_configuration(cfg: OperatorConfiguration) -> None:
    """validation/validation.go rule set."""
    errors = []
    if cfg.log_level not in LOG_LEVELS:
        errors.append(f"logLevel must be one of {LOG_LEVELS}")
    if cfg.log_format not in LOG_FORMATS:
        errors.append(f"logFormat must be one of {LOG_FORMATS}")
    for name, ctrl in (
        ("podCliqueSet", cfg.controllers.pod_clique_set),
        ("podClique", cfg.controllers.pod_clique),
        ("podCliqueScalingGroup", cfg.controllers.pod_clique_scaling_group),
    ):
        if ctrl.concurrent_syncs <= 0:
            errors.append(f"controllers.{name}.concurrentSyncs must be > 0")
    le = cfg.leader_election
    if le.enabled:
        if le.lease_duration <= le.renew_deadline:
            errors.append("leaderElection.leaseDuration must exceed renewDeadline")
        if le.renew_deadline <= le.retry_period:
            errors.append("leaderElection.renewDeadline must exceed retryPeriod")
    for port_name, port in (
        ("webhookPort", cfg.server.webhook_port),
        ("metricsPort", cfg.server.metrics_port),
        ("healthProbePort", cfg.server.health_probe_port),
    ):
        if not (0 < port < 65536):
            errors.append(f"server.{port_name} must be a valid port")
    if cfg.cluster_topology.enabled and not cfg.cluster_topology.name:
        errors.append("clusterTopology.name is required when enabled")
    if cfg.solver.chunk_size <= 0 or cfg.solver.max_waves <= 0:
        errors.append("solver.chunkSize and solver.maxWaves must be > 0")
    if errors:
        raise ValueError("invalid operator configuration: " + "; ".join(errors))
