"""Seeded fault injection for the worker-process wire boundary.

The coordinator↔worker channel (runtime/procworkers.py) is JSON frames
over a pipe — reliable in-order bytes. Gray failures live one layer up:
a frame that never arrives (drop), arrives twice (duplicate), or
arrives late (delay). :class:`BoundaryFaults` is the seeded fault PLAN
for one run: a pure function of ``(seed, direction, worker, frame
seq)`` via the tree's crc32 draw idiom (GL001 — no wall clock, no
unseeded RNG), so the coordinator and its forked children — each
holding a copy — compute identical verdicts without exchanging a byte.

The tolerance protocol the faults exercise (armed only — the unarmed
channel code is byte-identical to the fault-free build):

- every frame carries a monotone per-channel sequence number;
- receivers DEDUP on it: a frame at or below the high-water mark is a
  duplicate and is dropped (coordinator) or answered from the cached
  reply (worker — the idempotent-RPC shape: re-asking must not
  re-execute the batch);
- senders treat drop and delay as "the retry path delivers": the frame
  is withheld and the coordinator's retrying ``_recv`` retransmits the
  request after a :class:`BackoffPolicy` pace — a retransmitted request
  re-triggers the worker's cached-reply resend, healing a lost reply
  too. Retransmits bypass injection (one fault per frame seq — gray
  loss, not a dead link; the fail-closed ``BATCH_DEADLINE_S`` still
  bounds the whole exchange).
"""

from __future__ import annotations

import zlib

from grove_tpu.runtime.backoff import BackoffPolicy

# retransmit pacing: base real-time pause before the first re-send of a
# withheld/lost frame, doubling per attempt under the shared policy (the
# third retry loop unified onto runtime/backoff.py)
RETRANSMIT_BASE_S = 0.2
RETRANSMIT_CAP_S = 2.0

OK = "ok"
DROP = "drop"
DUP = "dup"
DELAY = "delay"


class BoundaryFaults:
    """One run's seeded fault plan for the wire-codec boundary.

    Rates are cumulative probabilities over the uniform crc32 draw:
    ``u < drop_rate`` drops, then ``dup_rate`` duplicates, then
    ``delay_rate`` delays; the rest pass clean. Deterministic per
    (seed, direction, worker, seq) — a forked copy agrees with the
    original on every verdict.
    """

    def __init__(
        self,
        seed: int,
        drop_rate: float = 0.0,
        dup_rate: float = 0.0,
        delay_rate: float = 0.0,
    ) -> None:
        self.seed = seed
        self.drop_rate = drop_rate
        self.dup_rate = dup_rate
        self.delay_rate = delay_rate
        self.policy = BackoffPolicy(
            base=RETRANSMIT_BASE_S, cap=RETRANSMIT_CAP_S
        )

    def decide(self, direction: str, worker: int, seq: int) -> str:
        """Fault verdict for frame ``seq`` on ``direction`` ("c2w" or
        "w2c") of worker ``worker``'s channel."""
        u = (
            zlib.crc32(
                f"{self.seed}:{direction}:{worker}:{seq}".encode()
            )
            & 0xFFFF
        ) / float(1 << 16)
        if u < self.drop_rate:
            return DROP
        u -= self.drop_rate
        if u < self.dup_rate:
            return DUP
        u -= self.dup_rate
        if u < self.delay_rate:
            return DELAY
        return OK

    def retransmit_after(self, worker: int, attempt: int) -> float:
        """Real-time pause before retransmit ``attempt`` (0-based) on
        worker ``worker``'s channel."""
        return self.policy.delay(("bseq", worker), attempt)
