"""Keyspace sharding for the control plane (docs/control-plane.md).

The scale-out story (ROADMAP "100k nodes / 1M pods"): every global fold
in the control plane — one store lock, one resourceVersion sequence, one
watch fan-out, status aggregation that touches every pod — stops scaling
once the solver hot path is incremental. This module partitions the
store's keyspace so no single structure spans the world:

- ``shard_of(namespace, S)`` hashes namespaces onto ``S`` shards.
  **Cluster-scoped objects (namespace == "") are pinned to shard 0** so
  singleton CRs (ClusterTopology, Queues, NodeDrains) have one home and
  the unsharded S=1 layout is the degenerate case of the same rule.
  crc32, not ``hash()``: the map must be identical across processes and
  replays (PYTHONHASHSEED), and must match the on-disk per-shard WAL
  layout a recovery re-reads.
- ``StoreShard`` is one shard's entire private state: committed/cached
  object maps, canonical blobs, label + namespace indices, its OWN
  resourceVersion sequence and write lock, its own system-watch
  subscriber list (the per-shard fan-out durability subscribes to), and
  its own level-1 pod aggregate (``runtime/aggregate.py``).
- ``ShardSummaryTree`` is the level-2 fold: per-shard (total, ready)
  pod partials folded up a fixed-fan-in tree so a cluster-wide
  readiness read is O(S/fan-in + depth) over S partials — never a scan
  of the pod population — and the fold depth is reported, which is what
  the bench's fold-depth histogram pins.

The **resourceVersion merge rule** (the wire-compat contract): each
shard runs its own rv sequence, per-object optimistic concurrency
compares rvs within one shard only (an object never changes shards —
its namespace is part of its key), and the store-level scalar
``Store.resource_version`` is the SUM of per-shard rvs. The sum is a
valid watermark — every commit bumps exactly one shard by exactly one,
so the scalar is the total commit count and strictly monotone — and at
S=1 it IS the legacy counter, byte-identical. Clients that need the
exact vector (per-shard durability, the sharded recovery merge) read
``Store.resource_version_vector()``.

Shard internals are PRIVATE to runtime/shards.py, runtime/store.py and
grove_tpu/durability/ — grovelint GL013 flags any other access, the way
GL011 guards the unsharded store internals.

The shard index stamped here is also the telemetry lane (PR 12
glass-box layer, docs/observability.md): ``WatchEvent.shard`` routes the
engine's backlogs AND the flight recorder's commit-digest rings; the
engine stamps spans/profiler phases with ``Store.shard_index(namespace)``
around each reconcile; the event recorder stamps ``EventRecord.shard``
through the same map; and each per-shard WAL stream attributes its
flushes to its own shard. One map, every signal — so when the ROADMAP's
parallel-CP PR runs shards as real workers, every layer already renders
them as separate lanes.
"""

from __future__ import annotations

import threading
import zlib
from typing import Callable, Dict, List, Tuple

from grove_tpu.runtime.aggregate import PodAggregate

# default fan-in of the level-2 summary fold tree: 8 keeps the tree two
# levels deep up to 64 shards (depth = ceil(log8 S) + 1 leaf level)
FOLD_FAN_IN = 8


def shard_of(namespace: str, num_shards: int) -> int:
    """Owning shard of a namespace. Deterministic across processes and
    replays (crc32, never hash()); cluster-scoped keys ("" namespace)
    pin to shard 0; S=1 degenerates to the unsharded store."""
    if num_shards <= 1 or not namespace:
        return 0
    return zlib.crc32(namespace.encode("utf-8")) % num_shards


class StoreShard:
    """One keyspace shard's private state. The Store routes every
    namespaced operation to exactly one shard; cross-shard reads merge
    (documented in docs/control-plane.md). Nothing outside the owning
    modules may touch these fields (GL013)."""

    __slots__ = (
        "index",
        "lock",
        "rv",
        "emitted",
        "committed",
        "cache",
        "blob",
        "cache_blob",
        "label_index",
        "cache_label_index",
        "ns_index",
        "cache_ns_index",
        "system_watchers",
        "agg_committed",
        "agg_cached",
    )

    def __init__(self, index: int, cache_lag: bool) -> None:
        self.index = index
        # per-shard write lock: threaded real-cluster consumers (the
        # background WAL committer's snapshot scan, concurrent apiserver
        # writers) serialize per shard instead of stopping the world.
        # Single-threaded sims never contend — an uncontended RLock
        # acquire is the only cost on the write path.
        self.lock = threading.RLock()
        # this shard's OWN resourceVersion sequence (the merge rule is
        # documented in the module docstring / docs/control-plane.md)
        self.rv = 0
        # count of EVERY event emitted on this shard — unlike rv it moves
        # on hard deletes too, so it is the staleness signal speculative
        # consumers (the scheduler's overlap pump) key their reuse on
        self.emitted = 0
        # kind -> "ns/name" -> obj (plus the canonical pickled blobs and
        # the lagged informer-cache twins), exactly the unsharded store's
        # layout scoped to this shard's namespaces
        self.committed: Dict[str, Dict[str, object]] = {}
        self.cache: Dict[str, Dict[str, object]] = {}
        self.blob: Dict[str, Dict[str, bytes]] = {}
        self.cache_blob: Dict[str, Dict[str, bytes]] = {}
        # kind -> (label_key, label_value) -> set of object keys
        self.label_index: Dict[str, Dict[tuple, set]] = {}
        self.cache_label_index: Dict[str, Dict[tuple, set]] = {}
        # kind -> namespace -> {key: None} (dict-as-ordered-set so a
        # namespace-scoped scan yields the EXACT order the flat full-map
        # filter used to: updates replace in place, never re-append)
        self.ns_index: Dict[str, Dict[str, Dict[str, None]]] = {}
        self.cache_ns_index: Dict[str, Dict[str, Dict[str, None]]] = {}
        # per-shard system watch fan-out: consumers that subscribe to ONE
        # shard (per-shard WAL streams) never see — and never head-of-
        # line-block on — another shard's traffic. (The engine keeps its
        # OWN per-shard backlogs, fed from the operator watch channel and
        # routed on WatchEvent.shard — push stays the only delivery mode.)
        self.system_watchers: List[Callable] = []
        # level-1 incremental pod aggregates, one per read view — the
        # same exactness contract as the unsharded PodAggregate, scoped
        # to this shard's namespaces
        self.agg_committed = PodAggregate()
        self.agg_cached = PodAggregate() if cache_lag else self.agg_committed

    # -- census (observability / bench) ---------------------------------

    def object_count(self) -> int:
        return sum(len(v) for v in self.committed.values())


class ShardSummaryTree:
    """Level-2 hierarchical fold over per-shard pod partials.

    Level 1 (the per-shard ``PodAggregate``) folds each watch delta into
    per-(namespace, clique) rows AND into the shard's (total, ready)
    partial — O(1) per event: commits never touch this tree. A
    ``pod_summary()`` read calls ``refold`` with the S fresh leaf
    partials and folds them upward with fan-in ``FOLD_FAN_IN`` — O(S)
    work over the partials, never a scan of the pod population, and no
    fold at any level sees more than ``fan_in`` rows.
    ``fold_depth_histogram`` reports nodes per level — the bench's proof
    the fold is a tree, not a flat O(pods) rescan."""

    __slots__ = ("num_shards", "fan_in", "levels")

    def __init__(self, num_shards: int, fan_in: int = FOLD_FAN_IN) -> None:
        self.num_shards = max(1, num_shards)
        self.fan_in = max(2, fan_in)
        # levels[0] = per-shard leaves, levels[-1] = single root
        self.levels: List[List[Tuple[int, int]]] = []
        width = self.num_shards
        while True:
            self.levels.append([(0, 0)] * width)
            if width == 1:
                break
            width = (width + self.fan_in - 1) // self.fan_in

    @property
    def depth(self) -> int:
        return len(self.levels)

    def refold(self, partials: List[Tuple[int, int]]) -> None:
        """Fold fresh leaf partials up the tree (called per summary read)."""
        self.levels[0] = list(partials)
        for li in range(1, len(self.levels)):
            below = self.levels[li - 1]
            level = []
            # each parent folds at most fan_in children — no fold at any
            # level ever sees more than fan_in rows
            for i in range(0, len(below), self.fan_in):
                total = ready = 0
                for t, r in below[i : i + self.fan_in]:
                    total += t
                    ready += r
                level.append((total, ready))
            self.levels[li] = level

    def update_leaf(self, index: int, partial: Tuple[int, int]) -> None:
        """Path refold: replace ONE leaf partial and refold only its
        ancestor chain — O(depth × fan_in) instead of O(S). The read-side
        skip for quiet stores: `pod_summary()` tracks which shards' level-1
        partials moved since the last read and path-refolds when few did
        (docs/control-plane.md §4 routing-overhead shave)."""
        self.levels[0][index] = partial
        child = index
        for li in range(1, len(self.levels)):
            parent = child // self.fan_in
            base = parent * self.fan_in
            below = self.levels[li - 1]
            total = ready = 0
            for t, r in below[base : base + self.fan_in]:
                total += t
                ready += r
            self.levels[li][parent] = (total, ready)
            child = parent

    def root(self) -> Tuple[int, int]:
        return self.levels[-1][0]

    def fold_depth_histogram(self) -> List[int]:
        """Nodes per fold level, leaves first — e.g. 16 shards, fan-in 8
        → [16, 2, 1]: the widest fold any read performs is fan_in."""
        return [len(level) for level in self.levels]
