"""Typed error codes + GroveError.

Re-host of /root/reference/operator/internal/errors/errors.go:31-103: every
component Sync surfaces `GroveError{code, operation, message}`; two sentinel
codes tunnel control-flow decisions (requeue) through the component boundary
back to the reconcile flow.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

# Sentinel codes driving control flow (errors.go:40-47)
ERR_REQUEUE_AFTER = "ERR_REQUEUE_AFTER"
ERR_CONTINUE_RECONCILE_AND_REQUEUE = "ERR_CONTINUE_RECONCILE_AND_REQUEUE"

# Representative operational codes (the reference defines ~40 ERR_* constants
# across components, e.g. pod.go:46-65); new codes are free-form strings.
ERR_GET_RESOURCE = "ERR_GET_RESOURCE"
ERR_LIST_RESOURCE = "ERR_LIST_RESOURCE"
ERR_CREATE_RESOURCE = "ERR_CREATE_RESOURCE"
ERR_UPDATE_RESOURCE = "ERR_UPDATE_RESOURCE"
ERR_DELETE_RESOURCE = "ERR_DELETE_RESOURCE"
ERR_SYNC_PODS = "ERR_SYNC_PODS"
ERR_VALIDATION = "ERR_VALIDATION"
ERR_CONFLICT = "ERR_CONFLICT"
ERR_NOT_FOUND = "ERR_NOT_FOUND"
ERR_FORBIDDEN = "ERR_FORBIDDEN"
# wire client: connection-level failure (refused/reset/timeout) — the one
# code retry loops may classify as transient
ERR_TRANSPORT = "ERR_TRANSPORT"


class GroveError(Exception):
    def __init__(
        self,
        code: str,
        message: str = "",
        operation: str = "",
        cause: Optional[Exception] = None,
        requeue_after: Optional[float] = None,
    ) -> None:
        super().__init__(f"[{code}] {operation}: {message}")
        self.code = code
        self.message = message
        self.operation = operation
        self.cause = cause
        # used with ERR_REQUEUE_AFTER / ERR_CONTINUE_RECONCILE_AND_REQUEUE
        self.requeue_after = requeue_after


def requeue_after_error(delay: float, operation: str = "", message: str = "") -> GroveError:
    return GroveError(
        ERR_REQUEUE_AFTER, message or f"requeue after {delay}s", operation,
        requeue_after=delay,
    )


def continue_and_requeue_error(
    delay: float, operation: str = "", message: str = ""
) -> GroveError:
    return GroveError(
        ERR_CONTINUE_RECONCILE_AND_REQUEUE, message or f"continue; requeue after {delay}s",
        operation, requeue_after=delay,
    )


@dataclass
class LastError:
    """Status-persisted error (errors.go:88-103 mapping to LastErrors)."""

    code: str
    description: str
    observed_at: float

    @staticmethod
    def from_errors(errors: List[GroveError], now: float) -> List["LastError"]:
        return [
            LastError(code=e.code, description=str(e), observed_at=now)
            for e in errors
            if e.code not in (ERR_REQUEUE_AFTER, ERR_CONTINUE_RECONCILE_AND_REQUEUE)
        ]


@dataclass
class ErrorAggregate(Exception):
    errors: List[GroveError] = field(default_factory=list)
