"""In-memory object store — the fake apiserver.

Stands in for kube-apiserver + etcd + informer caches (the reference's entire
"communication backend", SURVEY §5). Supports the exact semantics the
controllers rely on:

- resourceVersion bump per write; generation bump on spec updates only
- watch events (Added/Modified/Deleted) fanned out to subscribers
- finalizer-aware deletion (deletion_timestamp first, removal when finalizers
  drain — mirrors apiserver behavior the reference's ensureFinalizer flows use)
- label-selector list
- optional *cache lag*: reads can be served from a stale snapshot that only
  advances when `sync_cache()` is called, reproducing the informer-staleness
  race the reference's expectations store exists to absorb
  (expect/expectations.go:33-50). Tests run the controllers in lagged mode so
  those races can't hide.
- optional *keyspace sharding* (`num_shards`/`GROVE_TPU_STORE_SHARDS`,
  docs/control-plane.md): namespaces hash onto S shards
  (runtime/shards.py), each with its own object maps, indices, lock,
  resourceVersion sequence, watch fan-out and (when durability is
  attached) WAL segment stream. The router below preserves the exact
  Store API; cross-shard `list()`/`scan()` merge per the documented
  rv-vector rule. S=1 is the degenerate case and is provably
  byte-identical to the historical unsharded store (tests/test_shards.py
  pins the A/B).
"""

from __future__ import annotations

import copy as _copy
import os
import pickle
import threading
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from grove_tpu.api.meta import deep_copy, next_uid
from grove_tpu.observability.flightrec import FLIGHTREC
from grove_tpu.observability.journey import JOURNEYS
from grove_tpu.observability.profile import PROFILER
from grove_tpu.runtime.clock import Clock
from grove_tpu.runtime.errors import (
    ERR_CONFLICT,
    ERR_FORBIDDEN,
    ERR_NOT_FOUND,
    GroveError,
)
from grove_tpu.runtime.shards import ShardSummaryTree, StoreShard, shard_of

ADDED = "Added"
MODIFIED = "Modified"
DELETED = "Deleted"

_UNSET = object()  # commit_cow sentinel: "field not replaced"

# Label keys with inverted indices (the controllers' hot selectors). A
# selector containing any of these resolves to the candidate set instead of
# scanning the whole kind — the control plane's lists go O(matched).
INDEXED_LABELS = (
    "grove.io/podclique",
    "grove.io/podgang",
    "grove.io/podcliquescalinggroup",
    "app.kubernetes.io/part-of",
)


def _dumps(obj) -> Optional[bytes]:
    """Canonical pickled form of a committed object. Computed ONCE per
    write; every read materializes with a single pickle.loads — half the
    cost of a dumps+loads round trip, which profiling shows dominates
    control-plane time. None when the object doesn't pickle (then reads
    fall back to deep_copy)."""
    try:
        return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    except Exception:
        return None


def _materialize(obj, blob: Optional[bytes]):
    return pickle.loads(blob) if blob is not None else deep_copy(obj)


@dataclass
class WatchEvent:
    type: str
    kind: str
    obj: object  # READ-ONLY view shared by all subscribers — never mutate;
    # call materialize() for a private copy
    blob: Optional[bytes] = field(default=None, repr=False, compare=False)
    # previous committed object on MODIFIED events (same read-only
    # contract) — what controller-runtime's UpdateEvent.ObjectOld carries,
    # so watch predicates can gate on actual state TRANSITIONS
    # (reference register.go predicate.Funcs UpdateFunc(old, new))
    old: Optional[object] = field(default=None, repr=False, compare=False)
    # owning keyspace shard (runtime/shards.py) — consumers that keep
    # per-shard buffers (the engine's per-shard backlogs) route on this
    # instead of re-hashing the namespace per event
    shard: int = field(default=0, repr=False, compare=False)

    def materialize(self):
        """Private deep copy of the event payload (cheap: pre-pickled)."""
        return _materialize(self.obj, self.blob)


def obj_key(obj) -> str:
    return f"{obj.metadata.namespace}/{obj.metadata.name}"


def commit_status(store, view, status):
    """Status write against a readonly `view` via the store's copy-on-write
    path when available (in-memory Store), else the portable mutable
    re-get + update_status cycle (HttpStore). Returns the updated object,
    or None if it disappeared."""
    cow = getattr(store, "commit_cow", None)
    if cow is not None:
        return cow(view, status=status)
    fresh = store.get(view.kind, view.metadata.namespace, view.metadata.name)
    if fresh is None:
        return None
    fresh.status = status
    return store.update_status(fresh)


def commit_finalizer_add(store, view, finalizer: str):
    """Finalizer add (metadata write, no generation bump) against a
    readonly `view` via the copy-on-write path when available. Returns the
    committed object, or None if it disappeared (HttpStore fallback)."""
    cow = getattr(store, "commit_cow", None)
    if cow is not None:
        meta = _copy.copy(view.metadata)
        meta.finalizers = list(view.metadata.finalizers)
        meta.finalizers.append(finalizer)
        return cow(view, metadata=meta)
    fresh = store.get(view.kind, view.metadata.namespace, view.metadata.name)
    if fresh is None:
        return None
    if finalizer not in fresh.metadata.finalizers:
        fresh.metadata.finalizers.append(finalizer)
        return store.update(fresh, bump_generation=False)
    return fresh


def commit_spec(store, view, spec):
    """Spec write (no generation bump) against a readonly `view` via the
    copy-on-write path when available, else mutable re-get + update."""
    cow = getattr(store, "commit_cow", None)
    if cow is not None:
        return cow(view, spec=spec)
    fresh = store.get(view.kind, view.metadata.namespace, view.metadata.name)
    if fresh is None:
        return None
    fresh.spec = spec
    return store.update(fresh, bump_generation=False)


def _index_insert(index: Dict[tuple, set], obj) -> None:
    key = obj_key(obj)
    for lk in INDEXED_LABELS:
        lv = obj.metadata.labels.get(lk)
        if lv is not None:
            index.setdefault((lk, lv), set()).add(key)


def _index_delete(index: Dict[tuple, set], obj) -> None:
    key = obj_key(obj)
    for lk in INDEXED_LABELS:
        lv = obj.metadata.labels.get(lk)
        if lv is not None:
            entries = index.get((lk, lv))
            if entries is not None:
                entries.discard(key)


def _semantically_equal(a, b) -> bool:
    """Deep equality ignoring resourceVersion/generation bookkeeping.
    Swap-compare-restore: no extra deep copies on the hottest write path."""
    saved = (a.metadata.resource_version, a.metadata.generation)
    a.metadata.resource_version = b.metadata.resource_version
    a.metadata.generation = b.metadata.generation
    try:
        return a == b
    finally:
        a.metadata.resource_version, a.metadata.generation = saved


def matches_labels(obj, selector: Optional[Dict[str, str]]) -> bool:
    if not selector:
        return True
    labels = obj.metadata.labels or {}
    # plain loop, not all(genexpr): this runs per candidate per selector on
    # every controller list/scan — the generator frame overhead alone was
    # ~2% of a 2,000-set converge (profiled round 4)
    for k, v in selector.items():
        if labels.get(k) != v:
            return False
    return True


class Store:
    def __init__(
        self,
        clock: Optional[Clock] = None,
        cache_lag: bool = False,
        num_shards: Optional[int] = None,
    ) -> None:
        self.clock = clock or Clock()
        self.cache_lag = cache_lag
        # keyspace sharding (runtime/shards.py, docs/control-plane.md):
        # every per-keyspace structure — object maps, canonical blobs,
        # label/namespace indices, the rv sequence, the write lock, the
        # per-shard system watch fan-out, the level-1 pod aggregates —
        # lives in a StoreShard. S=1 (the default) is the historical
        # unsharded store, byte-identical (tests/test_shards.py A/B).
        if num_shards is None:
            num_shards = int(os.environ.get("GROVE_TPU_STORE_SHARDS", "1") or 1)
        self.num_shards = max(1, int(num_shards))
        self._shards: List[StoreShard] = [
            StoreShard(i, cache_lag) for i in range(self.num_shards)
        ]
        self._single = self.num_shards == 1
        self._shard_memo: Dict[str, StoreShard] = {}
        # level-2 hierarchical fold over the shards' (total, ready) pod
        # partials — refolded lazily on pod_summary() reads, zero cost on
        # the commit path beyond a set-add of the owning shard's index.
        # One tree per read view (the cached view's partials advance on
        # watch delivery, not at commit); dirty sets track which shards'
        # level-1 partials moved since the last read, so a quiet store's
        # summary read is a cached root and a one-shard-dirty read is a
        # path refold, not an O(S) whole-tree fold (docs/control-plane.md
        # §4 routing-overhead shave)
        self._summary_tree = ShardSummaryTree(self.num_shards)
        self._summary_tree_cached = (
            ShardSummaryTree(self.num_shards)
            if cache_lag
            else self._summary_tree
        )
        self._summary_dirty = set(range(self.num_shards))
        self._summary_dirty_cached = set(range(self.num_shards))
        self._watchers: List[Callable[[WatchEvent], None]] = []
        self._system_watchers: List[Callable[[WatchEvent], None]] = []
        # per-shard-fanned consumers (subscribe_system_per_shard) + the
        # deferred-capture plumbing the parallel control plane arms
        # (runtime/workers.py): inert — a plain list append and two
        # attributes — until arm_deferred_fanout() runs
        self._per_shard_fns: List[Callable[[WatchEvent], None]] = []
        self._deferred_armed = False
        self._capture_tls = threading.local()
        # copy-on-write commits skip the canonical pickle blob; under the
        # test-mode store guard (GROVE_TPU_STORE_GUARD, or sanitizer mode
        # GROVE_TPU_SANITIZE which generalizes it) they compute it eagerly
        # anyway so verify_readonly_integrity keeps byte-compare coverage
        from grove_tpu.analysis.sanitize import store_guard_enabled

        self._guard_blobs = store_guard_enabled()
        # optional admission guard (grove_tpu.admission.authorization):
        # writes are checked against the current actor; in-process
        # controllers act as the operator identity
        self.guard = None
        self.actor: Optional[str] = None
        # fault injection (reference test/utils/client.go): map of
        # "create"|"update"|"delete" -> callable(obj) -> Optional[Exception];
        # a returned exception is raised before the write commits
        self.error_injectors: Dict[str, Callable] = {}
        # shard attribution for the event recorder ("newest store wins",
        # like the tracer/event clocks): events then carry the involved
        # object's owning keyspace shard without re-hashing anywhere else
        from grove_tpu.observability.events import EVENTS

        EVENTS.shard_fn = self.shard_index if self.num_shards > 1 else None

    def _inject(self, operation: str, obj) -> None:
        injector = self.error_injectors.get(operation)
        if injector is not None:
            err = injector(obj)
            if err is not None:
                raise err

    @contextmanager
    def as_user(self, username: str):
        """Attribute subsequent writes to `username` (authorization guard)."""
        previous = self.actor
        self.actor = username
        try:
            yield self
        finally:
            self.actor = previous

    def _authorize(self, operation: str, obj) -> None:
        if self.guard is None:
            return
        from grove_tpu.admission.authorization import OPERATOR_USERNAME

        actor = self.actor or OPERATOR_USERNAME
        decision = self.guard.check(actor, operation, obj)
        if not decision.allowed:
            raise GroveError(ERR_FORBIDDEN, decision.reason, operation)

    # -- shard routing (runtime/shards.py, docs/control-plane.md) --------

    def _shard_for(self, namespace: str) -> StoreShard:
        """Owning shard of a namespace ("" — cluster-scoped — is shard 0).

        Memoized: the router runs on every get/list/emit — crc32 per call
        was ~1/5 of the sharded per-reconcile overhead at the 10k-set A/B
        — and the namespace population is tiny next to the call volume
        (the memo retains entries for deleted namespaces; bounded by
        namespaces ever seen, and the map is immutable per store)."""
        if self._single:
            return self._shards[0]
        shard = self._shard_memo.get(namespace)
        if shard is None:
            shard = self._shards[shard_of(namespace, self.num_shards)]
            self._shard_memo[namespace] = shard
        return shard

    def _shard_of_obj(self, obj) -> StoreShard:
        if self._single:
            return self._shards[0]
        return self._shard_for(obj.metadata.namespace)

    def shard_index(self, namespace: str) -> int:
        """Public keyspace map: which shard owns `namespace`."""
        return 0 if self._single else shard_of(namespace, self.num_shards)

    def shard_resource_version(self, index: int) -> int:
        """One shard's rv sequence (per-shard durability watermark)."""
        return self._shards[index].rv

    def shard_emitted(self, index: int) -> int:
        """One shard's emitted-event count. Moves on every commit AND on
        hard deletes (which rv skips) — the staleness token speculative
        readers (solver/scheduler.py overlap pump) compare before
        trusting work computed against an earlier view of the shard."""
        return self._shards[index].emitted

    def resource_version_vector(self) -> Tuple[int, ...]:
        """Per-shard resourceVersion vector — the exact form of the merge
        rule `resource_version` collapses to a scalar (docs/control-plane.md)."""
        return tuple(s.rv for s in self._shards)

    def shard_census(self) -> List[dict]:
        """Per-shard object count + rv (the scale bench/smoke's census);
        also publishes the `store_shard_objects` gauge per shard."""
        from grove_tpu.observability.metrics import METRICS

        out = []
        for s in self._shards:
            n = s.object_count()
            METRICS.set(f"store_shard_objects@{s.index}", n)
            METRICS.set(f"store_shard_rv@{s.index}", s.rv)
            out.append({"shard": s.index, "objects": n, "rv": s.rv})
        return out

    # -- watch ----------------------------------------------------------

    def subscribe(self, fn: Callable[[WatchEvent], None]) -> None:
        self._watchers.append(fn)

    def subscribe_system(
        self, fn: Callable[[WatchEvent], None], shard: Optional[int] = None
    ) -> None:
        """Subscribe a watcher OUTSIDE the operator process (sim kubelet /
        scheduler): operator-restart tests clear `_watchers` to model the
        crashed process's watches vanishing, but cluster-side components
        are separate processes whose watches survive an operator crash.

        With `shard=k` the subscription is PER-SHARD: the watcher sees
        only shard k's events (its slice of the keyspace), so a per-shard
        consumer (a shard's WAL segment stream) never filters — or waits
        on — another shard's traffic. Delivery order within a shard is
        identical to the unsharded fan-out.

        Store-wide (shard=None) consumers see EVERY shard's stream in
        one global order — under the parallel control plane that order
        is deferred-and-replayed in the serial batch order (their fold
        state, e.g. the sim cluster's not-ready working set, must not
        inherit a racy worker interleave); per-shard consumers (the WAL
        streams) stay live, their order is per-shard deterministic."""
        if shard is None:
            self._system_watchers.append(
                self._make_deferrable(fn) if self._deferred_armed else fn
            )
        else:
            self._shards[shard].system_watchers.append(fn)

    def subscribe_system_per_shard(self, fn: Callable[[WatchEvent], None]) -> None:
        """Register `fn` on EVERY shard's per-shard fan-out (S entries).
        For incremental-fold consumers (quota accountant, delta-solve
        state) whose per-object streams never straddle shards: they ride
        the per-shard delivery path — in front of any store-wide
        subscriber's traffic for other shards — without maintaining S
        callbacks themselves. At S=1 this is one subscription on the
        single shard, same delivery order as subscribe_system.

        These consumers fold SHARED, order-sensitive state (a quota
        row, the delta free matrix) from every shard's stream — under
        the parallel control plane (runtime/workers.py) their delivery
        is deferred-and-replayed in the serial order rather than called
        live from worker threads, so the registry below records exactly
        which callbacks `arm_deferred_fanout` must wrap (late
        registrations — delta state attached after the engine armed
        workers — wrap at registration time)."""
        self._per_shard_fns.append(fn)
        target = self._make_deferrable(fn) if self._deferred_armed else fn
        for s in self._shards:
            s.system_watchers.append(target)

    # -- deferred fan-out (runtime/workers.py, docs/control-plane.md §5) --

    def arm_deferred_fanout(self) -> None:
        """Make every ORDER-SENSITIVE watch consumer capturable: while a
        thread holds an open capture buffer (a parallel reconcile on a
        worker), deliveries are buffered instead of called, and the
        coordinator replays them in the serial batch order. Covered:
        `subscribe_system_per_shard` consumers (delta state, quota
        accountant — shared fold state whose float accumulation order
        must equal the serial drain's) AND store-wide `subscribe_system`
        consumers (the sim cluster's not-ready working set: a Python
        set's iteration order is its insertion history, and the kubelet
        + pending scan iterate it — a racy worker interleave there is a
        nondeterminism leak even though each add/discard is atomic).
        Threads with no open buffer (the scheduler, kubelet, component
        ticks on the coordinator) keep live delivery — the serial
        path's behavior exactly. Installed once, only when the engine
        arms workers; the serial drain never pays the extra
        thread-local read."""
        if self._deferred_armed:
            return
        self._deferred_armed = True
        wrapped = {fn: self._make_deferrable(fn) for fn in self._per_shard_fns}
        for s in self._shards:
            s.system_watchers = [
                wrapped.get(w, w) for w in s.system_watchers
            ]
        self._system_watchers = [
            self._make_deferrable(w) for w in self._system_watchers
        ]

    def _make_deferrable(self, fn: Callable[[WatchEvent], None]):
        tls = self._capture_tls

        def deliver(ev: WatchEvent, _fn=fn, _tls=tls) -> None:
            buf = getattr(_tls, "buf", None)
            if buf is None:
                _fn(ev)
            else:
                buf.append((_fn, ev))

        return deliver

    def begin_deferred_capture(self) -> list:
        """Open a capture buffer on THIS thread (one parallel reconcile's
        deferred deliveries). Returns the buffer to pass to
        `end_deferred_capture`."""
        buf: list = []
        self._capture_tls.buf = buf
        return buf

    def end_deferred_capture(self, buf: list) -> list:
        """Close this thread's capture buffer and return its (fn, event)
        deliveries for the coordinator's in-order replay."""
        self._capture_tls.buf = None
        return buf

    def _emit(
        self,
        type_: str,
        obj,
        blob: Optional[bytes],
        old: object = None,
        shard: Optional[StoreShard] = None,
    ) -> None:
        # zero-copy fanout: committed objects are immutable once stored, so
        # every subscriber may share the payload; WatchEvent.materialize()
        # (pre-pickled) is the escape hatch for watchers that must mutate
        if shard is None:
            shard = self._shard_of_obj(obj)
        ev = WatchEvent(
            type=type_, kind=obj.kind, obj=obj, blob=blob, old=old,
            shard=shard.index,
        )
        shard.emitted += 1
        # the committed view just mutated: fold the delta into the OWNING
        # SHARD's level-1 aggregate (kind-gated inside; `old` is the
        # previous committed object). The level-2 summary tree refolds
        # lazily on read — the commit path only notes WHICH shard's
        # partial moved, so a summary read after a quiet spell skips the
        # whole fold and a hot-shard burst path-refolds one leaf chain.
        shard.agg_committed.apply(type_, obj, old)
        if obj.kind == "Pod":
            self._summary_dirty.add(shard.index)
        # glass-box hooks (docs/observability.md), one boolean check each
        # while disabled: the flight recorder's per-shard commit-digest
        # ring, and the journey tracker's PodGang creation/deletion marks
        if FLIGHTREC.enabled:
            FLIGHTREC.note_commit(ev)
        if JOURNEYS.enabled and obj.kind == "PodGang":
            if type_ == ADDED:
                JOURNEYS.note_created(obj.metadata.namespace, obj.metadata.name)
            elif type_ == DELETED:
                JOURNEYS.note_deleted(obj.metadata.namespace, obj.metadata.name)
        # fan-out order: the owning shard's subscribers first (per-shard
        # streams), then the store-wide system watchers, then the operator
        # watchers — at S=1 with no per-shard subscriber this is exactly
        # the historical order
        for w in shard.system_watchers:
            w(ev)
        for w in self._system_watchers:
            w(ev)
        for w in self._watchers:
            w(ev)

    # -- cache ----------------------------------------------------------

    def sync_cache(self) -> None:
        """Advance the whole read cache to the committed state."""
        kinds = set()
        for shard in self._shards:
            kinds.update(shard.committed)
        for kind in kinds:
            self.sync_cache_kind(kind)

    def sync_cache_kind(self, kind: str) -> None:
        """Advance one kind's cache — models that kind's informer receiving
        its watch events (each informer syncs independently; cross-kind
        staleness is exactly the race expectations absorb). Committed
        objects are immutable, so the cache shares them (no copies)."""
        for shard in self._shards:
            shard.cache[kind] = dict(shard.committed.get(kind, {}))
            shard.cache_blob[kind] = dict(shard.blob.get(kind, {}))
            index: Dict[tuple, set] = {}
            ns_index: Dict[str, Dict[str, None]] = {}
            for key, obj in shard.cache[kind].items():
                _index_insert(index, obj)
                ns_index.setdefault(obj.metadata.namespace, {})[key] = None
            shard.cache_label_index[kind] = index
            shard.cache_ns_index[kind] = ns_index
            if kind == "Pod" and self.cache_lag:
                # full resync: the shard's cached aggregate re-derives
                # from its new view
                shard.agg_cached.rebuild(shard.cache[kind].values())
                self._summary_dirty_cached.add(shard.index)

    def apply_event_to_cache(self, ev: "WatchEvent") -> None:
        """Incrementally apply one delivered watch event to the read cache —
        O(1) informer semantics (sync_cache_kind re-syncs a whole kind and
        is kept for explicit full resyncs). Event payloads are immutable
        (read-only watcher contract), so the cache shares them."""
        # the event already carries its owning shard (stamped at _emit):
        # index straight into the shard table instead of re-routing the
        # namespace through the crc32 memo — this runs once per delivered
        # event on the informer hot path (docs/control-plane.md §4)
        shard = self._shards[ev.shard]
        kind_cache = shard.cache.setdefault(ev.kind, {})
        kind_blob = shard.cache_blob.setdefault(ev.kind, {})
        kind_index = shard.cache_label_index.setdefault(ev.kind, {})
        kind_ns = shard.cache_ns_index.setdefault(ev.kind, {})
        key = obj_key(ev.obj)
        old = kind_cache.get(key)
        if ev.kind == "Pod" and self.cache_lag:
            # the cached view advances exactly here — fold the same delta
            # into the shard's aggregate (old = the view's previous
            # object). Gated on cache_lag: without lag agg_cached aliases
            # agg_committed, which already folded this delta at commit.
            shard.agg_cached.apply(ev.type, ev.obj, old)
            self._summary_dirty_cached.add(shard.index)
        if old is not None:
            _index_delete(kind_index, old)
        if ev.type == DELETED:
            kind_cache.pop(key, None)
            kind_blob.pop(key, None)
            ns_map = kind_ns.get(ev.obj.metadata.namespace)
            if ns_map is not None:
                ns_map.pop(key, None)
            return
        kind_cache[key] = ev.obj
        if ev.blob is not None:
            kind_blob[key] = ev.blob
        else:
            kind_blob.pop(key, None)
        _index_insert(kind_index, ev.obj)
        # dict-as-ordered-set; replacing an existing key keeps its slot, so
        # the ns-scoped scan order equals the flat filtered-scan order
        kind_ns.setdefault(ev.obj.metadata.namespace, {})[key] = None

    # -- label + namespace indices ---------------------------------------

    def _index_add(self, shard: StoreShard, obj) -> None:
        _index_insert(shard.label_index.setdefault(obj.kind, {}), obj)

    def _index_remove(self, shard: StoreShard, obj) -> None:
        _index_delete(shard.label_index.get(obj.kind, {}), obj)

    def _shard_candidates(
        self,
        shard: StoreShard,
        kind: str,
        namespace: Optional[str],
        selector: Optional[Dict[str, str]],
        use_cache: bool,
        view: Dict[str, object],
    ):
        """Smallest indexed candidate set within one shard: an indexed
        label selector first (the controllers' hot selectors), else the
        per-kind NAMESPACE index (a kind+namespace list never scans the
        kind's full map — tests/test_shards.py pins no-full-scan), else
        all of the shard's keys."""
        if selector:
            index = (
                shard.cache_label_index if use_cache else shard.label_index
            ).get(kind)
            if index is not None:
                best = None
                for lk in INDEXED_LABELS:
                    if lk in selector:
                        entries = index.get((lk, selector[lk]), set())
                        if best is None or len(entries) < len(best):
                            best = entries
                if best is not None:
                    return [view[k] for k in list(best) if k in view]
        if namespace is not None:
            ns_map = (
                shard.cache_ns_index if use_cache else shard.ns_index
            ).get(kind, {}).get(namespace)
            if ns_map is None:
                return []
            # snapshot of the key list (not the objects): callers may
            # create/delete while iterating a scan
            return [view[k] for k in list(ns_map) if k in view]
        return list(view.values())

    # -- durability (grove_tpu/durability, docs/robustness.md) -----------

    @property
    def resource_version(self) -> int:
        """Store-level resourceVersion watermark (the WAL/snapshot
        watermark; reads only — writes bump it through commits).

        Merge rule (docs/control-plane.md): each shard runs its own rv
        sequence; the scalar is their SUM — every commit bumps exactly
        one shard by one, so the sum is the total commit count, strictly
        monotone, and at S=1 it IS the legacy counter byte-for-byte.
        Clients needing the exact per-shard form read
        `resource_version_vector()`."""
        if self._single:
            return self._shards[0].rv
        return sum(s.rv for s in self._shards)

    def kinds(self) -> List[str]:
        """Kinds with at least one committed object (snapshot scans pair
        this with `scan(kind)` to enumerate the whole population)."""
        kinds = set()
        for shard in self._shards:
            kinds.update(k for k, v in shard.committed.items() if v)
        return sorted(kinds)

    def shard_kinds(self, index: int) -> List[str]:
        """One shard's kinds (per-shard snapshot scans pair this with
        `scan(kind)` filtered by the shard's own view)."""
        shard = self._shards[index]
        return sorted(k for k, v in shard.committed.items() if v)

    def shard_scan(self, index: int, kind: str) -> Iterator[object]:
        """Zero-copy readonly iteration over ONE shard's committed objects
        of a kind (per-shard durability snapshots; same mutate-nothing
        contract as scan())."""
        yield from self._shards[index].committed.get(kind, {}).values()

    def restore_objects(
        self,
        objects,
        rv: int = 0,
        rv_vector: Optional[Sequence[int]] = None,
    ) -> int:
        """Recovery-path bulk load: commit `objects` VERBATIM — identity
        (uid/resourceVersion/generation/timestamps) preserved, no watch
        events (recovery precedes every subscriber; the boot resync
        machinery — engine.requeue_all, rebuild_bindings, monitor resync —
        covers delivery), aggregates/caches rebuilt, and the version
        counter(s) resumed so resourceVersion monotonicity survives the
        restart: scalar `rv` for the unsharded store, `rv_vector` (one
        watermark per shard, from the per-shard WAL dirs) when sharded.
        Only valid on a store with no prior commits."""
        if any(s.rv for s in self._shards):
            raise GroveError(
                ERR_CONFLICT,
                "restore_objects requires a fresh store (writes already"
                f" committed up to rv {self.resource_version})",
                "restore",
            )
        if rv_vector is not None and len(rv_vector) != self.num_shards:
            raise GroveError(
                ERR_CONFLICT,
                f"rv_vector has {len(rv_vector)} entries for a"
                f" {self.num_shards}-shard store",
                "restore",
            )
        if rv_vector is None and not self._single:
            raise GroveError(
                ERR_CONFLICT,
                "sharded restore requires the per-shard rv_vector (the"
                " scalar watermark cannot be split back into sequences)",
                "restore",
            )
        n = 0
        for obj in objects:
            shard = self._shard_of_obj(obj)
            self._commit(shard, obj)
            # keep each shard's sequence at/after its restored objects even
            # if the recorded watermark trails (defense in depth)
            shard.rv = max(shard.rv, obj.metadata.resource_version)
            n += 1
        if rv_vector is not None:
            for shard, shard_rv in zip(self._shards, rv_vector):
                shard.rv = max(shard.rv, int(shard_rv))
        else:
            self._shards[0].rv = max(self._shards[0].rv, int(rv))
        for shard in self._shards:
            shard.agg_committed.rebuild(
                shard.committed.get("Pod", {}).values()
            )
        self._summary_dirty = set(range(self.num_shards))
        if self.cache_lag:
            # warm informer caches (the initial LIST a restarted process
            # serves its informers); per-kind sync also rebuilds the
            # cached pod aggregate
            self.sync_cache()
        return n

    # -- remote mirror apply (runtime/procworkers.py) --------------------

    def apply_remote_event(self, etype: str, envelope: dict) -> "WatchEvent":
        """Mirror-apply ONE wire-encoded commit from a peer control-plane
        process (the worker-process backend, docs/control-plane.md §5).

        The process boundary is crossed only by the api/serialize.py wire
        codec — the same ``object_envelope``/``decode_envelope`` pair the
        WAL uses — so this is the single sanctioned entry for replicating
        a peer's commit into this process's mirror: decode, RESTAMP the
        object with this mirror's next rv, commit through the normal
        internal plumbing (indices, aggregates, canonical blob) and emit
        through the normal ``_emit`` fan-out so every consumer (WAL
        streams, engine backlogs, delta/quota folds, flight recorder)
        sees the commit exactly as if it had been made locally.

        Restamp, not replay-the-peer's-rv: best-effort Event objects are
        the one sanctioned cross-shard write (controller/common.py
        record_event), so two processes can interleave commits on the
        Event shard in different local orders — per-object rv VALUES are
        mirror-local. What every mirror agrees on is the COUNTS: each
        ADDED/MODIFIED apply bumps its shard's sequence by exactly one
        (hard deletes by zero, same as the local paths), so the scalar rv
        and per-shard final rv the serial-twin A/B compares are identical,
        and optimistic-concurrency rv checks never cross a process (each
        shard's non-Event writes happen in exactly one process).

        Returns the WatchEvent for the applied commit. The informer CACHE
        is deliberately NOT advanced here: cache advance is a ROUND
        boundary in the serial drain (route time), so the caller — the
        worker process, which never routes — holds the returned event
        and applies it to the cache when the coordinator's sync watermark
        says its round boundary has passed.
        """
        from grove_tpu.durability.wal import decode_envelope

        obj = decode_envelope(envelope)
        shard = self._shard_of_obj(obj)
        with shard.lock:
            key = obj_key(obj)
            old = shard.committed.get(obj.kind, {}).get(key)
            if etype == DELETED:
                # hard deletes do not bump the shard's rv sequence (they
                # have no new committed state) — mirror that exactly
                if old is None:
                    raise GroveError(
                        ERR_CONFLICT,
                        f"remote delete of unknown {obj.kind} {key}:"
                        " mirror diverged from the committing process",
                        "apply-remote",
                    )
                blob = self._uncommit(shard, old)
                self._emit(DELETED, old, blob, shard=shard)
                return WatchEvent(
                    type=DELETED, kind=old.kind, obj=old, blob=blob,
                    old=None, shard=shard.index,
                )
            shard.rv += 1
            obj.metadata.resource_version = shard.rv
            if old is not None:
                self._index_remove(shard, old)
            blob = self._commit(shard, obj)
            self._emit(etype, obj, blob, old=old, shard=shard)
            return WatchEvent(
                type=etype, kind=obj.kind, obj=obj, blob=blob,
                old=None, shard=shard.index,
            )

    # -- CRUD -----------------------------------------------------------

    def _commit(
        self,
        shard: StoreShard,
        stored,
        blob: Optional[bytes] = None,
        serialize: bool = True,
    ) -> Optional[bytes]:
        """Commit `stored` as the owning shard's new immutable committed
        state + canonical blob. `stored` must never be mutated after this
        call. With serialize=False (copy-on-write commits) no blob is
        computed: later mutable reads fall back to deep_copy."""
        if blob is None and serialize:
            blob = _dumps(stored)
        key = obj_key(stored)
        shard.committed.setdefault(stored.kind, {})[key] = stored
        if blob is not None:
            shard.blob.setdefault(stored.kind, {})[key] = blob
        else:
            shard.blob.get(stored.kind, {}).pop(key, None)
        self._index_add(shard, stored)
        # dict-as-ordered-set: re-commits of an existing key keep its slot
        shard.ns_index.setdefault(stored.kind, {}).setdefault(
            stored.metadata.namespace, {}
        )[key] = None
        return blob

    def verify_readonly_integrity(self) -> int:
        """Test-mode write barrier for the zero-copy readonly contract
        (`scan()` / `get(readonly=True)` / watch payloads): every committed
        object must still match its canonical blob byte-for-byte. A caller
        that mutated a readonly view in place diverges the object from the
        blob — the exact silent-corruption class the zero-copy optimization
        created — and fails HERE with the object named, instead of
        corrupting store state invisibly. O(total blob bytes), so it is
        wired to test harnesses (SimHarness under GROVE_TPU_STORE_GUARD),
        not production paths. Returns the number of objects verified;
        committed objects with no canonical blob (unpicklable — reads fall
        back to deep_copy) cannot be byte-compared and are tallied in
        `self.unverified_readonly` so the coverage gap is visible rather
        than silent."""
        checked = 0
        self.unverified_readonly = 0
        for shard in self._shards:
            checked += self._verify_shard_readonly(shard)
        return checked

    def _verify_shard_readonly(self, shard: StoreShard) -> int:
        checked = 0
        for kind, view in shard.committed.items():
            blobs = shard.blob.get(kind, {})
            for key, obj in view.items():
                blob = blobs.get(key)
                if blob is None:
                    self.unverified_readonly += 1
                    continue
                # byte compare first; pickle is not byte-idempotent for
                # every graph (e.g. an attribute string aliasing the
                # pickled class-name string dumps as a memo BINGET from
                # the caller's object but as a fresh string after loads),
                # so a byte mismatch falls back to structural equality —
                # a mutated readonly view still differs structurally
                if _dumps(obj) != blob and pickle.loads(blob) != obj:
                    raise AssertionError(
                        f"readonly contract violated: committed {kind} {key} "
                        "no longer matches its canonical blob — some caller "
                        "mutated a scan()/get(readonly=True)/watch view in "
                        "place (deep_copy before building updates)"
                    )
                checked += 1
        return checked

    def _uncommit(self, shard: StoreShard, obj) -> Optional[bytes]:
        key = obj_key(obj)
        shard.committed.get(obj.kind, {}).pop(key, None)
        blob = shard.blob.get(obj.kind, {}).pop(key, None)
        self._index_remove(shard, obj)
        ns_map = shard.ns_index.get(obj.kind, {}).get(obj.metadata.namespace)
        if ns_map is not None:
            ns_map.pop(key, None)
            if not ns_map:
                # bound memory: a drained namespace drops its index row
                shard.ns_index[obj.kind].pop(obj.metadata.namespace, None)
        return blob

    def _shard_blobs(
        self, shard: StoreShard, use_cache: bool, kind: str
    ) -> Dict[str, bytes]:
        return (shard.cache_blob if use_cache else shard.blob).get(kind, {})

    def create(self, obj, consume: bool = False, share: bool = False) -> object:
        # wall attribution (observability/profile.py): writes land on the
        # enclosing reconcile's (controller, shard, store-commit) row —
        # lock wait included, that IS part of the commit's wall. Disabled
        # profiling costs this one boolean check.
        prof = PROFILER.phase("store-commit") if PROFILER.enabled else None
        try:
            self._authorize("create", obj)
            self._inject("create", obj)
            shard = self._shard_of_obj(obj)
            with shard.lock:
                return self._create_locked(shard, obj, consume, share)
        finally:
            if prof is not None:
                prof.end()

    def _create_locked(
        self, shard: StoreShard, obj, consume: bool, share: bool
    ) -> object:
        kind_objs = shard.committed.setdefault(obj.kind, {})
        key = obj_key(obj)
        if key in kind_objs:
            raise GroveError(
                ERR_CONFLICT, f"{obj.kind} {key} already exists", "create"
            )
        if consume:
            # ownership-transfer create (fire-and-forget objects like
            # Events): the caller hands the object over and MUST NOT touch
            # it again, so it becomes the committed state directly — no
            # private pickled copy at all
            meta = obj.metadata
            shard.rv += 1
            meta.uid = meta.uid or next_uid()
            meta.resource_version = shard.rv
            meta.generation = 1
            meta.creation_timestamp = self.clock.now()
            blob = _dumps(obj) if self._guard_blobs else None
            self._commit(shard, obj, blob, serialize=False)
            self._emit(ADDED, obj, blob, shard=shard)
            return obj
        if share:
            # structural-sharing create for memoized DESIRED objects
            # (ctx.desired_cache): the committed object is a spine copy
            # sharing spec/status with the caller's template — which is
            # reused read-only across reconciles, so sharing is safe under
            # the committed-object immutability contract. Metadata gets a
            # private copy so identity never leaks back into the memo.
            stored = _copy.copy(obj)
            meta = stored.metadata = _copy.copy(obj.metadata)
            shard.rv += 1
            meta.uid = next_uid()
            meta.resource_version = shard.rv
            meta.generation = 1
            meta.creation_timestamp = self.clock.now()
            blob = _dumps(stored) if self._guard_blobs else None
            self._commit(shard, stored, blob, serialize=False)
            self._emit(ADDED, stored, blob, shard=shard)
            return stored
        # Serialize ONCE with the final identity already stamped: the same
        # bytes are the private committed copy (loads) and the canonical
        # blob (a deep_copy + commit-time dumps would pickle twice; create
        # is a per-pod cost at stress scale). The caller keeps ownership of
        # its argument — its metadata is restored below via the identity
        # copy-back.
        meta = obj.metadata
        saved = (
            meta.uid,
            meta.resource_version,
            meta.generation,
            meta.creation_timestamp,
        )
        shard.rv += 1
        try:
            meta.uid = meta.uid or next_uid()
            meta.resource_version = shard.rv
            meta.generation = 1
            meta.creation_timestamp = self.clock.now()
            blob = _dumps(obj)
            stored = pickle.loads(blob) if blob is not None else deep_copy(obj)
        finally:
            (
                meta.uid,
                meta.resource_version,
                meta.generation,
                meta.creation_timestamp,
            ) = saved
        self._commit(shard, stored, blob)
        self._emit(ADDED, stored, blob, shard=shard)
        # return the CALLER's object carrying the committed identity — its
        # content is what was committed (stored was copied from it), so a
        # fresh materialized copy would only duplicate it
        obj.metadata.uid = stored.metadata.uid
        obj.metadata.resource_version = stored.metadata.resource_version
        obj.metadata.generation = stored.metadata.generation
        obj.metadata.creation_timestamp = stored.metadata.creation_timestamp
        return obj

    def get(
        self,
        kind: str,
        namespace: str,
        name: str,
        cached: bool = False,
        readonly: bool = False,
    ):
        """Fetch one object. `readonly=True` returns the store's committed
        object WITHOUT a copy — the caller MUST NOT mutate it (same contract
        as scan(); re-get mutably before building an update)."""
        # snapshot-phase attribution; the readonly fast path stays a dict
        # hit + one boolean check while profiling is off
        prof = PROFILER.phase("snapshot") if PROFILER.enabled else None
        try:
            use_cache = cached and self.cache_lag
            shard = self._shard_for(namespace)
            key = f"{namespace}/{name}"
            view = (shard.cache if use_cache else shard.committed).get(kind, {})
            obj = view.get(key)
            if obj is None:
                return None
            if readonly:
                return obj
            return _materialize(
                obj, self._shard_blobs(shard, use_cache, kind).get(key)
            )
        finally:
            if prof is not None:
                prof.end()

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        cached: bool = False,
    ) -> List[object]:
        prof = PROFILER.phase("snapshot") if PROFILER.enabled else None
        try:
            use_cache = cached and self.cache_lag
            out = []
            # iterate shard-by-shard so the per-kind blob dict is fetched
            # ONCE per shard, not re-resolved per object (list("Pod") at the
            # 500k-pod shape would otherwise pay ~1M redundant lookups)
            for shard in self._shards_for_read(namespace):
                blobs = self._shard_blobs(shard, use_cache, kind)
                for obj in self._scan_shard(
                    shard, kind, namespace, label_selector, use_cache
                ):
                    out.append(_materialize(obj, blobs.get(obj_key(obj))))
            # cross-shard merge rule: one global (namespace, name) sort —
            # the same total order the unsharded store produced, whatever
            # shard each namespace hashed to
            out.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
            return out
        finally:
            if prof is not None:
                prof.end()

    def _shards_for_read(self, namespace: Optional[str]):
        """Shards a read must consult: the owner for a namespace-scoped
        read, every shard (index order) otherwise."""
        if namespace is None:
            return self._shards
        return (self._shard_for(namespace),)

    def _scan_shard(
        self,
        shard: StoreShard,
        kind: str,
        namespace: Optional[str],
        label_selector: Optional[Dict[str, str]],
        use_cache: bool,
    ) -> Iterator[object]:
        """One shard's slice of a scan (shared by scan()/list())."""
        view = (shard.cache if use_cache else shard.committed).get(kind, {})
        if not view:
            return
        for obj in self._shard_candidates(
            shard, kind, namespace, label_selector, use_cache, view
        ):
            if namespace is not None and obj.metadata.namespace != namespace:
                continue
            if matches_labels(obj, label_selector):
                yield obj

    def scan(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        cached: bool = False,
    ) -> Iterator[object]:
        """Zero-copy read-only iteration over matching objects (unsorted).

        The yielded objects ARE the store's committed state — callers MUST
        NOT mutate them (deep_copy first to build an update). This is the
        informer-cache contract from client-go, and it is what makes the
        hot status/compute scans O(matched) with no serialization cost.

        Sharded: a namespace-scoped scan touches ONLY the owning shard
        (and only that namespace's index row); namespace=None chains the
        shards in index order (within a shard, the historical order).
        """
        use_cache = cached and self.cache_lag
        for shard in self._shards_for_read(namespace):
            yield from self._scan_shard(
                shard, kind, namespace, label_selector, use_cache
            )

    def _require(self, shard: StoreShard, obj):
        kind_objs = shard.committed.get(obj.kind, {})
        key = obj_key(obj)
        if key not in kind_objs:
            raise GroveError(
                ERR_NOT_FOUND, f"{obj.kind} {key} not found", "update"
            )
        return kind_objs, key

    def update(self, obj, bump_generation: bool = True) -> object:
        """Spec write: bumps resourceVersion and (by default) generation.

        Enforces optimistic concurrency like the apiserver: writing from a
        stale read (resource_version behind committed) raises ERR_CONFLICT,
        so controllers that clobber concurrent writes fail in the sim too.
        """
        prof = PROFILER.phase("store-commit") if PROFILER.enabled else None
        try:
            shard = self._shard_of_obj(obj)
            with shard.lock:
                return self._update_locked(shard, obj, bump_generation)
        finally:
            if prof is not None:
                prof.end()

    def _update_locked(
        self, shard: StoreShard, obj, bump_generation: bool
    ) -> object:
        kind_objs, key = self._require(shard, obj)
        current = kind_objs[key]
        self._authorize("update", current)
        self._inject("update", obj)  # injectors see the state being written
        if (
            obj.metadata.resource_version
            and obj.metadata.resource_version != current.metadata.resource_version
        ):
            raise GroveError(
                ERR_CONFLICT,
                f"{obj.kind} {key}: resourceVersion "
                f"{obj.metadata.resource_version} != "
                f"{current.metadata.resource_version}",
                "update",
            )
        # No-op detection by STRUCTURAL equality with `obj`'s metadata
        # bookkeeping normalized to current's — zero serialization on the
        # no-op path (dataclass __eq__ short-circuits at the first real
        # difference on the write path). No-op writes get no version bump
        # and no event — the role the reference's change predicates
        # (GenerationChanged etc.) play in preventing self-triggering
        # reconcile livelock.
        #
        # A real write then pickles ONCE, with the FINAL metadata already
        # in place, so the same bytes serve as both the private committed
        # copy (loads) and the canonical blob — round 4 paid dumps(norm) +
        # loads + dumps(commit) per write; profiling the 10k-set
        # integrated bench put pickle at the top of control-plane cost.
        meta = obj.metadata
        saved = (
            meta.resource_version,
            meta.generation,
            meta.uid,
            meta.creation_timestamp,
        )

        def _return_caller_obj(committed) -> object:
            # hand the CALLER's object back carrying the committed identity
            # (no materialized copy: obj's content is what was committed —
            # or, on a no-op, semantically equal to it). update() requires a
            # caller-OWNED object (never a readonly view), so this is safe.
            meta.resource_version = committed.metadata.resource_version
            meta.generation = committed.metadata.generation
            meta.uid = committed.metadata.uid
            meta.creation_timestamp = committed.metadata.creation_timestamp
            return obj

        try:
            meta.resource_version = current.metadata.resource_version
            meta.generation = current.metadata.generation
            meta.uid = current.metadata.uid
            meta.creation_timestamp = current.metadata.creation_timestamp
            if obj == current:
                return _return_caller_obj(current)
            # real write: stamp the final identity and serialize once
            meta.resource_version = shard.rv + 1
            meta.generation = current.metadata.generation + (
                1 if bump_generation else 0
            )
            blob = _dumps(obj)
            if blob is not None:
                stored = pickle.loads(blob)  # private committed copy
            else:  # unpicklable: fall back to a structural deep copy
                stored = deep_copy(obj)
        finally:
            (
                meta.resource_version,
                meta.generation,
                meta.uid,
                meta.creation_timestamp,
            ) = saved
        shard.rv += 1
        self._index_remove(shard, current)
        self._commit(shard, stored, blob)
        self._emit(MODIFIED, stored, blob, old=current, shard=shard)
        return _return_caller_obj(stored)

    def update_status(self, obj) -> object:
        """Status write: no generation bump (status subresource semantics)."""
        return self.update(obj, bump_generation=False)

    def pod_counters(self, namespace: str, name: str, cached: bool = False):
        """Aggregated pod-status counters for one PodClique — the
        event-driven replacement for scanning+categorizing its pods on
        every reconcile. Always equals a full rescan of the view the caller
        would have scanned (committed, or the lagged cache when
        cached=True). Returned row is READ-ONLY.

        Two-level when sharded: the namespace's OWNING SHARD holds the
        level-1 row (a namespace never straddles shards), so the read is
        shard → row — no structure consulted spans the cluster."""
        shard = self._shard_for(namespace)
        agg = (
            shard.agg_cached
            if (cached and self.cache_lag)
            else shard.agg_committed
        )
        return agg.counters(namespace, name)

    def pod_summary(self, cached: bool = False) -> Tuple[int, int]:
        """Cluster-wide (total, ready) over live (non-terminating) pods —
        the hierarchical replacement for scanning the whole pod
        population: per-shard level-1 partials (folded per watch delta by
        the shard's PodAggregate) are folded up the level-2 summary tree
        (fan-in 8), so no fold at any level sees every pod or even every
        shard. Equivalence vs a flat rescan is pinned in
        tests/test_shards.py; the fold-depth histogram lands in the bench
        `"scale"` block."""
        from grove_tpu.observability.metrics import METRICS

        use_cache = cached and self.cache_lag
        tree = self._summary_tree_cached if use_cache else self._summary_tree
        # drain by atomic pop()s BEFORE reading the aggregates: committers
        # (threaded apiserver writers) add to this set holding only their
        # shard lock, so iterating it live could see a mid-add resize, and
        # clearing after the reads would lose an add that raced the fold.
        # Each GIL-atomic pop either lands in this read (whose aggregate
        # read comes after) or survives for the next one — no lock on the
        # commit path, no lost notification, no shared iteration.
        dirty = (
            self._summary_dirty_cached if use_cache else self._summary_dirty
        )
        drained = []
        while True:
            try:
                drained.append(dirty.pop())
            except KeyError:
                break
        drained.sort()
        if drained:
            if 2 * len(drained) > self.num_shards:
                tree.refold(
                    [
                        (
                            (
                                s.agg_cached if use_cache else s.agg_committed
                            ).grand_total,
                            (
                                s.agg_cached if use_cache else s.agg_committed
                            ).grand_ready,
                        )
                        for s in self._shards
                    ]
                )
            else:
                # few shards moved since the last read (the steady-state
                # common case is ONE): path-refold each dirty leaf's
                # ancestor chain instead of the whole tree
                for i in drained:
                    agg = (
                        self._shards[i].agg_cached
                        if use_cache
                        else self._shards[i].agg_committed
                    )
                    tree.update_leaf(i, (agg.grand_total, agg.grand_ready))
        METRICS.set("aggregate_fold_depth", tree.depth)
        return tree.root()

    def fold_depth_histogram(self) -> List[int]:
        """Nodes per level of the level-2 fold tree, leaves first."""
        return self._summary_tree.fold_depth_histogram()

    def commit_cow(
        self,
        view,
        *,
        status=_UNSET,
        spec=_UNSET,
        metadata=_UNSET,
        bump_generation: bool = False,
    ) -> object:
        """Copy-on-write commit — the write half of the zero-copy read path.

        `view` must be the caller's readonly committed view of the object
        (get(readonly=True)/scan()). The caller supplies PRIVATE replacement
        subtree(s) — typically a status built on a status_shadow, or a
        shallow-cloned spec. The new committed object structurally SHARES
        every untouched field with the previous committed object (both are
        immutable), so no pickling happens at all: this removes the
        _materialize loads + canonical dumps that dominated per-reconcile
        control-plane cost. The returned object is the new committed
        readonly view (same contract as scan()): do not mutate it.

        Semantics match update(): optimistic concurrency (a view whose
        resourceVersion is behind committed raises ERR_CONFLICT), no-op
        suppression (replaced fields equal to committed → no bump, no
        event), authorization + fault injection, MODIFIED event with `old`.
        """
        # status-only COW commits are the reconcile loops' dominant write
        # (phase/condition upkeep) — attribute them to their own
        # `status-write` row so the ISSUE's dequeue→snapshot→diff→commit→
        # status-write decomposition falls out of the report directly
        prof = None
        if PROFILER.enabled:
            only_status = (
                status is not _UNSET
                and spec is _UNSET
                and metadata is _UNSET
            )
            prof = PROFILER.phase(
                "status-write" if only_status else "store-commit"
            )
        try:
            shard = self._shard_of_obj(view)
            with shard.lock:
                return self._commit_cow_locked(
                    shard, view, status, spec, metadata, bump_generation
                )
        finally:
            if prof is not None:
                prof.end()

    def _commit_cow_locked(
        self, shard: StoreShard, view, status, spec, metadata,
        bump_generation: bool,
    ) -> object:
        kind_objs = shard.committed.get(view.kind, {})
        key = obj_key(view)
        current = kind_objs.get(key)
        if current is None:
            raise GroveError(
                ERR_NOT_FOUND, f"{view.kind} {key} not found", "update"
            )
        if (
            current is not view
            and view.metadata.resource_version
            and view.metadata.resource_version != current.metadata.resource_version
        ):
            raise GroveError(
                ERR_CONFLICT,
                f"{view.kind} {key}: resourceVersion "
                f"{view.metadata.resource_version} != "
                f"{current.metadata.resource_version}",
                "update",
            )
        self._authorize("update", current)
        stored = _copy.copy(current)
        changed = False
        if status is not _UNSET:
            stored.status = status
            changed = changed or status != current.status
        if spec is not _UNSET:
            stored.spec = spec
            changed = changed or spec != current.spec
        if metadata is not _UNSET:
            # caller-supplied private metadata clone (e.g. a finalizer add);
            # version/generation bookkeeping is restamped below
            stored.metadata = metadata
            changed = changed or metadata != current.metadata
        self._inject("update", stored)  # injectors see the state being written
        if not changed:
            return current
        meta = stored.metadata = _copy.copy(stored.metadata)
        shard.rv += 1
        meta.resource_version = shard.rv
        if bump_generation:
            meta.generation = current.metadata.generation + 1
        blob = _dumps(stored) if self._guard_blobs else None
        self._index_remove(shard, current)
        self._commit(shard, stored, blob, serialize=False)
        self._emit(MODIFIED, stored, blob, old=current, shard=shard)
        return stored

    def delete(self, kind: str, namespace: str, name: str) -> None:
        prof = PROFILER.phase("store-commit") if PROFILER.enabled else None
        try:
            shard = self._shard_for(namespace)
            with shard.lock:
                self._delete_locked(shard, kind, namespace, name)
        finally:
            if prof is not None:
                prof.end()

    def _delete_locked(
        self, shard: StoreShard, kind: str, namespace: str, name: str
    ) -> None:
        kind_objs = shard.committed.get(kind, {})
        key = f"{namespace}/{name}"
        obj = kind_objs.get(key)
        if obj is None:
            raise GroveError(ERR_NOT_FOUND, f"{kind} {key} not found", "delete")
        self._authorize("delete", obj)
        self._inject("delete", obj)
        if obj.metadata.finalizers:
            if obj.metadata.deletion_timestamp is None:
                # committed objects are immutable: commit a fresh copy with
                # the deletion timestamp instead of mutating in place
                stored = _materialize(obj, shard.blob.get(kind, {}).get(key))
                stored.metadata.deletion_timestamp = self.clock.now()
                shard.rv += 1
                stored.metadata.resource_version = shard.rv
                self._index_remove(shard, obj)
                blob = self._commit(shard, stored)
                self._emit(MODIFIED, stored, blob, old=obj, shard=shard)
            return
        blob = self._uncommit(shard, obj)
        self._emit(DELETED, obj, blob, shard=shard)

    def remove_finalizer(self, kind: str, namespace: str, name: str, finalizer: str) -> None:
        shard = self._shard_for(namespace)
        with shard.lock:
            kind_objs = shard.committed.get(kind, {})
            key = f"{namespace}/{name}"
            obj = kind_objs.get(key)
            if obj is None:
                return
            # finalizer drain is an update-class write: same guard + fault
            # hooks
            self._authorize("update", obj)
            self._inject("update", obj)
            if finalizer in obj.metadata.finalizers:
                stored = _materialize(obj, shard.blob.get(kind, {}).get(key))
                stored.metadata.finalizers.remove(finalizer)
                shard.rv += 1
                stored.metadata.resource_version = shard.rv
                self._index_remove(shard, obj)
                blob = self._commit(shard, stored)
                self._emit(MODIFIED, stored, blob, old=obj, shard=shard)
            self.complete_deletion_if_drained(kind, namespace, name)

    def complete_deletion_if_drained(
        self, kind: str, namespace: str, name: str
    ) -> bool:
        """Finish a finalizer-gated deletion once finalizers have drained —
        the apiserver-side rule the HTTP server applies after updates that
        rewrite metadata.finalizers (a real apiserver deletes the object when
        deletionTimestamp is set and the finalizer list becomes empty)."""
        shard = self._shard_for(namespace)
        with shard.lock:  # reentrant from remove_finalizer (RLock)
            kind_objs = shard.committed.get(kind, {})
            key = f"{namespace}/{name}"
            obj = kind_objs.get(key)
            if (
                obj is not None
                and obj.metadata.deletion_timestamp is not None
                and not obj.metadata.finalizers
            ):
                blob = self._uncommit(shard, obj)
                self._emit(DELETED, obj, blob, shard=shard)
                return True
            return False

    def delete_collection(
        self,
        kind: str,
        namespace: str,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> int:
        """DeleteAllOf equivalent (used by gang termination)."""
        victims = self.list(kind, namespace, label_selector)
        for v in victims:
            self.delete(kind, namespace, v.metadata.name)
        return len(victims)
