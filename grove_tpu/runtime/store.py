"""In-memory object store — the fake apiserver.

Stands in for kube-apiserver + etcd + informer caches (the reference's entire
"communication backend", SURVEY §5). Supports the exact semantics the
controllers rely on:

- resourceVersion bump per write; generation bump on spec updates only
- watch events (Added/Modified/Deleted) fanned out to subscribers
- finalizer-aware deletion (deletion_timestamp first, removal when finalizers
  drain — mirrors apiserver behavior the reference's ensureFinalizer flows use)
- label-selector list
- optional *cache lag*: reads can be served from a stale snapshot that only
  advances when `sync_cache()` is called, reproducing the informer-staleness
  race the reference's expectations store exists to absorb
  (expect/expectations.go:33-50). Tests run the controllers in lagged mode so
  those races can't hide.
"""

from __future__ import annotations

import copy as _copy
import pickle
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional

from grove_tpu.api.meta import deep_copy, next_uid
from grove_tpu.runtime.aggregate import PodAggregate
from grove_tpu.runtime.clock import Clock
from grove_tpu.runtime.errors import (
    ERR_CONFLICT,
    ERR_FORBIDDEN,
    ERR_NOT_FOUND,
    GroveError,
)

ADDED = "Added"
MODIFIED = "Modified"
DELETED = "Deleted"

_UNSET = object()  # commit_cow sentinel: "field not replaced"

# Label keys with inverted indices (the controllers' hot selectors). A
# selector containing any of these resolves to the candidate set instead of
# scanning the whole kind — the control plane's lists go O(matched).
INDEXED_LABELS = (
    "grove.io/podclique",
    "grove.io/podgang",
    "grove.io/podcliquescalinggroup",
    "app.kubernetes.io/part-of",
)


def _dumps(obj) -> Optional[bytes]:
    """Canonical pickled form of a committed object. Computed ONCE per
    write; every read materializes with a single pickle.loads — half the
    cost of a dumps+loads round trip, which profiling shows dominates
    control-plane time. None when the object doesn't pickle (then reads
    fall back to deep_copy)."""
    try:
        return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    except Exception:
        return None


def _materialize(obj, blob: Optional[bytes]):
    return pickle.loads(blob) if blob is not None else deep_copy(obj)


@dataclass
class WatchEvent:
    type: str
    kind: str
    obj: object  # READ-ONLY view shared by all subscribers — never mutate;
    # call materialize() for a private copy
    blob: Optional[bytes] = field(default=None, repr=False, compare=False)
    # previous committed object on MODIFIED events (same read-only
    # contract) — what controller-runtime's UpdateEvent.ObjectOld carries,
    # so watch predicates can gate on actual state TRANSITIONS
    # (reference register.go predicate.Funcs UpdateFunc(old, new))
    old: Optional[object] = field(default=None, repr=False, compare=False)

    def materialize(self):
        """Private deep copy of the event payload (cheap: pre-pickled)."""
        return _materialize(self.obj, self.blob)


def obj_key(obj) -> str:
    return f"{obj.metadata.namespace}/{obj.metadata.name}"


def commit_status(store, view, status):
    """Status write against a readonly `view` via the store's copy-on-write
    path when available (in-memory Store), else the portable mutable
    re-get + update_status cycle (HttpStore). Returns the updated object,
    or None if it disappeared."""
    cow = getattr(store, "commit_cow", None)
    if cow is not None:
        return cow(view, status=status)
    fresh = store.get(view.kind, view.metadata.namespace, view.metadata.name)
    if fresh is None:
        return None
    fresh.status = status
    return store.update_status(fresh)


def commit_finalizer_add(store, view, finalizer: str):
    """Finalizer add (metadata write, no generation bump) against a
    readonly `view` via the copy-on-write path when available. Returns the
    committed object, or None if it disappeared (HttpStore fallback)."""
    cow = getattr(store, "commit_cow", None)
    if cow is not None:
        meta = _copy.copy(view.metadata)
        meta.finalizers = list(view.metadata.finalizers)
        meta.finalizers.append(finalizer)
        return cow(view, metadata=meta)
    fresh = store.get(view.kind, view.metadata.namespace, view.metadata.name)
    if fresh is None:
        return None
    if finalizer not in fresh.metadata.finalizers:
        fresh.metadata.finalizers.append(finalizer)
        return store.update(fresh, bump_generation=False)
    return fresh


def commit_spec(store, view, spec):
    """Spec write (no generation bump) against a readonly `view` via the
    copy-on-write path when available, else mutable re-get + update."""
    cow = getattr(store, "commit_cow", None)
    if cow is not None:
        return cow(view, spec=spec)
    fresh = store.get(view.kind, view.metadata.namespace, view.metadata.name)
    if fresh is None:
        return None
    fresh.spec = spec
    return store.update(fresh, bump_generation=False)


def _index_insert(index: Dict[tuple, set], obj) -> None:
    key = obj_key(obj)
    for lk in INDEXED_LABELS:
        lv = obj.metadata.labels.get(lk)
        if lv is not None:
            index.setdefault((lk, lv), set()).add(key)


def _index_delete(index: Dict[tuple, set], obj) -> None:
    key = obj_key(obj)
    for lk in INDEXED_LABELS:
        lv = obj.metadata.labels.get(lk)
        if lv is not None:
            entries = index.get((lk, lv))
            if entries is not None:
                entries.discard(key)


def _semantically_equal(a, b) -> bool:
    """Deep equality ignoring resourceVersion/generation bookkeeping.
    Swap-compare-restore: no extra deep copies on the hottest write path."""
    saved = (a.metadata.resource_version, a.metadata.generation)
    a.metadata.resource_version = b.metadata.resource_version
    a.metadata.generation = b.metadata.generation
    try:
        return a == b
    finally:
        a.metadata.resource_version, a.metadata.generation = saved


def matches_labels(obj, selector: Optional[Dict[str, str]]) -> bool:
    if not selector:
        return True
    labels = obj.metadata.labels or {}
    # plain loop, not all(genexpr): this runs per candidate per selector on
    # every controller list/scan — the generator frame overhead alone was
    # ~2% of a 2,000-set converge (profiled round 4)
    for k, v in selector.items():
        if labels.get(k) != v:
            return False
    return True


class Store:
    def __init__(self, clock: Optional[Clock] = None, cache_lag: bool = False) -> None:
        self.clock = clock or Clock()
        self.cache_lag = cache_lag
        self._committed: Dict[str, Dict[str, object]] = {}
        self._cache: Dict[str, Dict[str, object]] = {}
        # canonical pickled form per committed/cached object, computed once
        # per write: reads materialize with ONE pickle.loads instead of a
        # dumps+loads round trip (the control plane's hottest path).
        # Committed objects are IMMUTABLE once stored — every write commits
        # a fresh object — so blobs never go stale.
        self._blob: Dict[str, Dict[str, bytes]] = {}
        self._cache_blob: Dict[str, Dict[str, bytes]] = {}
        # kind -> (label_key, label_value) -> set of object keys
        self._index: Dict[str, Dict[tuple, set]] = {}
        self._cache_index: Dict[str, Dict[tuple, set]] = {}
        self._rv = 0
        self._watchers: List[Callable[[WatchEvent], None]] = []
        self._system_watchers: List[Callable[[WatchEvent], None]] = []
        # event-driven status aggregation (runtime/aggregate.py): one
        # counter mirror per READ VIEW — committed (updated at commit time)
        # and, under cache lag, the informer cache (updated exactly when
        # events are applied to it), so pod_counters() always equals a full
        # rescan of the view the caller would have scanned
        self._agg_committed = PodAggregate()
        self._agg_cached = PodAggregate() if cache_lag else self._agg_committed
        # copy-on-write commits skip the canonical pickle blob; under the
        # test-mode store guard (GROVE_TPU_STORE_GUARD, or sanitizer mode
        # GROVE_TPU_SANITIZE which generalizes it) they compute it eagerly
        # anyway so verify_readonly_integrity keeps byte-compare coverage
        from grove_tpu.analysis.sanitize import store_guard_enabled

        self._guard_blobs = store_guard_enabled()
        # optional admission guard (grove_tpu.admission.authorization):
        # writes are checked against the current actor; in-process
        # controllers act as the operator identity
        self.guard = None
        self.actor: Optional[str] = None
        # fault injection (reference test/utils/client.go): map of
        # "create"|"update"|"delete" -> callable(obj) -> Optional[Exception];
        # a returned exception is raised before the write commits
        self.error_injectors: Dict[str, Callable] = {}

    def _inject(self, operation: str, obj) -> None:
        injector = self.error_injectors.get(operation)
        if injector is not None:
            err = injector(obj)
            if err is not None:
                raise err

    @contextmanager
    def as_user(self, username: str):
        """Attribute subsequent writes to `username` (authorization guard)."""
        previous = self.actor
        self.actor = username
        try:
            yield self
        finally:
            self.actor = previous

    def _authorize(self, operation: str, obj) -> None:
        if self.guard is None:
            return
        from grove_tpu.admission.authorization import OPERATOR_USERNAME

        actor = self.actor or OPERATOR_USERNAME
        decision = self.guard.check(actor, operation, obj)
        if not decision.allowed:
            raise GroveError(ERR_FORBIDDEN, decision.reason, operation)

    # -- watch ----------------------------------------------------------

    def subscribe(self, fn: Callable[[WatchEvent], None]) -> None:
        self._watchers.append(fn)

    def subscribe_system(self, fn: Callable[[WatchEvent], None]) -> None:
        """Subscribe a watcher OUTSIDE the operator process (sim kubelet /
        scheduler): operator-restart tests clear `_watchers` to model the
        crashed process's watches vanishing, but cluster-side components
        are separate processes whose watches survive an operator crash."""
        self._system_watchers.append(fn)

    def _emit(
        self, type_: str, obj, blob: Optional[bytes], old: object = None
    ) -> None:
        # zero-copy fanout: committed objects are immutable once stored, so
        # every subscriber may share the payload; WatchEvent.materialize()
        # (pre-pickled) is the escape hatch for watchers that must mutate
        ev = WatchEvent(type=type_, kind=obj.kind, obj=obj, blob=blob, old=old)
        # the committed view just mutated: fold the delta into its aggregate
        # (kind-gated inside; `old` is the previous committed object)
        self._agg_committed.apply(type_, obj, old)
        for w in self._system_watchers:
            w(ev)
        for w in self._watchers:
            w(ev)

    # -- cache ----------------------------------------------------------

    def sync_cache(self) -> None:
        """Advance the whole read cache to the committed state."""
        for kind in self._committed:
            self.sync_cache_kind(kind)

    def sync_cache_kind(self, kind: str) -> None:
        """Advance one kind's cache — models that kind's informer receiving
        its watch events (each informer syncs independently; cross-kind
        staleness is exactly the race expectations absorb). Committed
        objects are immutable, so the cache shares them (no copies)."""
        self._cache[kind] = dict(self._committed.get(kind, {}))
        self._cache_blob[kind] = dict(self._blob.get(kind, {}))
        index: Dict[tuple, set] = {}
        for obj in self._cache[kind].values():
            _index_insert(index, obj)
        self._cache_index[kind] = index
        if kind == "Pod" and self.cache_lag:
            # full resync: the cached aggregate re-derives from the new view
            self._agg_cached.rebuild(self._cache[kind].values())

    def apply_event_to_cache(self, ev: "WatchEvent") -> None:
        """Incrementally apply one delivered watch event to the read cache —
        O(1) informer semantics (sync_cache_kind re-syncs a whole kind and
        is kept for explicit full resyncs). Event payloads are immutable
        (read-only watcher contract), so the cache shares them."""
        kind_cache = self._cache.setdefault(ev.kind, {})
        kind_blob = self._cache_blob.setdefault(ev.kind, {})
        kind_index = self._cache_index.setdefault(ev.kind, {})
        key = obj_key(ev.obj)
        old = kind_cache.get(key)
        if ev.kind == "Pod" and self.cache_lag:
            # the cached view advances exactly here — fold the same delta
            # into its aggregate (old = the view's previous object). Gated
            # on cache_lag: without lag _agg_cached aliases _agg_committed,
            # which already folded this delta at commit time.
            self._agg_cached.apply(ev.type, ev.obj, old)
        if old is not None:
            _index_delete(kind_index, old)
        if ev.type == DELETED:
            kind_cache.pop(key, None)
            kind_blob.pop(key, None)
            return
        kind_cache[key] = ev.obj
        if ev.blob is not None:
            kind_blob[key] = ev.blob
        else:
            kind_blob.pop(key, None)
        _index_insert(kind_index, ev.obj)

    # -- label index ------------------------------------------------------

    def _index_add(self, obj) -> None:
        _index_insert(self._index.setdefault(obj.kind, {}), obj)

    def _index_remove(self, obj) -> None:
        _index_delete(self._index.get(obj.kind, {}), obj)

    def _candidates(
        self,
        kind: str,
        selector: Optional[Dict[str, str]],
        cached: bool,
        view: Dict[str, object],
    ):
        """Smallest indexed candidate set for the selector, else all keys."""
        if selector:
            index = (self._cache_index if cached else self._index).get(kind)
            if index is not None:
                best = None
                for lk in INDEXED_LABELS:
                    if lk in selector:
                        entries = index.get((lk, selector[lk]), set())
                        if best is None or len(entries) < len(best):
                            best = entries
                if best is not None:
                    return [view[k] for k in list(best) if k in view]
        # snapshot of the reference list (not the objects): callers may
        # create/delete while iterating a scan
        return list(view.values())

    def _read_view(self, cached: bool) -> Dict[str, Dict[str, object]]:
        if cached and self.cache_lag:
            return self._cache
        return self._committed

    # -- durability (grove_tpu/durability, docs/robustness.md) -----------

    @property
    def resource_version(self) -> int:
        """Highest resourceVersion committed so far (the WAL/snapshot
        watermark; reads only — writes bump it through commits)."""
        return self._rv

    def kinds(self) -> List[str]:
        """Kinds with at least one committed object (snapshot scans pair
        this with `scan(kind)` to enumerate the whole population)."""
        return sorted(k for k, v in self._committed.items() if v)

    def restore_objects(self, objects, rv: int) -> int:
        """Recovery-path bulk load: commit `objects` VERBATIM — identity
        (uid/resourceVersion/generation/timestamps) preserved, no watch
        events (recovery precedes every subscriber; the boot resync
        machinery — engine.requeue_all, rebuild_bindings, monitor resync —
        covers delivery), aggregates/caches rebuilt, and the version
        counter resumed at `rv` so resourceVersion monotonicity survives
        the restart. Only valid on a store with no prior commits."""
        if self._rv:
            raise GroveError(
                ERR_CONFLICT,
                "restore_objects requires a fresh store (writes already"
                f" committed up to rv {self._rv})",
                "restore",
            )
        n = 0
        for obj in objects:
            self._commit(obj)
            n += 1
        self._rv = max(self._rv, int(rv))
        self._agg_committed.rebuild(
            self._committed.get("Pod", {}).values()
        )
        if self.cache_lag:
            # warm informer caches (the initial LIST a restarted process
            # serves its informers); per-kind sync also rebuilds the
            # cached pod aggregate
            self.sync_cache()
        return n

    # -- CRUD -----------------------------------------------------------

    def _commit(
        self, stored, blob: Optional[bytes] = None, serialize: bool = True
    ) -> Optional[bytes]:
        """Commit `stored` as the new immutable committed state + canonical
        blob. `stored` must never be mutated after this call. With
        serialize=False (copy-on-write commits) no blob is computed: later
        mutable reads fall back to deep_copy."""
        if blob is None and serialize:
            blob = _dumps(stored)
        self._committed.setdefault(stored.kind, {})[obj_key(stored)] = stored
        if blob is not None:
            self._blob.setdefault(stored.kind, {})[obj_key(stored)] = blob
        else:
            self._blob.get(stored.kind, {}).pop(obj_key(stored), None)
        self._index_add(stored)
        return blob

    def verify_readonly_integrity(self) -> int:
        """Test-mode write barrier for the zero-copy readonly contract
        (`scan()` / `get(readonly=True)` / watch payloads): every committed
        object must still match its canonical blob byte-for-byte. A caller
        that mutated a readonly view in place diverges the object from the
        blob — the exact silent-corruption class the zero-copy optimization
        created — and fails HERE with the object named, instead of
        corrupting store state invisibly. O(total blob bytes), so it is
        wired to test harnesses (SimHarness under GROVE_TPU_STORE_GUARD),
        not production paths. Returns the number of objects verified;
        committed objects with no canonical blob (unpicklable — reads fall
        back to deep_copy) cannot be byte-compared and are tallied in
        `self.unverified_readonly` so the coverage gap is visible rather
        than silent."""
        checked = 0
        self.unverified_readonly = 0
        for kind, view in self._committed.items():
            blobs = self._blob.get(kind, {})
            for key, obj in view.items():
                blob = blobs.get(key)
                if blob is None:
                    self.unverified_readonly += 1
                    continue
                # byte compare first; pickle is not byte-idempotent for
                # every graph (e.g. an attribute string aliasing the
                # pickled class-name string dumps as a memo BINGET from
                # the caller's object but as a fresh string after loads),
                # so a byte mismatch falls back to structural equality —
                # a mutated readonly view still differs structurally
                if _dumps(obj) != blob and pickle.loads(blob) != obj:
                    raise AssertionError(
                        f"readonly contract violated: committed {kind} {key} "
                        "no longer matches its canonical blob — some caller "
                        "mutated a scan()/get(readonly=True)/watch view in "
                        "place (deep_copy before building updates)"
                    )
                checked += 1
        return checked

    def _uncommit(self, obj) -> Optional[bytes]:
        key = obj_key(obj)
        self._committed.get(obj.kind, {}).pop(key, None)
        blob = self._blob.get(obj.kind, {}).pop(key, None)
        self._index_remove(obj)
        return blob

    def _blob_view(self, use_cache: bool, kind: str) -> Dict[str, bytes]:
        return (self._cache_blob if use_cache else self._blob).get(kind, {})

    def create(self, obj, consume: bool = False, share: bool = False) -> object:
        self._authorize("create", obj)
        self._inject("create", obj)
        kind_objs = self._committed.setdefault(obj.kind, {})
        key = obj_key(obj)
        if key in kind_objs:
            raise GroveError(
                ERR_CONFLICT, f"{obj.kind} {key} already exists", "create"
            )
        if consume:
            # ownership-transfer create (fire-and-forget objects like
            # Events): the caller hands the object over and MUST NOT touch
            # it again, so it becomes the committed state directly — no
            # private pickled copy at all
            meta = obj.metadata
            self._rv += 1
            meta.uid = meta.uid or next_uid()
            meta.resource_version = self._rv
            meta.generation = 1
            meta.creation_timestamp = self.clock.now()
            blob = _dumps(obj) if self._guard_blobs else None
            self._commit(obj, blob, serialize=False)
            self._emit(ADDED, obj, blob)
            return obj
        if share:
            # structural-sharing create for memoized DESIRED objects
            # (ctx.desired_cache): the committed object is a spine copy
            # sharing spec/status with the caller's template — which is
            # reused read-only across reconciles, so sharing is safe under
            # the committed-object immutability contract. Metadata gets a
            # private copy so identity never leaks back into the memo.
            stored = _copy.copy(obj)
            meta = stored.metadata = _copy.copy(obj.metadata)
            self._rv += 1
            meta.uid = next_uid()
            meta.resource_version = self._rv
            meta.generation = 1
            meta.creation_timestamp = self.clock.now()
            blob = _dumps(stored) if self._guard_blobs else None
            self._commit(stored, blob, serialize=False)
            self._emit(ADDED, stored, blob)
            return stored
        # Serialize ONCE with the final identity already stamped: the same
        # bytes are the private committed copy (loads) and the canonical
        # blob (a deep_copy + commit-time dumps would pickle twice; create
        # is a per-pod cost at stress scale). The caller keeps ownership of
        # its argument — its metadata is restored below via the identity
        # copy-back.
        meta = obj.metadata
        saved = (
            meta.uid,
            meta.resource_version,
            meta.generation,
            meta.creation_timestamp,
        )
        self._rv += 1
        try:
            meta.uid = meta.uid or next_uid()
            meta.resource_version = self._rv
            meta.generation = 1
            meta.creation_timestamp = self.clock.now()
            blob = _dumps(obj)
            stored = pickle.loads(blob) if blob is not None else deep_copy(obj)
        finally:
            (
                meta.uid,
                meta.resource_version,
                meta.generation,
                meta.creation_timestamp,
            ) = saved
        self._commit(stored, blob)
        self._emit(ADDED, stored, blob)
        # return the CALLER's object carrying the committed identity — its
        # content is what was committed (stored was copied from it), so a
        # fresh materialized copy would only duplicate it
        obj.metadata.uid = stored.metadata.uid
        obj.metadata.resource_version = stored.metadata.resource_version
        obj.metadata.generation = stored.metadata.generation
        obj.metadata.creation_timestamp = stored.metadata.creation_timestamp
        return obj

    def get(
        self,
        kind: str,
        namespace: str,
        name: str,
        cached: bool = False,
        readonly: bool = False,
    ):
        """Fetch one object. `readonly=True` returns the store's committed
        object WITHOUT a copy — the caller MUST NOT mutate it (same contract
        as scan(); re-get mutably before building an update)."""
        use_cache = cached and self.cache_lag
        key = f"{namespace}/{name}"
        obj = self._read_view(cached).get(kind, {}).get(key)
        if obj is None:
            return None
        if readonly:
            return obj
        return _materialize(obj, self._blob_view(use_cache, kind).get(key))

    def list(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        cached: bool = False,
    ) -> List[object]:
        use_cache = cached and self.cache_lag
        blobs = self._blob_view(use_cache, kind)
        out = [
            _materialize(obj, blobs.get(obj_key(obj)))
            for obj in self.scan(kind, namespace, label_selector, cached)
        ]
        out.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
        return out

    def scan(
        self,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        cached: bool = False,
    ) -> Iterator[object]:
        """Zero-copy read-only iteration over matching objects (unsorted).

        The yielded objects ARE the store's committed state — callers MUST
        NOT mutate them (deep_copy first to build an update). This is the
        informer-cache contract from client-go, and it is what makes the
        hot status/compute scans O(matched) with no serialization cost.
        """
        use_cache = cached and self.cache_lag
        view = self._read_view(cached).get(kind, {})
        for obj in self._candidates(kind, label_selector, use_cache, view):
            if namespace is not None and obj.metadata.namespace != namespace:
                continue
            if matches_labels(obj, label_selector):
                yield obj

    def _require(self, obj):
        kind_objs = self._committed.get(obj.kind, {})
        key = obj_key(obj)
        if key not in kind_objs:
            raise GroveError(
                ERR_NOT_FOUND, f"{obj.kind} {key} not found", "update"
            )
        return kind_objs, key

    def update(self, obj, bump_generation: bool = True) -> object:
        """Spec write: bumps resourceVersion and (by default) generation.

        Enforces optimistic concurrency like the apiserver: writing from a
        stale read (resource_version behind committed) raises ERR_CONFLICT,
        so controllers that clobber concurrent writes fail in the sim too.
        """
        kind_objs, key = self._require(obj)
        current = kind_objs[key]
        self._authorize("update", current)
        self._inject("update", obj)  # injectors see the state being written
        if (
            obj.metadata.resource_version
            and obj.metadata.resource_version != current.metadata.resource_version
        ):
            raise GroveError(
                ERR_CONFLICT,
                f"{obj.kind} {key}: resourceVersion "
                f"{obj.metadata.resource_version} != "
                f"{current.metadata.resource_version}",
                "update",
            )
        # No-op detection by STRUCTURAL equality with `obj`'s metadata
        # bookkeeping normalized to current's — zero serialization on the
        # no-op path (dataclass __eq__ short-circuits at the first real
        # difference on the write path). No-op writes get no version bump
        # and no event — the role the reference's change predicates
        # (GenerationChanged etc.) play in preventing self-triggering
        # reconcile livelock.
        #
        # A real write then pickles ONCE, with the FINAL metadata already
        # in place, so the same bytes serve as both the private committed
        # copy (loads) and the canonical blob — round 4 paid dumps(norm) +
        # loads + dumps(commit) per write; profiling the 10k-set
        # integrated bench put pickle at the top of control-plane cost.
        meta = obj.metadata
        saved = (
            meta.resource_version,
            meta.generation,
            meta.uid,
            meta.creation_timestamp,
        )

        def _return_caller_obj(committed) -> object:
            # hand the CALLER's object back carrying the committed identity
            # (no materialized copy: obj's content is what was committed —
            # or, on a no-op, semantically equal to it). update() requires a
            # caller-OWNED object (never a readonly view), so this is safe.
            meta.resource_version = committed.metadata.resource_version
            meta.generation = committed.metadata.generation
            meta.uid = committed.metadata.uid
            meta.creation_timestamp = committed.metadata.creation_timestamp
            return obj

        try:
            meta.resource_version = current.metadata.resource_version
            meta.generation = current.metadata.generation
            meta.uid = current.metadata.uid
            meta.creation_timestamp = current.metadata.creation_timestamp
            if obj == current:
                return _return_caller_obj(current)
            # real write: stamp the final identity and serialize once
            meta.resource_version = self._rv + 1
            meta.generation = current.metadata.generation + (
                1 if bump_generation else 0
            )
            blob = _dumps(obj)
            if blob is not None:
                stored = pickle.loads(blob)  # private committed copy
            else:  # unpicklable: fall back to a structural deep copy
                stored = deep_copy(obj)
        finally:
            (
                meta.resource_version,
                meta.generation,
                meta.uid,
                meta.creation_timestamp,
            ) = saved
        self._rv += 1
        self._index_remove(current)
        self._commit(stored, blob)
        self._emit(MODIFIED, stored, blob, old=current)
        return _return_caller_obj(stored)

    def update_status(self, obj) -> object:
        """Status write: no generation bump (status subresource semantics)."""
        return self.update(obj, bump_generation=False)

    def pod_counters(self, namespace: str, name: str, cached: bool = False):
        """Aggregated pod-status counters for one PodClique — the
        event-driven replacement for scanning+categorizing its pods on
        every reconcile. Always equals a full rescan of the view the caller
        would have scanned (committed, or the lagged cache when
        cached=True). Returned row is READ-ONLY."""
        agg = self._agg_cached if (cached and self.cache_lag) else self._agg_committed
        return agg.counters(namespace, name)

    def commit_cow(
        self,
        view,
        *,
        status=_UNSET,
        spec=_UNSET,
        metadata=_UNSET,
        bump_generation: bool = False,
    ) -> object:
        """Copy-on-write commit — the write half of the zero-copy read path.

        `view` must be the caller's readonly committed view of the object
        (get(readonly=True)/scan()). The caller supplies PRIVATE replacement
        subtree(s) — typically a status built on a status_shadow, or a
        shallow-cloned spec. The new committed object structurally SHARES
        every untouched field with the previous committed object (both are
        immutable), so no pickling happens at all: this removes the
        _materialize loads + canonical dumps that dominated per-reconcile
        control-plane cost. The returned object is the new committed
        readonly view (same contract as scan()): do not mutate it.

        Semantics match update(): optimistic concurrency (a view whose
        resourceVersion is behind committed raises ERR_CONFLICT), no-op
        suppression (replaced fields equal to committed → no bump, no
        event), authorization + fault injection, MODIFIED event with `old`.
        """
        kind_objs = self._committed.get(view.kind, {})
        key = obj_key(view)
        current = kind_objs.get(key)
        if current is None:
            raise GroveError(
                ERR_NOT_FOUND, f"{view.kind} {key} not found", "update"
            )
        if (
            current is not view
            and view.metadata.resource_version
            and view.metadata.resource_version != current.metadata.resource_version
        ):
            raise GroveError(
                ERR_CONFLICT,
                f"{view.kind} {key}: resourceVersion "
                f"{view.metadata.resource_version} != "
                f"{current.metadata.resource_version}",
                "update",
            )
        self._authorize("update", current)
        stored = _copy.copy(current)
        changed = False
        if status is not _UNSET:
            stored.status = status
            changed = changed or status != current.status
        if spec is not _UNSET:
            stored.spec = spec
            changed = changed or spec != current.spec
        if metadata is not _UNSET:
            # caller-supplied private metadata clone (e.g. a finalizer add);
            # version/generation bookkeeping is restamped below
            stored.metadata = metadata
            changed = changed or metadata != current.metadata
        self._inject("update", stored)  # injectors see the state being written
        if not changed:
            return current
        meta = stored.metadata = _copy.copy(stored.metadata)
        self._rv += 1
        meta.resource_version = self._rv
        if bump_generation:
            meta.generation = current.metadata.generation + 1
        blob = _dumps(stored) if self._guard_blobs else None
        self._index_remove(current)
        self._commit(stored, blob, serialize=False)
        self._emit(MODIFIED, stored, blob, old=current)
        return stored

    def delete(self, kind: str, namespace: str, name: str) -> None:
        kind_objs = self._committed.get(kind, {})
        key = f"{namespace}/{name}"
        obj = kind_objs.get(key)
        if obj is None:
            raise GroveError(ERR_NOT_FOUND, f"{kind} {key} not found", "delete")
        self._authorize("delete", obj)
        self._inject("delete", obj)
        if obj.metadata.finalizers:
            if obj.metadata.deletion_timestamp is None:
                # committed objects are immutable: commit a fresh copy with
                # the deletion timestamp instead of mutating in place
                stored = _materialize(obj, self._blob.get(kind, {}).get(key))
                stored.metadata.deletion_timestamp = self.clock.now()
                self._rv += 1
                stored.metadata.resource_version = self._rv
                self._index_remove(obj)
                blob = self._commit(stored)
                self._emit(MODIFIED, stored, blob, old=obj)
            return
        blob = self._uncommit(obj)
        self._emit(DELETED, obj, blob)

    def remove_finalizer(self, kind: str, namespace: str, name: str, finalizer: str) -> None:
        kind_objs = self._committed.get(kind, {})
        key = f"{namespace}/{name}"
        obj = kind_objs.get(key)
        if obj is None:
            return
        # finalizer drain is an update-class write: same guard + fault hooks
        self._authorize("update", obj)
        self._inject("update", obj)
        if finalizer in obj.metadata.finalizers:
            stored = _materialize(obj, self._blob.get(kind, {}).get(key))
            stored.metadata.finalizers.remove(finalizer)
            self._rv += 1
            stored.metadata.resource_version = self._rv
            self._index_remove(obj)
            blob = self._commit(stored)
            self._emit(MODIFIED, stored, blob, old=obj)
        self.complete_deletion_if_drained(kind, namespace, name)

    def complete_deletion_if_drained(
        self, kind: str, namespace: str, name: str
    ) -> bool:
        """Finish a finalizer-gated deletion once finalizers have drained —
        the apiserver-side rule the HTTP server applies after updates that
        rewrite metadata.finalizers (a real apiserver deletes the object when
        deletionTimestamp is set and the finalizer list becomes empty)."""
        kind_objs = self._committed.get(kind, {})
        key = f"{namespace}/{name}"
        obj = kind_objs.get(key)
        if (
            obj is not None
            and obj.metadata.deletion_timestamp is not None
            and not obj.metadata.finalizers
        ):
            blob = self._uncommit(obj)
            self._emit(DELETED, obj, blob)
            return True
        return False

    def delete_collection(
        self,
        kind: str,
        namespace: str,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> int:
        """DeleteAllOf equivalent (used by gang termination)."""
        victims = self.list(kind, namespace, label_selector)
        for v in victims:
            self.delete(kind, namespace, v.metadata.name)
        return len(victims)
