"""Create/delete expectations store.

Re-host of /root/reference/operator/internal/expect/expectations.go:33-136.
Compensates for stale informer caches: after issuing creates/deletes, the
controller records the UIDs it expects to (dis)appear; the replica-diff
computation then folds pending expectations in instead of trusting the cache.
Self-heals by syncing against observed state.
"""

from __future__ import annotations

from typing import Dict, Iterable, Set, Tuple


class ExpectationsStore:
    def __init__(self, name: str = "") -> None:
        self.name = name
        self._creates: Dict[str, Set[str]] = {}
        self._deletes: Dict[str, Set[str]] = {}

    # -- record ----------------------------------------------------------

    def expect_creations(self, key: str, uids: Iterable[str]) -> None:
        self._creates.setdefault(key, set()).update(uids)

    def expect_deletions(self, key: str, uids: Iterable[str]) -> None:
        self._deletes.setdefault(key, set()).update(uids)

    # -- observe ---------------------------------------------------------

    def observed_creation(self, key: str, uid: str) -> None:
        self._creates.get(key, set()).discard(uid)

    def observed_deletion(self, key: str, uid: str) -> None:
        self._deletes.get(key, set()).discard(uid)

    # -- query (folded into replica diff) --------------------------------

    def pending(self, key: str, observed_uids: Iterable[str]) -> Tuple[Set[str], Set[str]]:
        """Returns (pending_creates, pending_deletes) after self-healing
        against the observed UID set (SyncExpectations,
        expectations.go:112-136): an expected create already visible is done;
        an expected delete no longer visible is done."""
        observed = set(observed_uids)
        pending_creates = self._creates.get(key, set()) - observed
        self._creates[key] = set(pending_creates)
        pending_deletes = self._deletes.get(key, set()) & observed
        self._deletes[key] = set(pending_deletes)
        return pending_creates, pending_deletes

    def delete_expectations(self, key: str) -> None:
        self._creates.pop(key, None)
        self._deletes.pop(key, None)

    # -- process-boundary shipping (runtime/procworkers.py) ---------------

    def export_key(self, key: str) -> Tuple[list, list]:
        """One key's pending UID sets in canonical (sorted) wire form — a
        worker process ships the entry back after each reconcile so the
        coordinator's store carries the raise/lower into the next drain."""
        return (
            sorted(self._creates.get(key) or ()),
            sorted(self._deletes.get(key) or ()),
        )

    def import_key(self, key: str, creates: Iterable[str], deletes: Iterable[str]) -> None:
        """Adopt a peer process's entry for `key` verbatim (empty both ways
        == no entry; `pending()` treats them identically)."""
        creates = set(creates)
        deletes = set(deletes)
        if creates or deletes:
            self._creates[key] = creates
            self._deletes[key] = deletes
        else:
            self._creates.pop(key, None)
            self._deletes.pop(key, None)
