"""Keyed work queue with dedup, delayed re-adds and per-key backoff.

Single-process, virtual-time equivalent of client-go's rate-limited workqueue
as used by the reference's controllers (manager concurrency model,
controller/manager.go). Items are (kind, namespace, name) keys; a key is
deduped while pending, like the real workqueue.
"""

from __future__ import annotations

import heapq
import itertools
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional, Set, Tuple

Key = Tuple[str, str, str]  # (kind, namespace, name)

BASE_BACKOFF = 0.005
MAX_BACKOFF = 1000.0
# a zero (or negative) requeue delay would make the key ready again within
# the SAME engine drain round — `Engine.drain` freezes `now` per call and
# drains each controller's whole ready set, so the re-add would livelock
# inside one round, bypassing the max_rounds backstop. Floor every delayed
# re-add at a strictly positive epsilon: the key lands in the NEXT drain.
# 1us, NOT something tinier: `now` under the wall Clock is ~1.7e9 where the
# float64 ULP is ~2.4e-7 — an epsilon below that would vanish in the
# addition and resurrect the livelock.
MIN_DELAY = 1e-6


@dataclass(order=True)
class _Delayed:
    ready_at: float
    seq: int
    key: Key


class WorkQueue:
    def __init__(self) -> None:
        self._ready: Deque[Key] = deque()
        self._pending: Set[Key] = set()
        self._delayed: List[_Delayed] = []
        self._seq = itertools.count()
        self._failures: Dict[Key, int] = {}

    def add(self, key: Key) -> None:
        if key not in self._pending:
            self._pending.add(key)
            self._ready.append(key)

    def add_after(self, key: Key, delay: float, now: float) -> None:
        delay = max(delay, MIN_DELAY)
        heapq.heappush(self._delayed, _Delayed(now + delay, next(self._seq), key))

    def add_rate_limited(self, key: Key, now: float) -> None:
        """Exponential per-key backoff (client-go ItemExponentialFailureRateLimiter)."""
        failures = self._failures.get(key, 0)
        delay = min(BASE_BACKOFF * (2**failures), MAX_BACKOFF)
        self._failures[key] = failures + 1
        self.add_after(key, delay, now)

    def forget(self, key: Key) -> None:
        self._failures.pop(key, None)

    def _promote_delayed(self, now: float) -> None:
        while self._delayed and self._delayed[0].ready_at <= now:
            item = heapq.heappop(self._delayed)
            self.add(item.key)

    def pop(self, now: float) -> Optional[Key]:
        self._promote_delayed(now)
        if not self._ready:
            return None
        key = self._ready.popleft()
        self._pending.discard(key)
        return key

    def next_delayed_at(self) -> Optional[float]:
        return self._delayed[0].ready_at if self._delayed else None

    def __len__(self) -> int:
        return len(self._ready)

    def empty(self, now: float) -> bool:
        self._promote_delayed(now)
        return not self._ready
