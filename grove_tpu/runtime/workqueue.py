"""Keyed work queue with dedup, delayed re-adds and per-key backoff.

Single-process, virtual-time equivalent of client-go's rate-limited workqueue
as used by the reference's controllers (manager concurrency model,
controller/manager.go). Items are (kind, namespace, name) keys; a key is
deduped while pending, like the real workqueue.

Sharded mode (``num_shards > 1``, docs/control-plane.md): ready keys are
bucketed by the owning keyspace shard of their namespace
(runtime/shards.py ``shard_of`` — the store's map) and popped round-robin
across non-empty buckets via a rotation pointer, so one shard's hot key —
re-added every round by a crash-looping tenant — cannot starve another
shard's entries (including delayed re-adds, which promote into their
shard's bucket and get their rotation turn). The delayed heap stays
global: it is time-ordered, and promotion is by readiness, not shard.
At ``num_shards=1`` there is one bucket and the pointer is pinned at 0 —
pop order is the historical FIFO, byte-identical.

Single-drainer contract (docs/control-plane.md §5): the rotation pointer
and buckets assume exactly ONE popping thread. Under the parallel
control plane (runtime/workers.py) that thread is the coordinator — it
pops each round's whole batch in this queue's deterministic order and
only then fans the per-shard groups out to their owning workers, so the
pop order (and therefore each shard's reconcile sub-order) is
byte-identical to the serial drain's. Workers never pop; grovelint
GL018 keeps the bucket state private to the owning modules.
"""

from __future__ import annotations

import heapq
import itertools
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional, Set, Tuple

# the rate-limiter curve and its constants live in runtime/backoff.py —
# the one deterministic-jitter policy shared by every retry loop in the
# tree; re-exported here because this was their historical home and
# consumers (and tests) import them from the queue
from grove_tpu.runtime.backoff import (  # noqa: F401  (re-export)
    BASE_BACKOFF,
    JITTER_FRAC,
    MAX_BACKOFF,
    BackoffPolicy,
)
from grove_tpu.runtime.shards import shard_of

Key = Tuple[str, str, str]  # (kind, namespace, name)
# a zero (or negative) requeue delay would make the key ready again within
# the SAME engine drain round — `Engine.drain` freezes `now` per call and
# drains each controller's whole ready set, so the re-add would livelock
# inside one round, bypassing the max_rounds backstop. Floor every delayed
# re-add at a strictly positive epsilon: the key lands in the NEXT drain.
# 1us, NOT something tinier: `now` under the wall Clock is ~1.7e9 where the
# float64 ULP is ~2.4e-7 — an epsilon below that would vanish in the
# addition and resurrect the livelock.
MIN_DELAY = 1e-6


@dataclass(order=True)
class _Delayed:
    ready_at: float
    seq: int
    key: Key


class WorkQueue:
    def __init__(
        self,
        base_backoff: float = BASE_BACKOFF,
        max_backoff: float = MAX_BACKOFF,
        num_shards: int = 1,
    ) -> None:
        # per-instance rate-limiter curve: reconcile queues keep the
        # client-go-style 5ms base, while coarser consumers (gang requeue
        # after node failure) pick a second-scale base with a tighter cap
        self.policy = BackoffPolicy(base=base_backoff, cap=max_backoff)
        self.num_shards = max(1, num_shards)
        # per-shard ready buckets + rotation pointer (module docstring);
        # one bucket at num_shards=1 keeps the historical FIFO exactly
        self._buckets: List[Deque[Key]] = [
            deque() for _ in range(self.num_shards)
        ]
        self._rotation = 0
        # namespace -> bucket memo (the keyspace map is immutable per
        # queue; crc32 per add is measurable at stress volume)
        self._bucket_memo: Dict[str, Deque[Key]] = {}
        self._ready_count = 0
        self._pending: Set[Key] = set()
        self._delayed: List[_Delayed] = []
        self._seq = itertools.count()
        self._failures: Dict[Key, int] = {}

    @property
    def base_backoff(self) -> float:
        return self.policy.base

    @property
    def max_backoff(self) -> float:
        return self.policy.cap

    def _bucket_of(self, key: Key) -> Deque[Key]:
        if self.num_shards == 1:
            return self._buckets[0]
        # key[1] is the namespace — the same keyspace map the store routes
        # writes with, so a shard's reconcile traffic is exactly its slice
        bucket = self._bucket_memo.get(key[1])
        if bucket is None:
            bucket = self._buckets[shard_of(key[1], self.num_shards)]
            self._bucket_memo[key[1]] = bucket
        return bucket

    def add(self, key: Key) -> None:
        if key not in self._pending:
            self._pending.add(key)
            self._bucket_of(key).append(key)
            self._ready_count += 1

    def add_after(self, key: Key, delay: float, now: float) -> None:
        delay = max(delay, MIN_DELAY)
        heapq.heappush(self._delayed, _Delayed(now + delay, next(self._seq), key))

    def add_rate_limited(self, key: Key, now: float) -> None:
        """Exponential per-key backoff with deterministic jitter, capped at
        MAX_BACKOFF (client-go ItemExponentialFailureRateLimiter + the
        bucket limiter's ceiling). The curve is runtime/backoff.py's
        BackoffPolicy — byte-identical to the formula that used to live
        inline here (tests/test_runtime.py pins the A/B)."""
        failures = self._failures.get(key, 0)
        delay = self.policy.delay(key, failures)
        self._failures[key] = failures + 1
        self.add_after(key, delay, now)

    def failures(self, key: Key) -> int:
        """Consecutive rate-limited failures recorded for the key."""
        return self._failures.get(key, 0)

    def forget(self, key: Key) -> None:
        self._failures.pop(key, None)

    def discard_delayed(self, key: Key) -> int:
        """Drop every not-yet-promoted delayed entry for `key` (O(delayed)).
        For consumers that release a key out of band (e.g. the node-health
        monitor when capacity returns): an orphaned heap entry would later
        pop and grant the key an extra, unscheduled release."""
        before = len(self._delayed)
        self._delayed = [d for d in self._delayed if d.key != key]
        if len(self._delayed) != before:
            heapq.heapify(self._delayed)
        return before - len(self._delayed)

    def _promote_delayed(self, now: float) -> None:
        while self._delayed and self._delayed[0].ready_at <= now:
            item = heapq.heappop(self._delayed)
            self.add(item.key)

    def pop(self, now: float) -> Optional[Key]:
        """Next ready key: FIFO within a shard bucket, deterministic
        round-robin across buckets (the pointer advances past each served
        shard, so consecutive pops rotate shards while any other bucket
        has work — the per-shard fairness pin in tests/test_runtime.py)."""
        self._promote_delayed(now)
        if not self._ready_count:
            return None
        for off in range(self.num_shards):
            idx = (self._rotation + off) % self.num_shards
            bucket = self._buckets[idx]
            if bucket:
                key = bucket.popleft()
                self._pending.discard(key)
                self._ready_count -= 1
                self._rotation = (idx + 1) % self.num_shards
                return key
        return None

    def next_delayed_at(self) -> Optional[float]:
        return self._delayed[0].ready_at if self._delayed else None

    def has_delayed(self, key: Key) -> bool:
        """True while a not-yet-promoted delayed entry exists for the key —
        the chaos harness asserts every monitor-held gang keeps one (a hold
        with no scheduled release would be stranded forever)."""
        return any(d.key == key for d in self._delayed)

    def __len__(self) -> int:
        return self._ready_count

    def empty(self, now: float) -> bool:
        self._promote_delayed(now)
        return not self._ready_count
