"""One deterministic-jitter backoff policy for every retry loop.

Three hand-rolled retry curves grew in this tree before this module
existed: the workqueue's rate limiter (client-go
ItemExponentialFailureRateLimiter shape), the node-health monitor's
requeue backoff (a WorkQueue with a second-scale base), and the
procworkers ``_recv`` poll/deadline loop. They are now all expressed as
a :class:`BackoffPolicy` — same formula, same constants, byte-identical
delays at the old defaults (tests/test_runtime.py pins the A/B).

delay(key, failures) = min(base · 2^failures · (1 + J·u), cap)

where u ∈ [0, 1) is a crc32 of ``f"{key}:{failures}"`` — DETERMINISTIC
per (key, failures): crc32, not random or hash(), so virtual-time
replays and cross-process runs (PYTHONHASHSEED) see identical
schedules. J < 1.0 keeps growth strictly monotone in ``failures``: the
worst case 2^f·(1+J) vs 2^(f+1)·1 still grows since 1+J < 2.
"""

from __future__ import annotations

import zlib

# client-go-style 5ms reconcile base; coarser consumers (gang requeue
# after node failure) pick a second-scale base with a tighter cap
BASE_BACKOFF = 0.005
# HARD cap on the delay, applied AFTER jitter: no key ever waits longer
# than this between retries, however many times it failed
# (tests/test_runtime.py pins the cap and the monotone growth toward it)
MAX_BACKOFF = 1000.0
# multiplicative jitter span on the exponential backoff: many keys
# failing in the same instant (a node loss requeueing every affected
# gang, a store outage failing a whole drain round) must not retry in
# one synchronized burst
JITTER_FRAC = 0.1


class BackoffPolicy:
    """Deterministic-jitter exponential backoff curve.

    Stateless with respect to failure counts — callers own their own
    failure bookkeeping (the workqueue's per-key dict, a retransmit
    loop's attempt counter) and ask the policy only for the delay. That
    keeps one instance shareable across keys and threads with no locks.
    """

    def __init__(
        self,
        base: float = BASE_BACKOFF,
        cap: float = MAX_BACKOFF,
        jitter_frac: float = JITTER_FRAC,
    ) -> None:
        self.base = base
        self.cap = cap
        self.jitter_frac = jitter_frac

    def jitter_u(self, key, failures: int) -> float:
        """The deterministic jitter draw u ∈ [0, 1) for (key, failures).

        ``key`` is formatted with ``f"{key}:..."`` — tuples keep their
        repr, so WorkQueue keys hash to the exact same token bytes the
        inline formula produced (the byte-identical A/B pin).
        """
        return (
            zlib.crc32(f"{key}:{failures}".encode()) & 0xFFFF
        ) / float(1 << 16)

    def delay(self, key, failures: int) -> float:
        """Backoff delay for the ``failures``-th consecutive failure of
        ``key`` (0-based: the first failure gets roughly ``base``)."""
        return min(
            self.base * (2**failures) * (1.0 + self.jitter_frac * self.jitter_u(key, failures)),
            self.cap,
        )
