"""Concurrent control-plane executor: per-shard reconcile workers.

PR 10's scale artifact made the wall unambiguous — at 100k nodes /
500k pods the solver is 4.97 s of a 985 s converge while the
single-threaded control plane burns 964 s (~1,028 µs/reconcile). PR 9's
negative result says keyspace sharding bought per-shard locks, WALs and
HOL isolation, *not* single-thread speed. This module cashes that
isolation in: the S store shards become the ownership boundaries of N
concurrent reconcile workers (docs/control-plane.md §5).

Ownership boundaries
--------------------

``worker_of(shard) = shard % workers``. Worker 0 IS the coordinator
thread — cluster-scoped shard 0 therefore always reconciles on the
coordination plane, which also runs everything that must stay
single-threaded: event routing, workqueue pops, completion bookkeeping,
the scheduler/solver, component ticks and WAL pumps. A worker owns, for
each of its shards: the shard's event backlog (drained only via the
coordinator's deterministic round-robin — see below), the shard's
workqueue buckets' keys, the reconcile bodies for those keys, and the
shard's WAL stream (fed from the per-shard watch fan-out by the
worker's own commits; flushed by the coordinator's pump at tick
boundaries).

Determinism (the serial-twin contract, sim/parallel.py)
-------------------------------------------------------

The parallel drain reproduces the serial drain's schedule EXACTLY,
shard by shard:

1. Event routing and workqueue pops run ONLY on the coordinator, using
   the same rotation pointers as the serial drain — so each round's
   batch (per controller) is byte-identical to what the serial drain
   would pop. ``Engine._route_events`` asserts single-drainer ownership
   (the rotation pointer assumes one drainer; that is now a checked
   contract, not an accident).
2. The batch is partitioned by owning shard, order-preserving, and each
   worker executes its sub-sequence in order. Within a shard, the
   reconcile order therefore equals the serial drain's per-shard
   projection; reconciles of ONE shard only write to that shard (plus
   best-effort Event objects — see the audit in docs/control-plane.md
   §5), so each shard's commit order, rv sequence, watch stream and WAL
   record stream are identical to the serial run's.
3. Order-sensitive CROSS-shard consumers (the delta-solve state and the
   quota accountant, registered via ``subscribe_system_per_shard``) are
   not fed live from worker threads: the store captures their
   deliveries per reconcile (``Store.arm_deferred_fanout``), and the
   coordinator replays the per-reconcile groups in batch order — which
   is exactly the serial drain's global delivery order (each serial
   reconcile's commits form a contiguous group in pop order).
4. Completion bookkeeping (requeue/backoff/forget) runs on the
   coordinator in batch order with the round's frozen ``now`` — the
   serial semantics verbatim.

``sim/parallel.py`` pins the contract end-to-end: the same event
schedule through the serial drain and the worker drain must produce
identical admissions, reconcile counts, store content and per-shard WAL
acked prefixes (``parallel_selfcheck``; ``make parallel-smoke``).

Threads vs processes
--------------------

Workers are threads. On free-threaded builds (and for the C-heavy
slices of the reconcile path — pickling, fsync, numpy — even under the
GIL) they overlap for real; on GIL builds the drain stays correct and
deterministic with bounded overhead, which is what the worker-count
sweep in ``make parallel-smoke`` reports honestly. The worker-PROCESS
backend (runtime/procworkers.py, GROVE_TPU_CP_BACKEND=process) shares
this module's ownership map and coordination points and crosses its
boundary only through the wire codec — the thread executor here is the
semantic contract both backends meet, pinned by the serial-twin A/B at
both backend settings.

Worker-pool internals are PRIVATE to runtime/ (grovelint GL018
``worker-affinity``): per-shard state may only be touched from its
owning worker context or at the documented coordination points.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List

from grove_tpu.observability.metrics import METRICS
from grove_tpu.observability.tracing import TRACER


def workers_from_env() -> int:
    """The opt-in knob: GROVE_TPU_CP_WORKERS=N (0/1/unset = serial)."""
    try:
        return int(os.environ.get("GROVE_TPU_CP_WORKERS", "1") or 1)
    except ValueError:
        return 1


class ParallelDrain:
    """Worker-thread drain for one Engine (docs/control-plane.md §5).

    Built by ``Engine.enable_workers(n)``; owns the worker pool and the
    shard → worker map. The engine's ``drain()`` delegates here when
    armed. Lifetime: the pool is engine-lifetime (``close()`` releases
    it with ``Engine.close()``)."""

    backend = "thread"

    def __init__(self, engine, workers: int) -> None:
        self.engine = engine
        # clamp to the shard count: `worker_of = shard % W` can never
        # route work to workers beyond S, so extra threads would sit
        # idle forever while gauges/sweep rows report a fiction
        self.workers = max(2, min(int(workers), engine.num_shards))
        # worker 0 is the coordinator thread itself; the pool holds the
        # other W-1 workers
        self._pool = ThreadPoolExecutor(
            max_workers=self.workers - 1, thread_name_prefix="cp-worker"
        )
        # lifetime counters (the bench "scale"/parallel blocks)
        self.reconciles_by_worker = [0] * self.workers
        self._worker_busy_s = [0.0] * self.workers
        METRICS.set("cp_workers", self.workers)
        METRICS.set("cp_backend_process", 0)

    # -- ownership map ---------------------------------------------------

    def worker_of(self, shard: int) -> int:
        """Owning worker of a keyspace shard. Shard 0 (cluster-scoped
        keys) maps to worker 0 — the coordination plane."""
        if shard < 0:
            return 0
        return shard % self.workers

    def busy_snapshot(self) -> List[float]:
        """Copy of the per-worker busy-second accumulators — callers that
        measure a WINDOW (the glassbox converge, whose attribution
        cross-check covers converge only) snapshot before and diff after,
        instead of dividing lifetime busy by a window wall."""
        return list(self._worker_busy_s)

    def utilization(
        self, wall_seconds: float, since: List[float] = None
    ) -> List[float]:
        """Per-worker busy share of a measured wall (the bench's
        per-worker utilization rows; >1.0 impossible per worker, but the
        SUM exceeding 1.0 is exactly the parallelism win). ``since``:
        a `busy_snapshot()` taken at the window start."""
        if wall_seconds <= 0:
            return [0.0] * self.workers
        base = since or [0.0] * self.workers
        return [
            round((b - b0) / wall_seconds, 4)
            for b, b0 in zip(self._worker_busy_s, base)
        ]

    def close(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)

    # -- drive -----------------------------------------------------------

    def drain(self, max_rounds: int) -> int:
        """The parallel drain IS the engine's shared round loop
        (``Engine._drain_rounds``: route → pop in deterministic order →
        execute → gauges → quiesce) with this executor substituted for
        the serial per-key loop — one loop implementation, so the serial
        and parallel drains cannot structurally drift."""
        return self.engine._drain_rounds(
            max_rounds, execute_batch=self._run_batch
        )

    def _run_batch(self, ctrl, batch: List, now: float) -> None:
        """One controller's round batch: partition by owning worker
        (order-preserving), execute groups concurrently, then do the
        completion bookkeeping and the deferred-consumer replay on the
        coordinator in batch order — the serial drain's order."""
        eng = self.engine
        groups: Dict[int, List] = {}
        for key in batch:
            w = self.worker_of(eng._shard_of_key(key))
            groups.setdefault(w, []).append(key)
        futures = {
            w: self._pool.submit(self._run_group, ctrl, keys, w)
            for w, keys in groups.items()
            if w != 0
        }
        outcomes: Dict[tuple, tuple] = {}
        if 0 in groups:
            # the coordinator IS worker 0 (shard 0's coordination plane)
            outcomes.update(self._run_group(ctrl, groups[0], 0))
        for fut in futures.values():
            outcomes.update(fut.result())
        # coordination point: bookkeeping + replay in serial batch order
        deferred = []
        for key in batch:
            result, error, captured = outcomes[key]
            eng._complete(ctrl, key, result, error, now)
            if captured:
                deferred.extend(captured)
        for fn, ev in deferred:
            fn(ev)

    def _run_group(self, ctrl, keys: List, worker: int) -> Dict[tuple, tuple]:
        """One worker's sub-sequence of the batch, in batch order.
        Returns key -> (result, error, captured deferred deliveries)."""
        import time as _time

        eng = self.engine
        store = eng.store
        t0 = _time.perf_counter()
        if TRACER.enabled:
            TRACER.set_worker(worker)
        out: Dict[tuple, tuple] = {}
        try:
            for key in keys:
                buf = store.begin_deferred_capture()
                result = error = None
                try:
                    result = eng._timed(ctrl, key)
                except Exception as e:  # RecoverPanic parity with _complete
                    error = e
                finally:
                    captured = store.end_deferred_capture(buf)
                out[key] = (result, error, captured)
        finally:
            if TRACER.enabled:
                TRACER.set_worker(None)
            busy = _time.perf_counter() - t0
            self._worker_busy_s[worker] += busy
            self.reconciles_by_worker[worker] += len(keys)
            METRICS.inc(f"cp_worker_reconciles@{worker}", len(keys))
        return out

    # -- reporting -------------------------------------------------------

    def stats(self) -> dict:
        """Lifetime counters (the bench/smoke "parallel" block)."""
        return {
            "backend": self.backend,
            "workers": self.workers,
            "reconciles_by_worker": list(self.reconciles_by_worker),
            "busy_seconds_by_worker": [
                round(b, 3) for b in self._worker_busy_s
            ],
        }
