"""Controller engine: watch → map → keyed workqueue → reconcile.

Single-threaded, virtual-time re-host of the controller-runtime manager the
reference builds in controller/manager.go + the per-controller watch wiring in
each register.go. Determinism is a feature: the 10k-gang stress sim and every
timing test replay identically. Concurrency hazards the reference absorbs with
its expectations store are reproduced via the store's cache-lag mode rather
than threads.

A Controller owns:
- a primary kind (reconciled on its own events)
- watch mappings: (watched kind, map_fn(event) -> [primary keys]) — the
  equivalent of handler.EnqueueRequestsFromMapFunc + predicates
  (e.g. podclique/register.go:49-80, :242-278).

Reconcile functions return a ReconcileStepResult; "requeue" gets exponential
backoff, "requeue_after" a fixed delay — matching the ReconcileStepResult DSL
semantics in common/flow.go.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

from grove_tpu.observability.flightrec import FLIGHTREC
from grove_tpu.observability.metrics import METRICS
from grove_tpu.observability.profile import NO_SHARD, PROFILER
from grove_tpu.observability.tracing import TRACER
from grove_tpu.runtime.clock import Clock
from grove_tpu.runtime.errors import GroveError
from grove_tpu.runtime.flow import ReconcileStepResult
from grove_tpu.runtime.store import Store, WatchEvent
from grove_tpu.runtime.workqueue import Key, WorkQueue

MapFn = Callable[[WatchEvent], List[Tuple[str, str]]]  # -> [(namespace, name)]
PredicateFn = Callable[[WatchEvent], bool]
ReconcileFn = Callable[[Key], ReconcileStepResult]


@dataclass
class Controller:
    name: str
    kind: str
    reconcile: ReconcileFn
    # watch entries: (kind, map_fn) or (kind, map_fn, predicate) — the
    # predicate is controller-runtime's builder.WithPredicates: an event it
    # rejects never reaches the map fn (reference register.go:100-171
    # predicate.Funcs). Without one, every event of the kind enqueues.
    watches: List[tuple] = field(default_factory=list)
    # predicate on the PRIMARY kind's own events (For(..., WithPredicates)),
    # e.g. GenerationChangedPredicate so self-inflicted status writes don't
    # re-enqueue the owner (podcliqueset/register.go:53)
    primary_predicate: Optional[PredicateFn] = None
    queue: WorkQueue = field(default_factory=WorkQueue)
    # ConcurrentSyncs equivalent: keys processed per engine round. In the
    # default single-threaded drain this is batching; drain_concurrent runs
    # this many reconciles of the controller in REAL parallel threads
    # (same-key never concurrent — client-go workqueue semantics).
    concurrent_syncs: int = 1
    # keys currently being reconciled by a worker thread (drain_concurrent)
    busy: set = field(default_factory=set)
    # batched-drain hook: called once per drain round with the controller's
    # COALESCED ready keys before any of them reconciles — reconcilers use
    # it to build per-batch state (one component build / informer-frozen
    # memo) served to every key of the round instead of rebuilt per key
    batch_hook: Optional[Callable[[List[Key]], None]] = None


class Engine:
    def __init__(self, store: Store, clock: Optional[Clock] = None) -> None:
        from collections import deque

        self.store = store
        self.clock = clock or store.clock
        self.controllers: List[Controller] = []
        # keyspace sharding (runtime/shards.py, docs/control-plane.md):
        # one event backlog per store shard, routed on WatchEvent.shard,
        # drained deterministic-round-robin so one busy tenant's shard
        # cannot head-of-line-block the others' reconcile traffic. At S=1
        # (non-sharded stores, HttpStore) there is ONE backlog and the
        # subscription appends to it directly — the historical layout.
        self.num_shards = max(1, getattr(store, "num_shards", 1))
        # deque + popleft-drain: watch THREADS append concurrently in
        # cluster mode, and a list snapshot-then-clear would silently drop
        # events appended in between (deque.append/popleft are atomic)
        self._backlogs = [deque() for _ in range(self.num_shards)]
        self._event_backlog = self._backlogs[0]  # S=1 alias (tests poke it)
        self._backlog_rotation = 0
        self.held_kinds: set = set()
        self._pool = None  # lazy engine-lifetime reconcile thread pool
        # single-drainer contract (docs/control-plane.md §5): the event
        # routing + workqueue rotation pointers assume exactly ONE thread
        # drains at a time — under the parallel control plane that thread
        # is the coordinator. The non-blocking lock turns a concurrent
        # second drainer from silent pointer corruption into a loud error.
        self._router_lock = threading.Lock()
        # parallel control plane (runtime/workers.py, opt-in via
        # GROVE_TPU_CP_WORKERS=N): per-shard reconcile workers; None keeps
        # the historical single-threaded drain byte-identically
        self.workers = None
        # scheduler overlap pump (runtime/procworkers.py + sim/scheduler):
        # the process drain calls this between dispatching a round's
        # remote batches and collecting replies — the coordinator spends
        # worker flight time on speculative gang encode instead of idling
        self.overlap_hook = None
        # round-boundary callback for the process drain's cache watermark
        # (see _drain_rounds)
        self.round_hook = None
        # per-kind routing table (built lazily after registration): an event
        # consults only the entries subscribed to its kind instead of
        # iterating every controller × watch per event — at stress scale
        # (hundreds of thousands of events) the miss checks dominated
        # _route_events
        self._dispatch = None
        # shard attribution for the glass-box layer: key namespace -> owning
        # shard (the in-memory Store's crc32 memo; HttpStore has none —
        # reconciles there attribute to NO_SHARD)
        self._shard_index = getattr(store, "shard_index", None)
        if self.num_shards == 1:
            store.subscribe(self._event_backlog.append)
        else:
            store.subscribe(self._enqueue_sharded)
        # opt-in concurrent drain: honored only when the store is sharded
        # (the shard IS the ownership boundary) and supports the deferred
        # fan-out capture (in-memory Store; HttpStore keeps
        # drain_concurrent as its threading model)
        from grove_tpu.runtime.workers import workers_from_env

        env_workers = workers_from_env()
        if env_workers > 1:
            self.enable_workers(env_workers)

    def enable_workers(self, workers: int, backend: str = None) -> bool:
        """Arm the parallel control plane (docs/control-plane.md §5):
        `drain()` partitions each round's batches over per-shard worker
        groups. `backend` picks the executor — "thread"
        (runtime/workers.py, the default) or "process"
        (runtime/procworkers.py, shared-nothing worker processes over the
        wire codec); unset falls back to GROVE_TPU_CP_BACKEND. No-op
        (False) when the store is unsharded or cannot defer its per-shard
        fan-out — the serial drain is the degenerate W=1 case either
        way."""
        if workers <= 1 or self.workers is not None:
            return self.workers is not None
        if self.num_shards <= 1:
            return False
        if getattr(self.store, "arm_deferred_fanout", None) is None:
            return False
        if backend is None:
            from grove_tpu.runtime.procworkers import backend_from_env

            backend = backend_from_env()
        self.store.arm_deferred_fanout()
        if backend == "process":
            from grove_tpu.runtime.procworkers import ProcessDrain

            self.workers = ProcessDrain(self, workers)
        else:
            from grove_tpu.runtime.workers import ParallelDrain

            self.workers = ParallelDrain(self, workers)
        return True

    def _enqueue_sharded(self, ev: WatchEvent) -> None:
        # WatchEvent.shard is stamped by the store's _emit — no re-hash
        self._backlogs[ev.shard].append(ev)

    def register(self, controller: Controller) -> None:
        if self.num_shards > 1 and controller.queue.num_shards == 1:
            # give the controller a shard-bucketed ready set (same backoff
            # curve) so one shard's hot keys round-robin against the rest;
            # registration happens before any traffic, so nothing to carry
            controller.queue = WorkQueue(
                base_backoff=controller.queue.base_backoff,
                max_backoff=controller.queue.max_backoff,
                num_shards=self.num_shards,
            )
        self.controllers.append(controller)
        self._dispatch = None  # rebuilt on next routing

    def _build_dispatch(self):
        """kind -> [(ctrl, map_fn, predicate, metric_name)] in registration
        order (primary entry first per controller, map_fn=None), matching
        the original iteration order exactly."""
        dispatch: dict = {}
        for ctrl in self.controllers:
            dispatch.setdefault(ctrl.kind, []).append(
                (ctrl, None, None, f"events_enqueued/{ctrl.name}/self")
            )
            for watch in ctrl.watches:
                watched_kind, map_fn = watch[0], watch[1]
                pred = watch[2] if len(watch) > 2 else None
                dispatch.setdefault(watched_kind, []).append(
                    (
                        ctrl,
                        map_fn,
                        pred,
                        f"events_enqueued/{ctrl.name}/{watched_kind}",
                    )
                )
        self._dispatch = dispatch
        return dispatch

    # -- event delivery --------------------------------------------------

    def hold_events(self, kind: str) -> None:
        """Delay delivery of a kind's watch events (that kind's informer
        'falls behind') — used by tests to surface staleness races."""
        self.held_kinds.add(kind)

    def release_events(self, kind: str) -> None:
        self.held_kinds.discard(kind)

    def discard_pending_events(self) -> int:
        """Drop undelivered watch events. A leader-election STANDBY never
        drains, so its backlog would grow without bound; standbys drop and
        the fresh leader does a full `requeue_all` resync instead."""
        n = 0
        for backlog in self._backlogs:
            while True:
                try:
                    backlog.popleft()
                except IndexError:
                    break
                n += 1
        return n

    def requeue_all(self) -> None:
        """Enqueue every live object of every controller's kind — the
        informer ListAndWatch-restart equivalent a fresh leader runs to
        cover whatever events were dropped while it stood by."""
        for ctrl in self.controllers:
            for obj in self.store.scan(ctrl.kind):
                ctrl.queue.add(
                    (ctrl.kind, obj.metadata.namespace, obj.metadata.name)
                )

    def _next_event(self) -> Optional[WatchEvent]:
        """Pop the next backlog event. S=1: plain popleft. Sharded:
        deterministic round-robin over the per-shard backlogs — the
        rotation pointer advances past each served shard, so every
        non-empty shard gets a turn per cycle (seeded-reproducible under
        the sim's virtual clock: the schedule depends only on event
        arrival order, never on wall time or hashing)."""
        if self.num_shards == 1:
            try:
                return self._event_backlog.popleft()
            except IndexError:
                return None
        for off in range(self.num_shards):
            idx = (self._backlog_rotation + off) % self.num_shards
            try:
                ev = self._backlogs[idx].popleft()
            except IndexError:
                continue
            self._backlog_rotation = (idx + 1) % self.num_shards
            return ev
        return None

    def _route_events(self) -> None:
        # single-drainer contract: the backlog rotation pointer and the
        # workqueue rotation pointers advance under exactly one routing
        # thread at a time (the serial drainer, or the parallel drain's
        # coordinator). A second concurrent drainer would silently corrupt
        # the deterministic round-robin the serial-twin A/B compares
        # against — fail loudly instead (pinned in tests/test_workers.py).
        if not self._router_lock.acquire(blocking=False):
            raise RuntimeError(
                "concurrent event routing: the engine's rotation pointers"
                " assume a single drainer (docs/control-plane.md §5) —"
                " route/drain only from the coordination plane"
            )
        # disabled profiling costs exactly this one boolean check per round
        prof = (
            PROFILER.phase("dequeue", controller="engine")
            if PROFILER.enabled
            else None
        )
        try:
            self._route_events_inner()
        finally:
            if prof is not None:
                prof.end()
            self._router_lock.release()

    def _route_events_inner(self) -> None:
        # Drain via popleft until empty: reconciles (and concurrent watch
        # threads) emit new events while we iterate; popping one at a time
        # can never lose a concurrent append.
        remaining: List[WatchEvent] = []
        while True:
            ev = self._next_event()
            if ev is None:
                break
            if ev.kind in self.held_kinds:
                remaining.append(ev)
                continue
            # a kind's cache advances exactly when its events are delivered
            # (incremental informer application); held kinds stay stale
            if self.store.cache_lag:
                self.store.apply_event_to_cache(ev)
            dispatch = self._dispatch
            if dispatch is None:
                dispatch = self._build_dispatch()
            for ctrl, map_fn, pred, metric in dispatch.get(ev.kind, ()):
                if map_fn is None:
                    # primary-kind entry (For(...) + primary predicate)
                    if ctrl.primary_predicate is None or ctrl.primary_predicate(ev):
                        METRICS.inc(metric)
                        ctrl.queue.add(
                            (
                                ctrl.kind,
                                ev.obj.metadata.namespace,
                                ev.obj.metadata.name,
                            )
                        )
                    continue
                if pred is not None and not pred(ev):
                    continue
                hits = map_fn(ev)
                if hits:
                    METRICS.inc(metric, len(hits))
                for ns, name in hits:
                    ctrl.queue.add((ctrl.kind, ns, name))
        for ev in remaining:
            # held events return to their owning shard's backlog
            self._backlogs[ev.shard if self.num_shards > 1 else 0].append(ev)

    # -- run loop --------------------------------------------------------

    def _complete(self, ctrl: Controller, key, result, error, now) -> None:
        """Shared workqueue bookkeeping for a finished reconcile — single
        home for the requeue/backoff/forget semantics so the deterministic
        and threaded drains can never drift."""
        if error is not None:
            METRICS.inc(f"reconcile_panics_total/{ctrl.name}")
            if FLIGHTREC.enabled:
                # postmortem evidence AT the failure: ring snapshot plus a
                # bundle when a GroveError escaped a reconcile (store
                # outage, forbidden write, torn recovery) — dump count is
                # capped inside trigger(), so error storms can't disk-spam
                FLIGHTREC.note_error(ctrl.name, key, error)
                if isinstance(error, GroveError):
                    FLIGHTREC.trigger(
                        "reconcile-grove-error",
                        f"{ctrl.name} {key[1]}/{key[2]}: {error}",
                    )
            # RecoverPanic equivalent (manager.go:99-101): requeue
            ctrl.queue.add_rate_limited(key, now)
            return
        if result.result == "requeue":
            METRICS.inc(f"reconcile_errors_total/{ctrl.name}")
            ctrl.queue.add_rate_limited(key, now)
        elif result.result == "requeue_after":
            ctrl.queue.forget(key)
            ctrl.queue.add_after(key, result.requeue_after or 0.0, now)
        else:
            ctrl.queue.forget(key)

    def drain(self, max_rounds: int = 10_000) -> int:
        """Process until no controller has a ready item at the current time.
        Returns the number of reconciles executed. With workers armed
        (enable_workers / GROVE_TPU_CP_WORKERS) the rounds run through the
        parallel executor — same pop order, per-shard reconcile groups on
        worker threads (runtime/workers.py)."""
        if self.workers is not None:
            if not PROFILER.enabled:
                return self.workers.drain(max_rounds)
            with PROFILER.phase("drain", controller="engine"):
                return self.workers.drain(max_rounds)
        if not PROFILER.enabled:
            return self._drain_rounds(max_rounds)
        # attribution window: the drain loop's own glue (pops, metrics,
        # quiescence checks) lands on (engine, -, drain); dequeue and each
        # reconcile open their own child phases
        with PROFILER.phase("drain", controller="engine"):
            return self._drain_rounds(max_rounds)

    def _execute_batch(self, ctrl: Controller, batch: List[Key], now) -> None:
        """Serial batch executor: reconcile each popped key in pop order
        on this (the draining) thread. The parallel control plane
        substitutes its per-shard group dispatch here
        (runtime/workers.py `ParallelDrain._run_batch`) — everything
        AROUND the executor is the one shared round loop, so the serial
        and parallel drains cannot structurally drift."""
        for key in batch:
            result = error = None
            try:
                result = self._timed(ctrl, key)
            except Exception as e:
                error = e
            self._complete(ctrl, key, result, error, now)

    def _drain_rounds(self, max_rounds: int, execute_batch=None) -> int:
        """THE round loop, shared by the serial drain and the parallel
        drain (which passes its own `execute_batch`): route, pop each
        controller's whole ready set in deterministic order, execute,
        publish gauges, quiesce. One implementation so a future change
        (a new gauge, a quiescence tweak) can never silently apply to
        one drain and not the other — the serial-twin A/B's structural
        half."""
        if execute_batch is None:
            execute_batch = self._execute_batch
        executed = 0
        now = self.clock.now()
        for _ in range(max_rounds):
            self._route_events()
            if self.round_hook is not None:
                # routing IS the round's cache-advance boundary: the
                # process drain records its sync-log watermark here so
                # worker mirrors advance their caches at the same
                # boundary the serial drain does
                self.round_hook()
            progressed = False
            for ctrl in self.controllers:
                # BATCHED drain: pop the controller's whole ready set up
                # front (events emitted by these reconciles are routed only
                # at the next round's start, and every delayed re-add lands
                # strictly after `now`, so the upfront pop sees exactly the
                # keys the old pop-one-at-a-time loop would have) — sibling
                # updates COALESCE into one owner requeue (dedup) instead
                # of one owner reconcile per child event, and the batch
                # hook lets a reconciler serve every key of the round from
                # one component build. Terminates: reconciles can only add
                # to the backlog (routed next round) or the delayed heap
                # (>= backoff).
                batch: List[Key] = []
                while True:
                    key = ctrl.queue.pop(now)
                    if key is None:
                        break
                    batch.append(key)
                if not batch:
                    continue
                progressed = True
                executed += len(batch)
                METRICS.inc(f"reconcile_total/{ctrl.name}", len(batch))
                span = None
                if TRACER.enabled:
                    attrs = {"controller": ctrl.name, "keys": len(batch)}
                    if self.workers is not None:
                        attrs["workers"] = self.workers.workers
                    span = TRACER.span("reconcile.batch", **attrs)
                if ctrl.batch_hook is not None:
                    # per-batch memo built BEFORE any execution (under
                    # workers: on the coordinator, before any worker
                    # reads it — read-only afterwards)
                    ctrl.batch_hook(batch)
                try:
                    execute_batch(ctrl, batch, now)
                finally:
                    if span is not None:
                        span.end()
            for ctrl in self.controllers:
                METRICS.set(f"workqueue_depth/{ctrl.name}", len(ctrl.queue))
            self._set_backlog_gauges()
            if not progressed:
                # new events may have landed during the last round
                self._route_events()
                if all(c.queue.empty(now) for c in self.controllers):
                    return executed
        raise RuntimeError(
            f"engine did not quiesce within {max_rounds} rounds "
            "(reconcile livelock?)"
        )

    def _set_backlog_gauges(self) -> None:
        """Per-shard backlog depth gauges, once per drain round (a hot
        tenant's shard shows up here while the rotation keeps the others
        draining). Shared by the serial and parallel drains."""
        if self.num_shards > 1:
            for idx, backlog in enumerate(self._backlogs):
                METRICS.set(f"engine_shard_backlog@{idx}", len(backlog))

    def _timed(self, ctrl: Controller, key):
        t0 = time.perf_counter()
        # disabled tracing costs exactly this one boolean check per reconcile
        span = None
        if TRACER.enabled:
            # thread-local shard context: every span opened INSIDE the
            # reconcile inherits the lane (cleared in the finally)
            TRACER.set_shard(self._shard_of_key(key))
            span = TRACER.span(
                "engine.reconcile",
                controller=ctrl.name,
                key=f"{key[1]}/{key[2]}",
            )
        # ... and disabled profiling this one: the reconcile phase re-keys
        # the attribution context, so store reads/writes inside land under
        # (controller, shard, snapshot/store-commit/status-write)
        prof = (
            PROFILER.reconcile(ctrl.name, self._shard_of_key(key))
            if PROFILER.enabled
            else None
        )
        outcome = "error"
        try:
            result = ctrl.reconcile(key)
            outcome = result.result if result is not None else "done"
            return result
        finally:
            if prof is not None:
                prof.end()
            if span is not None:
                span.set("outcome", outcome)
                span.end()
                TRACER.set_shard(None)
            METRICS.observe(
                f"reconcile_seconds/{ctrl.name}", time.perf_counter() - t0
            )

    def _shard_of_key(self, key) -> int:
        """Owning keyspace shard of a reconcile key's namespace (NO_SHARD
        when the store has no shard map — HttpStore in cluster mode)."""
        if self._shard_index is None:
            return NO_SHARD
        return self._shard_index(key[1])

    def _ensure_pool(self):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor

            size = max(
                sum(max(c.concurrent_syncs, 1) for c in self.controllers), 1
            )
            self._pool = ThreadPoolExecutor(
                max_workers=size, thread_name_prefix="reconcile"
            )
        return self._pool

    def close(self) -> None:
        """Release the reconcile thread pools (no-op if never threaded)."""
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)
            self._pool = None
        if self.workers is not None:
            self.workers.close()
            self.workers = None

    def drain_concurrent(self, max_iterations: int = 100_000) -> int:
        """Threaded drain: each controller runs up to `concurrent_syncs`
        reconciles in REAL parallel threads — the reference's goroutine
        concurrency model (MaxConcurrentReconciles) rather than the
        deterministic single-threaded batching of `drain`.

        Completion-driven: slots refill AS reconciles finish (no per-round
        join barrier), so the busy set genuinely carries the same-key
        exclusion guarantee — a key whose reconcile is in flight is popped,
        seen busy, and re-queued to run after the in-flight one completes
        (client-go workqueue semantics). The pool is an engine-lifetime
        resource (`close()` releases it).

        Intended for real-cluster mode over a thread-safe store (HttpStore /
        the locked apiserver). The sim keeps the deterministic drain."""
        from concurrent.futures import FIRST_COMPLETED, wait

        pool = self._ensure_pool()
        executed = 0
        futures = {}  # future -> (controller, key)
        for _ in range(max_iterations):
            now = self.clock.now()
            self._route_events()
            for ctrl in self.controllers:
                slots = max(ctrl.concurrent_syncs, 1) - sum(
                    1 for (c, _k) in futures.values() if c is ctrl
                )
                for _slot in range(slots):
                    key = ctrl.queue.pop(now)
                    if key is None:
                        break
                    if key in ctrl.busy:
                        # in flight on another thread: run it AFTER that
                        # reconcile completes, never concurrently
                        ctrl.queue.add(key)  # no backoff: not a failure
                        break
                    ctrl.busy.add(key)
                    executed += 1
                    METRICS.inc(f"reconcile_total/{ctrl.name}")
                    futures[pool.submit(self._timed, ctrl, key)] = (ctrl, key)
            if not futures:
                self._route_events()
                if all(
                    c.queue.empty(self.clock.now()) for c in self.controllers
                ):
                    return executed
                continue
            done, _pending = wait(futures, return_when=FIRST_COMPLETED)
            now = self.clock.now()
            for fut in done:
                ctrl, key = futures.pop(fut)
                result = error = None
                try:
                    result = fut.result()
                except Exception as e:
                    error = e
                self._complete(ctrl, key, result, error, now)
                ctrl.busy.discard(key)
            for ctrl in self.controllers:
                METRICS.set(f"workqueue_depth/{ctrl.name}", len(ctrl.queue))
        raise RuntimeError(
            f"engine did not quiesce within {max_iterations} iterations "
            "(reconcile livelock?)"
        )

    def advance(self, seconds: float) -> None:
        self.clock.advance(seconds)  # type: ignore[attr-defined]

    def advance_and_drain(self, seconds: float) -> int:
        """Advance virtual time then drain — fires due requeue_after items
        (gang termination delays, rolling-update waits)."""
        self.advance(seconds)
        return self.drain()

    def next_wakeup(self) -> Optional[float]:
        """Earliest scheduled requeue across controllers (None if idle)."""
        times = [
            t for c in self.controllers if (t := c.queue.next_delayed_at()) is not None
        ]
        return min(times) if times else None

    def run_until_idle(self, max_virtual_seconds: float = 3600.0) -> int:
        """Drain, then keep advancing virtual time to the next scheduled
        requeue until nothing is pending or the budget is exhausted."""
        total = self.drain()
        budget_end = self.clock.now() + max_virtual_seconds
        while True:
            wake = self.next_wakeup()
            if wake is None or wake > budget_end:
                return total
            if wake > self.clock.now():
                self.advance(wake - self.clock.now())
            total += self.drain()
