"""Worker-process control plane: shared-nothing executor over the wire codec.

The thread executor (runtime/workers.py) proved the determinism contract
— coordinator-only routing/pops/bookkeeping, per-shard worker groups in
batch order, deferred cross-shard fan-out replayed serially — but on GIL
builds its workers time-share one interpreter. This module is the
worker-PROCESS backend docs/control-plane.md §5 designed and deferred:
the same `Engine.enable_workers` surface (GROVE_TPU_CP_BACKEND=process),
one forked OS process per worker group, the process boundary crossed
ONLY by the api/serialize.py wire codec (the WAL's envelope form —
GL004/GL011/GL020: no pickle of store objects on a boundary).

Fork-per-drain generations
--------------------------

Workers are forked at the first remote batch of each `drain()` and exit
when the drain returns. The fork IS the state-shipping mechanism: a
copy-on-write snapshot of the coordinator's entire live state (store
shards, informer caches, cluster sim, disruption broker, expectations)
at the drain boundary — exactly the state the serial drain would read —
so nothing outside the store ever needs replicating across a drain
boundary. Within the drain, the only state that moves is:

- coordinator -> worker: the round's keys + a SYNC STREAM of every
  commit since the worker's last batch (wire envelopes, in the serial
  batch order the coordinator applied them), so worker informer caches
  advance exactly one round behind — the serial cache-lag contract.
- worker -> coordinator: per-key reconcile outcomes + the key's commits
  as wire envelopes (mirror-applied, and re-emitted to every
  coordinator-side consumer, in batch order) + the key's expectations
  entry (runtime/expectations.py `export_key`) so raise/lower survives
  the generation.

Mirrors never exchange resourceVersions: `Store.apply_remote_event`
restamps on apply (per-object rv values are mirror-local because
best-effort Events interleave; the COUNTS every A/B compares are
identical — each apply bumps exactly one shard by one).

WAL ownership
-------------

A worker process owns its shards' WAL streams for the generation's
lifetime: the coordinator's stream handles go inert (`wal.remote`), the
worker's live `note_event` subscription buffers its own commits, and the
generation's stop handshake final-flushes + ships the watermarks back
before `drain()` returns — so the tick-boundary pump cadence and the
acked-prefix audit are unchanged. Crash repatriation: the coordinator
keeps a per-shard ring of the commits it mirror-applied while the
stream was remote; a dead worker's ring replays into the re-localized
stream, so no acked-prefix hole ever opens (the worker never fsyncs
mid-drain — its buffer dies with it, exactly like a crashed serial
store's).

Crash robustness (chaos `worker_crash`)
---------------------------------------

A dead channel (EOF, SIGKILL, stall past the batch deadline) is
detected at the reply phase; the coordinator repatriates the worker's
shards and re-executes its keys inline AT THEIR BATCH POSITIONS from
its own mirror — deterministically equivalent to the worker having run
them (same inputs: the mirror is exact). Protocol corruption fails
closed with a GroveError + flight-recorder bundle. Never a hang (the
reply wait is deadline-bounded), never divergent state.

Worker-pool internals are PRIVATE to runtime/ (grovelint GL018/GL020).
"""

from __future__ import annotations

import json
import os
import signal
import time
from typing import Dict, List, Optional

from grove_tpu.observability.flightrec import FLIGHTREC
from grove_tpu.observability.metrics import METRICS
from grove_tpu.observability.tracing import TRACER
from grove_tpu.runtime.errors import ERR_TRANSPORT, GroveError
from grove_tpu.runtime.flow import ReconcileStepResult

# one generous bound so a wedged worker can never hang the coordinator:
# covers the slowest single-worker round at stress scale with margin
BATCH_DEADLINE_S = 600.0


def backend_from_env() -> str:
    """GROVE_TPU_CP_BACKEND=thread|process (default thread — the PR 15
    executor stays the default until a box with cores to spend says
    otherwise)."""
    backend = os.environ.get("GROVE_TPU_CP_BACKEND", "thread").strip().lower()
    return backend if backend in ("thread", "process") else "thread"


def _encode_error(e: BaseException) -> dict:
    if isinstance(e, GroveError):
        return {
            "grove": True,
            "code": e.code,
            "msg": e.message,
            "op": e.operation,
            "ra": e.requeue_after,
        }
    return {"grove": False, "msg": repr(e)}


def _decode_error(doc: Optional[dict]) -> Optional[Exception]:
    if doc is None:
        return None
    if doc.get("grove"):
        return GroveError(
            doc["code"], doc.get("msg", ""), doc.get("op", ""),
            requeue_after=doc.get("ra"),
        )
    return RuntimeError(doc.get("msg", "worker reconcile error"))


def _decode_result(doc: Optional[dict]):
    if doc is None:
        return None
    return ReconcileStepResult(
        result=doc["result"], requeue_after=doc.get("ra")
    )


class ProcessDrain:
    """Worker-process drain for one Engine (docs/control-plane.md §5).

    Mirrors ParallelDrain's executor surface exactly (`worker_of`,
    `busy_snapshot`, `utilization`, `stats`, `drain`, `close`) so every
    caller — sweep, bench, glassbox — is backend-agnostic."""

    backend = "process"

    def __init__(self, engine, workers: int) -> None:
        import multiprocessing

        if "fork" not in multiprocessing.get_all_start_methods():
            raise GroveError(
                ERR_TRANSPORT,
                "the worker-process backend needs the fork start method"
                " (POSIX); use GROVE_TPU_CP_BACKEND=thread here",
                "enable-workers",
            )
        self._mp = multiprocessing.get_context("fork")
        self.engine = engine
        # same clamp as the thread backend: worker_of = shard % W can
        # never route beyond S workers
        self.workers = max(2, min(int(workers), engine.num_shards))
        self.reconciles_by_worker = [0] * self.workers
        self._worker_busy_s = [0.0] * self.workers
        # generation state (populated per drain, torn down before the
        # drain returns)
        self._gen_active = False
        self._epoch = 0  # fork-generation counter (event-seq slot spacing)
        self._procs: Dict[int, object] = {}
        self._conns: Dict[int, object] = {}
        self._dead: set = set()
        self._log: List[dict] = []  # sync stream, serial apply order
        self._cursors: Dict[int, int] = {}  # per-worker shipped offset
        self._rings: Dict[int, list] = {}  # per-shard WAL backfill rings
        self._ring_gate: Dict[int, bool] = {}
        self._ring_subscribed: set = set()
        self._recorder_installed = False
        self._muted = False  # recorder off while mirror-applying (the
        # shipped envelope is appended to the log directly, stamped with
        # its true origin — the live emit must not double-log it as o=0)
        self._child_id: Optional[int] = None  # set inside a forked worker
        self._clog: List[object] = []  # child: commits of the running key
        self._recording = False
        self._echo_queue: List[object] = []  # child: commits awaiting echo
        # chaos `worker_crash` arm (sim/chaos.py): SIGKILL this worker
        # right after the next batch is dispatched to it
        self.chaos_kill_worker: Optional[int] = None
        self.crashes = 0
        # boundary accounting (docs/observability.md)
        self.boundary_bytes = 0
        # gray-failure injection (runtime/boundary.py): None = the
        # fault-free channel code, byte-identical to the pre-fault
        # build; armed, every frame carries a sequence number and the
        # dedup/retransmit protocol below tolerates drop/dup/delay
        self._faults = None
        self.boundary_fault_counts = {
            "drop": 0,
            "dup": 0,
            "delay": 0,
            "retransmits": 0,
            "deduped": 0,
        }
        self._tx_seq: Dict[int, int] = {}  # per-worker request seqs
        self._rx_seq: Dict[int, int] = {}  # per-worker reply high-water
        self._last_sent: Dict[int, bytes] = {}  # retransmit buffer
        self._crx_high = 0  # child: request high-water mark
        self._creply_cache: Dict[int, bytes] = {}  # child: seq -> reply
        # cache watermark: sync-log position at the last routing boundary.
        # Records before it are cache-advanceable in worker mirrors (the
        # serial drain advanced its cache for them at that routing);
        # records after it are committed-only until the next round — the
        # serial cache-lag contract, byte for byte. -1 = no routing since
        # the generation forked (nothing advanceable).
        self._cache_mark = -1
        self._pending_cache: List[tuple] = []  # child: (index, ev) stash
        METRICS.set("cp_workers", self.workers)
        METRICS.set("cp_backend_process", 1)
        engine.store._process_drain = self
        engine.round_hook = self._on_round

    # -- ownership map (ParallelDrain-identical) --------------------------

    def worker_of(self, shard: int) -> int:
        if shard < 0:
            return 0
        return shard % self.workers

    def _lane_of(self, shard: int) -> int:
        """worker_of with crash degradation: a dead worker's shards
        repatriate to the coordination plane for the rest of the drain."""
        w = self.worker_of(shard)
        return 0 if w in self._dead else w

    def busy_snapshot(self) -> List[float]:
        return list(self._worker_busy_s)

    def utilization(
        self, wall_seconds: float, since: List[float] = None
    ) -> List[float]:
        if wall_seconds <= 0:
            return [0.0] * self.workers
        base = since or [0.0] * self.workers
        return [
            round((b - b0) / wall_seconds, 4)
            for b, b0 in zip(self._worker_busy_s, base)
        ]

    @property
    def active(self) -> bool:
        """A worker generation is live (mid-drain)."""
        return self._gen_active

    def close(self) -> None:
        if self._gen_active:
            self._stop_gen()
        if getattr(self.engine.store, "_process_drain", None) is self:
            self.engine.store._process_drain = None
        if self.engine.round_hook == self._on_round:
            self.engine.round_hook = None

    def inject_boundary_faults(
        self,
        seed: int,
        drop_rate: float = 0.0,
        dup_rate: float = 0.0,
        delay_rate: float = 0.0,
    ) -> None:
        """Arm seeded drop/dup/delay injection on the wire boundary
        (chaos ``boundary_faults`` arm). Must be armed before the drain
        whose generation should see faults — children inherit the plan
        at fork and compute identical verdicts."""
        from grove_tpu.runtime.boundary import BoundaryFaults

        self._faults = BoundaryFaults(
            seed,
            drop_rate=drop_rate,
            dup_rate=dup_rate,
            delay_rate=delay_rate,
        )

    def _on_round(self) -> None:
        """Engine round hook: routing just ran — everything logged so far
        is now cache-advanced in the serial twin, so worker mirrors may
        advance through it too."""
        if self._gen_active:
            self._cache_mark = len(self._log)

    # -- drive ------------------------------------------------------------

    def drain(self, max_rounds: int) -> int:
        """One engine drain through the shared round loop, with this
        executor substituted. Workers fork lazily at the first batch that
        routes off the coordination plane (idle ticks never fork) and the
        generation is torn down — worker WAL streams final-flushed,
        watermarks shipped home, processes reaped — before returning."""
        try:
            return self.engine._drain_rounds(
                max_rounds, execute_batch=self._run_batch
            )
        finally:
            if self._gen_active:
                self._stop_gen()

    # -- sync recorder ----------------------------------------------------

    def _record(self, ev) -> None:
        """Store-wide system watcher. Coordinator: while a generation is
        live, append every commit — lane-0 reconcile commits arrive here
        via the deferred-capture replay (batch order), mirror-applies and
        coordinator-phase commits live (their emit order IS the serial
        order) — to the sync stream workers mirror from. Worker: while a
        reconcile runs, collect its commits for the reply."""
        if self._child_id is not None:
            if self._recording:
                self._clog.append(ev)
            return
        if self._gen_active and not self._muted:
            self._log.append({"t": ev.type, "o": 0, "ev": ev})

    def _ring_cb(self, shard_index: int):
        def cb(ev, _i=shard_index) -> None:
            # WAL backfill ring: only while the shard's stream is remote,
            # and never Events (outside the durability contract)
            if self._ring_gate.get(_i) and ev.kind != "Event":
                self._rings[_i].append(ev)

        return cb

    def _ship_slice(self, w: int):
        """(base, records): the sync records worker `w` has not seen yet,
        envelope-encoded once (encoding is cached on the record — every
        worker ships the same doc), plus their starting position in the
        log so the worker can gate each against the cache watermark."""
        from grove_tpu.durability.wal import object_envelope

        base = self._cursors.get(w, 0)
        out = []
        for rec in self._log[base:]:
            if "env" not in rec:
                rec["env"] = object_envelope(rec["ev"].obj)
                rec["ev"] = None  # encoded once; every worker ships this doc
            out.append({"t": rec["t"], "o": rec["o"], "env": rec["env"]})
        self._cursors[w] = len(self._log)
        return base, out

    # -- generation lifecycle ---------------------------------------------

    def _start_gen(self) -> None:
        store = self.engine.store
        dur = getattr(store, "_durability", None)
        if dur is not None and dur._committer is not None:
            raise GroveError(
                ERR_TRANSPORT,
                "worker-process backend cannot run under a background WAL"
                " committer thread (fork while another thread may hold the"
                " stream locks); stop the committer first",
                "enable-workers",
            )
        if not self._recorder_installed:
            # registered AFTER arm_deferred_fanout wrapped the store-wide
            # fan-out, so lane-0 capture defers these deliveries into the
            # batch-order replay — the recorder sees the serial order
            store.subscribe_system(self._record)
            self._recorder_installed = True
        self._epoch += 1
        self._log = []
        self._cursors = {}
        self._cache_mark = -1
        self._dead = set()
        self._tx_seq = {}
        self._rx_seq = {}
        self._last_sent = {}
        self._crx_high = 0
        self._creply_cache = {}
        child_shards = [
            i for i in range(self.engine.num_shards) if self.worker_of(i) != 0
        ]
        for i in child_shards:
            if i not in self._ring_subscribed:
                store.subscribe_system(self._ring_cb(i), shard=i)
                self._ring_subscribed.add(i)
            self._rings[i] = []
            self._ring_gate[i] = dur is not None
        if dur is not None:
            for i in child_shards:
                # flush BEFORE the fork: records buffered by coordinator
                # phases since the last pump would otherwise be copied
                # into the child (which final-flushes them) AND stay in
                # this process's buffer (flushed again at the next pump)
                # — duplicate seqs that truncate the durable fold
                dur.wals[i].flush()
                dur.wals[i].remote = True
        # all channels exist before any fork: each child closes every fd
        # that is not its own, so a dead worker's EOF is observable (a
        # sibling holding the write end would mask it)
        channels = {
            w: self._mp.Pipe(duplex=True) for w in range(1, self.workers)
        }
        self._gen_active = True
        procs = {}
        import warnings

        with warnings.catch_warnings():
            # the fork-with-threads hazard this warns about is exactly
            # what the committer guard above rules out; the warning would
            # otherwise print once per generation into smoke artifacts
            warnings.filterwarnings("ignore", category=RuntimeWarning)
            for w in range(1, self.workers):
                p = self._mp.Process(
                    target=self._child_main,
                    args=(w, channels),
                    daemon=True,
                    name=f"cp-worker-{w}",
                )
                p.start()
                procs[w] = p
        for w, (parent_conn, child_conn) in channels.items():
            child_conn.close()
        self._conns = {w: pc for w, (pc, _cc) in channels.items()}
        self._procs = procs

    def _stop_gen(self) -> None:
        dur = getattr(self.engine.store, "_durability", None)
        live = [
            w for w in self._procs
            if w not in self._dead
        ]
        for w in live:
            try:
                self._send(w, {"cmd": "stop"})
            except (OSError, ValueError):
                self._repatriate(w, "stop-send failed")
        for w in live:
            if w in self._dead:
                continue
            bye = self._recv(w, timeout=30.0)
            if bye is None or bye.get("cmd") != "bye":
                self._repatriate(w, "no stop handshake")
                continue
            if dur is not None:
                for wm in bye.get("wal", []):
                    wal = dur.wals[wm["shard"]]
                    # adopt the worker's stream position wholesale: seq
                    # numbering, durable watermarks and the segment cursor
                    # continue exactly where the owner left them
                    wal._seq = wm["seq"]
                    wal.durable_seq = wm["durable_seq"]
                    wal.durable_rv = wm["durable_rv"]
                    wal.flushed_bytes = wm["flushed_bytes"]
                    wal.flushed_records = wm["flushed_records"]
                    if wal._fh is not None:
                        wal._fh.close()
                        wal._fh = None
                    wal._segment_index = wm["segment_index"]
                    wal._segment_bytes = wm["segment_bytes"]
                    self._rings[wm["shard"]] = []
        self._gen_active = False
        for i in list(self._ring_gate):
            self._ring_gate[i] = False
            self._rings[i] = []
        if dur is not None:
            for wal in dur.wals:
                wal.remote = False
        for w, p in self._procs.items():
            p.join(timeout=5.0)
            if p.is_alive():
                p.terminate()
                p.join(timeout=5.0)
        for conn in self._conns.values():
            try:
                conn.close()
            except OSError:
                pass
        self._procs = {}
        self._conns = {}

    def kill_all(self) -> None:
        """SIGKILL every live worker (StoreDurability.simulate_crash: the
        control plane dies as ONE failure domain — buffered worker records
        are lost exactly like the coordinator's own buffer). Streams
        re-localize WITHOUT ring replay: a crash loses unacked records by
        definition."""
        if not self._gen_active:
            return
        for w, p in self._procs.items():
            if p.is_alive():
                try:
                    os.kill(p.pid, signal.SIGKILL)
                except (OSError, TypeError):
                    pass
            p.join(timeout=5.0)
        dur = getattr(self.engine.store, "_durability", None)
        if dur is not None:
            for wal in dur.wals:
                wal.remote = False
        self._gen_active = False
        for i in list(self._ring_gate):
            self._ring_gate[i] = False
            self._rings[i] = []
        self._procs = {}
        self._conns = {}

    def _repatriate(self, w: int, why: str) -> None:
        """Worker `w`'s channel died: take its shards back. Its WAL
        streams re-localize and the mirror-applied commits it never
        fsynced backfill from the rings, so the acked prefix stays
        gap-free; its in-flight keys re-execute inline at their batch
        positions (deterministic: the mirror is exact)."""
        if w in self._dead:
            return
        self._dead.add(w)
        self.crashes += 1
        METRICS.inc("cp_worker_crashes_total")
        p = self._procs.get(w)
        if p is not None:
            if p.is_alive():
                try:
                    os.kill(p.pid, signal.SIGKILL)
                except (OSError, TypeError):
                    pass
            p.join(timeout=5.0)
        dur = getattr(self.engine.store, "_durability", None)
        for i in range(self.engine.num_shards):
            if self.worker_of(i) != w:
                continue
            self._ring_gate[i] = False
            if dur is not None:
                wal = dur.wals[i]
                wal.remote = False
                for ev in self._rings.get(i, ()):
                    wal.note_event(ev)
            self._rings[i] = []
        if FLIGHTREC.enabled:
            FLIGHTREC.trigger(
                "cp-worker-crash",
                f"worker {w} {why}; coordinator repatriated its shards"
                " and re-executes its keys inline",
            )

    # -- channel ----------------------------------------------------------

    def _send(self, w: int, msg: dict) -> None:
        if self._faults is None:
            payload = json.dumps(msg, separators=(",", ":")).encode(
                "utf-8"
            )
            self.boundary_bytes += len(payload)
            METRICS.inc("cp_boundary_bytes_total", len(payload))
            self._conns[w].send_bytes(payload)
            return
        # armed: frame with a per-channel sequence number and let the
        # fault plan decide. drop/delay withhold the frame — the
        # retrying _recv below retransmits it (that IS the delay) —
        # dup transmits twice (the worker's seq dedup eats the copy).
        seq = self._tx_seq.get(w, 0) + 1
        self._tx_seq[w] = seq
        payload = json.dumps(
            {"fs": seq, "fm": msg}, separators=(",", ":")
        ).encode("utf-8")
        self._last_sent[w] = payload
        verdict = self._faults.decide("c2w", w, seq)
        if verdict in ("drop", "delay"):
            self.boundary_fault_counts[verdict] += 1
            METRICS.inc("cp_boundary_faults_total")
            return
        self.boundary_bytes += len(payload)
        METRICS.inc("cp_boundary_bytes_total", len(payload))
        self._conns[w].send_bytes(payload)
        if verdict == "dup":
            self.boundary_fault_counts["dup"] += 1
            METRICS.inc("cp_boundary_faults_total")
            self._conns[w].send_bytes(payload)

    def _recv(self, w: int, timeout: float) -> Optional[dict]:
        """One framed reply from worker `w`, deadline-bounded. None means
        the channel is dead (caller repatriates); a live-but-stalled
        worker past the deadline fails CLOSED. With boundary faults
        armed this loop also DEDUPS (stale reply seqs are duplicates)
        and RETRANSMITS the last request on a BackoffPolicy pace —
        withheld or lost frames heal here, inside the same deadline."""
        conn = self._conns[w]
        proc = self._procs[w]
        armed = self._faults is not None
        deadline = time.monotonic() + timeout
        attempt = 0
        next_retx = (
            time.monotonic() + self._faults.retransmit_after(w, 0)
            if armed
            else None
        )
        while True:
            try:
                if conn.poll(0.05):
                    data = conn.recv_bytes()
                    self.boundary_bytes += len(data)
                    METRICS.inc("cp_boundary_bytes_total", len(data))
                    doc = json.loads(data)
                    if armed and isinstance(doc, dict) and "fs" in doc:
                        seq = doc["fs"]
                        if seq <= self._rx_seq.get(w, 0):
                            # duplicate of a reply already consumed
                            self.boundary_fault_counts["deduped"] += 1
                            continue
                        self._rx_seq[w] = seq
                        return doc["fm"]
                    return doc
            except (EOFError, OSError):
                return None
            if not proc.is_alive():
                # drain anything the worker wrote before dying
                try:
                    if conn.poll(0.0):
                        continue
                except (EOFError, OSError):
                    pass
                return None
            now = time.monotonic()
            if now > deadline:
                raise GroveError(
                    ERR_TRANSPORT,
                    f"worker {w} stalled past the {timeout:.0f}s batch"
                    " deadline; failing closed (flight bundle dumped)",
                    "process-drain",
                )
            if armed and now >= next_retx:
                last = self._last_sent.get(w)
                if last is not None:
                    # retransmits bypass injection: one fault per frame
                    # seq models gray loss, and the retry path must be
                    # the reliable one or nothing ever converges
                    try:
                        conn.send_bytes(last)
                    except (OSError, ValueError):
                        return None
                    self.boundary_fault_counts["retransmits"] += 1
                    METRICS.inc("cp_boundary_retransmits_total")
                attempt += 1
                next_retx = now + self._faults.retransmit_after(
                    w, attempt
                )

    # -- coordinator batch path -------------------------------------------

    def _run_batch(self, ctrl, batch: List, now: float) -> None:
        eng = self.engine
        if not self._gen_active:
            # idle ticks never reach here with remote keys before forking:
            # fork lazily only when this drain actually has a batch
            if all(
                self.worker_of(eng._shard_of_key(k)) == 0 for k in batch
            ):
                self._run_local(ctrl, batch, now, {})
                return
            self._start_gen()
        bytes0 = self.boundary_bytes
        groups: Dict[int, List] = {}
        for key in batch:
            groups.setdefault(self._lane_of(eng._shard_of_key(key)), []).append(key)
        ci = next(i for i, c in enumerate(eng.controllers) if c is ctrl)
        dispatched: List[int] = []
        for w, keys in groups.items():
            if w == 0:
                continue
            base, records = self._ship_slice(w)
            try:
                self._send(
                    w,
                    {
                        "cmd": "batch",
                        "ci": ci,
                        "keys": [list(k) for k in keys],
                        "sync": records,
                        "base": base,
                        "cm": self._cache_mark,
                    },
                )
                dispatched.append(w)
            except (OSError, ValueError):
                self._repatriate(w, "batch dispatch failed")
        if self.chaos_kill_worker is not None:
            victim = self.chaos_kill_worker
            if victim in dispatched:
                # chaos `worker_crash`: the process dies MID-ROUND, after
                # the batch left the coordinator — the recovery path must
                # cope whether or not a reply was already in the pipe
                self.chaos_kill_worker = None
                p = self._procs.get(victim)
                if p is not None and p.is_alive():
                    os.kill(p.pid, signal.SIGKILL)
        # lane 0 executes during worker flight (under deferred capture —
        # replayed at batch position below), then the overlap hook spends
        # the remaining flight time on the scheduler's speculative encode
        local_outcomes: Dict[tuple, tuple] = {}
        if 0 in groups:
            self._run_local(ctrl, groups[0], now, local_outcomes, defer=True)
        if eng.overlap_hook is not None:
            eng.overlap_hook()
        # collect replies
        replies: Dict[tuple, dict] = {}
        reply_worker: Dict[tuple, int] = {}
        for w in dispatched:
            if w in self._dead:
                continue
            msg = self._recv(w, timeout=BATCH_DEADLINE_S)
            if msg is None:
                self._repatriate(w, "channel died mid-round")
                continue
            if msg.get("cmd") == "fatal":
                self._repatriate(w, f"fatal: {msg.get('error')}")
                raise GroveError(
                    ERR_TRANSPORT,
                    f"worker {w} failed: {msg.get('error')}",
                    "process-drain",
                )
            results = msg.get("results", [])
            if msg.get("cmd") != "done" or len(results) != len(groups[w]):
                self._repatriate(w, "malformed reply")
                raise GroveError(
                    ERR_TRANSPORT,
                    f"worker {w} reply did not match its batch"
                    f" ({len(results)} results for {len(groups[w])} keys)",
                    "process-drain",
                )
            for key, entry in zip(groups[w], results):
                replies[key] = entry
                reply_worker[key] = w
            self.reconciles_by_worker[w] += len(groups[w])
            busy = sum(e.get("dur", 0.0) for e in results)
            self._worker_busy_s[w] += busy
            METRICS.inc(f"cp_worker_reconciles@{w}", len(groups[w]))
        # coordination point: apply + bookkeeping in serial batch order.
        # Each key lands exactly once, at its batch position: a lane-0
        # key replays its captured deliveries, a worker key mirror-applies
        # its shipped commits (live emission = the serial delivery
        # order), a crashed worker's key re-executes inline right here.
        from grove_tpu.controller.common import contexts_of_store

        ctxs = contexts_of_store(eng.store)
        for key in batch:
            if key in replies:
                entry = replies[key]
                w = reply_worker[key]
                self._muted = True
                try:
                    for doc in entry.get("commits", []):
                        eng.store.apply_remote_event(doc["t"], doc["env"])
                        self._log.append(
                            {"t": doc["t"], "o": w, "env": doc["env"]}
                        )
                finally:
                    self._muted = False
                exp = entry.get("exp")
                if exp is not None and ctxs:
                    ctxs[0].pod_expectations.import_key(
                        f"{key[1]}/{key[2]}", exp[0], exp[1]
                    )
                result = _decode_result(entry.get("r"))
                error = _decode_error(entry.get("e"))
                eng._complete(ctrl, key, result, error, now)
                METRICS.observe(
                    f"reconcile_seconds/{ctrl.name}", entry.get("dur", 0.0)
                )
            elif key in local_outcomes:
                result, error, captured = local_outcomes[key]
                eng._complete(ctrl, key, result, error, now)
                for fn, ev in captured:
                    fn(ev)
            else:
                # crashed worker: deterministic inline re-execution from
                # the coordinator's own mirror, at the key's position
                result = error = None
                try:
                    result = eng._timed(ctrl, key)
                except Exception as e:
                    error = e
                self.reconciles_by_worker[0] += 1
                eng._complete(ctrl, key, result, error, now)
        METRICS.set("cp_boundary_bytes_round", self.boundary_bytes - bytes0)

    def _run_local(
        self, ctrl, keys: List, now: float, outcomes: Dict, defer: bool = False
    ) -> None:
        """Lane 0: the coordinator's own sub-sequence. With defer=True the
        outcomes (and captured deliveries) are returned for batch-order
        completion; otherwise complete immediately (all-local batch — the
        serial path verbatim)."""
        eng = self.engine
        store = eng.store
        t0 = time.perf_counter()
        for key in keys:
            buf = store.begin_deferred_capture() if defer else None
            result = error = None
            try:
                result = eng._timed(ctrl, key)
            except Exception as e:
                error = e
            finally:
                captured = store.end_deferred_capture(buf) if defer else []
            if defer:
                outcomes[key] = (result, error, captured)
            else:
                eng._complete(ctrl, key, result, error, now)
        self._worker_busy_s[0] += time.perf_counter() - t0
        self.reconciles_by_worker[0] += len(keys)
        METRICS.inc("cp_worker_reconciles@0", len(keys))

    # -- worker process ---------------------------------------------------

    def _child_main(self, me: int, channels: Dict[int, tuple]) -> None:
        """Forked worker body. Exits only via os._exit: the child must
        never run the parent's inherited atexit/finalizer chain (shared
        tmpdirs, metric dumps)."""
        conn = None
        try:
            for w, (parent_conn, child_conn) in channels.items():
                if w == me:
                    parent_conn.close()
                    conn = child_conn
                else:
                    parent_conn.close()
                    child_conn.close()
            self._child_setup(me)
            while True:
                frame = json.loads(conn.recv_bytes())
                seq = 0
                if (
                    self._faults is not None
                    and isinstance(frame, dict)
                    and "fs" in frame
                ):
                    seq = frame["fs"]
                    if seq <= self._crx_high:
                        # retransmit of a request already executed:
                        # answer from the cached reply — idempotent, the
                        # batch must never run twice
                        cached = self._creply_cache.get(seq)
                        if cached is not None:
                            conn.send_bytes(cached)
                        continue
                    self._crx_high = seq
                    msg = frame["fm"]
                else:
                    msg = frame
                if msg["cmd"] == "batch":
                    self._child_reply(
                        conn, me, seq, self._child_batch(msg)
                    )
                elif msg["cmd"] == "stop":
                    # the stop handshake carries the WAL watermarks —
                    # never inject on it (the child exits right after,
                    # so the retransmit path could not heal a drop)
                    self._child_reply(
                        conn,
                        me,
                        seq,
                        {"cmd": "bye", "wal": self._child_final_flush(me)},
                        faultable=False,
                    )
                    os._exit(0)
        except EOFError:
            os._exit(0)  # coordinator closed the channel / died
        except BaseException as e:  # noqa: BLE001 — ships the postmortem
            try:
                if conn is not None:
                    conn.send_bytes(
                        json.dumps(
                            {"cmd": "fatal", "error": repr(e)},
                            separators=(",", ":"),
                        ).encode("utf-8")
                    )
            except OSError:
                pass
            os._exit(1)

    def _child_reply(
        self, conn, me: int, seq: int, msg: dict, faultable: bool = True
    ) -> None:
        """Send one reply frame from a worker. Armed: frame with the
        request's seq (monotone — the coordinator dedups on it), cache
        the payload for retransmit-triggered resends, and let the fault
        plan withhold or duplicate the transmit."""
        if self._faults is None:
            conn.send_bytes(
                json.dumps(msg, separators=(",", ":")).encode("utf-8")
            )
            return
        payload = json.dumps(
            {"fs": seq, "fm": msg}, separators=(",", ":")
        ).encode("utf-8")
        # cache keyed by request seq: a retransmitted request resends
        # this exact payload (the cached-reply path bypasses injection)
        self._creply_cache = {seq: payload}
        if faultable:
            verdict = self._faults.decide("w2c", me, seq)
            if verdict in ("drop", "delay"):
                return  # withheld: the coordinator's retransmit heals it
            conn.send_bytes(payload)
            if verdict == "dup":
                conn.send_bytes(payload)
            return
        conn.send_bytes(payload)

    def _child_setup(self, me: int) -> None:
        from grove_tpu.api.meta import reset_uid_namespace
        from grove_tpu.controller.common import rebase_event_sequences

        self._child_id = me
        self._clog = []
        self._echo_queue = []
        # commits routed by the coordinator before the fork sit in the
        # inherited backlogs: they are committed (COW) but not yet
        # cache-advanced. They advance at the parent's next routing —
        # index -1 puts them before every sync record, so any watermark
        # from a post-fork routing releases them.
        self._pending_cache = []
        for backlog in self.engine._backlogs:
            for ev in backlog:
                self._pending_cache.append((-1, ev))
            backlog.clear()
        # fresh uid incarnation + a disjoint evt-N range per (generation,
        # worker): forked allocators would otherwise re-issue the
        # coordinator's next uid/event name
        reset_uid_namespace()
        rebase_event_sequences(self._epoch * self.workers + me)
        try:
            TRACER.enabled = False
        except AttributeError:
            pass
        store = self.engine.store
        store._process_drain = None
        dur = getattr(store, "_durability", None)
        if dur is not None:
            for i, wal in enumerate(dur.wals):
                wal.remote = self.worker_of(i) != me
        for i in self._ring_gate:
            self._ring_gate[i] = False

    def _child_batch(self, msg: dict) -> dict:
        from grove_tpu.controller.common import contexts_of_store
        from grove_tpu.durability.wal import object_envelope

        eng = self.engine
        store = eng.store
        self._child_apply_sync(
            msg.get("sync", []), msg.get("base", 0), msg.get("cm", -1)
        )
        ctrl = eng.controllers[msg["ci"]]
        keys = [tuple(k) for k in msg["keys"]]
        if ctrl.batch_hook is not None:
            # re-run the coordinator's per-batch hook locally (it builds
            # lazy caches off the frozen informer view — deterministic)
            ctrl.batch_hook(keys)
        ctxs = contexts_of_store(store)
        results = []
        for key in keys:
            t0 = time.perf_counter()
            self._clog = []
            self._recording = True
            result = error = None
            try:
                result = eng._timed(ctrl, key)
            except Exception as e:
                error = e
            finally:
                self._recording = False
            commits = [
                {"t": ev.type, "env": object_envelope(ev.obj)}
                for ev in self._clog
            ]
            self._echo_queue.extend(self._clog)
            entry = {
                "r": None
                if result is None
                else {"result": result.result, "ra": result.requeue_after},
                "e": None if error is None else _encode_error(error),
                "commits": commits,
                "dur": time.perf_counter() - t0,
            }
            if ctxs:
                entry["exp"] = list(
                    ctxs[0].pod_expectations.export_key(f"{key[1]}/{key[2]}")
                )
            results.append(entry)
        # the worker never routes: drop the backlog its own commits fed
        # (cache advance happens through the sync stream instead)
        for backlog in eng._backlogs:
            backlog.clear()
        return {"cmd": "done", "results": results}

    def _child_apply_sync(
        self, records: List[dict], base: int, cm: int
    ) -> None:
        """Advance this worker's mirror by the coordinator's sync slice —
        the serial apply order. A record of our own origin is an ECHO:
        the commit is already in our committed maps and only the cache
        step remains; a foreign record mirror-applies.

        The informer cache advances SEPARATELY, gated by the watermark
        `cm`: a record at log position < cm was routed by the serial
        twin (its round boundary passed), so it is cache-visible; one at
        position >= cm is committed-only until a later batch's watermark
        releases it — a reconcile here must see exactly the frozen
        round view the serial reconcile sees."""
        store = self.engine.store
        if store.cache_lag:
            keep = []
            for i, ev in self._pending_cache:
                if i < cm:
                    store.apply_event_to_cache(ev)
                else:
                    keep.append((i, ev))
            self._pending_cache = keep
        for pos, rec in enumerate(records):
            if rec["o"] == self._child_id:
                if not self._echo_queue:
                    raise GroveError(
                        ERR_TRANSPORT,
                        "sync echo with no matching local commit: the"
                        " mirrors diverged",
                        "process-drain",
                    )
                ev = self._echo_queue.pop(0)
                env = rec["env"]
                if ev.kind != env["kind"] or ev.obj.metadata.name != env["name"]:
                    raise GroveError(
                        ERR_TRANSPORT,
                        f"sync echo mismatch: local {ev.kind}/"
                        f"{ev.obj.metadata.name} vs shipped"
                        f" {env['kind']}/{env['name']}",
                        "process-drain",
                    )
            else:
                ev = store.apply_remote_event(rec["t"], rec["env"])
            if store.cache_lag:
                if base + pos < cm:
                    store.apply_event_to_cache(ev)
                else:
                    self._pending_cache.append((base + pos, ev))

    def _child_final_flush(self, me: int) -> List[dict]:
        """Stop handshake: fsync every owned stream once and report the
        stream positions the coordinator adopts."""
        dur = getattr(self.engine.store, "_durability", None)
        if dur is None:
            return []
        out = []
        for i, wal in enumerate(dur.wals):
            if self.worker_of(i) != me:
                continue
            wal.flush()
            out.append(
                {
                    "shard": i,
                    "seq": wal._seq,
                    "durable_seq": wal.durable_seq,
                    "durable_rv": wal.durable_rv,
                    "flushed_bytes": wal.flushed_bytes,
                    "flushed_records": wal.flushed_records,
                    "segment_index": wal._segment_index,
                    "segment_bytes": wal._segment_bytes,
                }
            )
            if wal._fh is not None:
                wal._fh.close()
                wal._fh = None
        return out

    # -- reporting --------------------------------------------------------

    def stats(self) -> dict:
        return {
            "backend": "process",
            "workers": self.workers,
            "reconciles_by_worker": list(self.reconciles_by_worker),
            "busy_seconds_by_worker": [
                round(b, 3) for b in self._worker_busy_s
            ],
            "worker_crashes": self.crashes,
            "boundary_bytes": self.boundary_bytes,
            "boundary_faults": dict(self.boundary_fault_counts),
        }
