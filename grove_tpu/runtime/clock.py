"""Injectable clock.

The reference reads wall time off informer events; we need deterministic
virtual time so the 10k-gang stress sim and the gang-termination /
rolling-update timing tests run instantly and reproducibly.
"""

from __future__ import annotations

import time


class Clock:
    def now(self) -> float:
        # grovelint: disable=GL001 -- this IS the wall-clock injection boundary every other module must go through
        return time.time()

    def sleep(self, seconds: float) -> None:
        # grovelint: disable=GL001 -- the real clock's sleep; virtual-time code gets VirtualClock.sleep via injection
        time.sleep(seconds)


class VirtualClock(Clock):
    def __init__(self, start: float = 0.0) -> None:
        self._now = start

    def now(self) -> float:
        return self._now

    def sleep(self, seconds: float) -> None:
        self.advance(seconds)

    def advance(self, seconds: float) -> None:
        if seconds < 0:
            raise ValueError("cannot move time backwards")
        self._now += seconds
