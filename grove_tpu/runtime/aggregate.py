"""Event-driven status aggregation: incremental per-PodClique pod counters.

The PCLQ status flow used to recompute its replica counters by scanning and
categorizing every constituent pod on every reconcile — O(pods) per event,
the re-host of the reference's O(pods) rescans (syncflow.go:86-98). This
module maintains the same counters incrementally from watch deltas: each
committed pod mutation (or, in cache-lag mode, each cache application)
folds a small feature diff into a per-(namespace, podclique) counter row.
A reconcile then reads its counters in O(1) instead of re-deriving them.

Exactness contract: the counters must be BYTE-IDENTICAL to what a full
rescan of the same store view would produce (tests/test_aggregation.py
replays randomized event storms against both). The feature extraction below
therefore mirrors controller/podclique/status.py::reconcile_status exactly:
terminating pods are invisible; "updated" is keyed by the pod-template-hash
label (resolved against the PCLQ's own label at read time); error-exits and
started-not-ready reproduce the availability buckets of
reconcilestatus.go:205-215.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from grove_tpu.api import names as namegen
from grove_tpu.api.pod import (
    has_erroneous_exit,
    is_ready,
    is_schedule_gated,
    is_scheduled,
)


class PodCounters:
    """One PodClique's incremental pod-status counters (read-only to
    consumers; only the owning PodAggregate mutates them)."""

    __slots__ = (
        "total",
        "ready",
        "scheduled",
        "gated",
        "error_exits",
        "started_not_ready",
        "hash_counts",
    )

    def __init__(self) -> None:
        self.total = 0
        self.ready = 0
        self.scheduled = 0
        self.gated = 0
        self.error_exits = 0
        self.started_not_ready = 0
        self.hash_counts: Dict[str, int] = {}

    def updated(self, current_hash: Optional[str]) -> int:
        """Pods carrying `current_hash` (0 when the PCLQ has no hash yet —
        the falsy-hash guard in status.py::reconcile_status)."""
        if not current_hash:
            return 0
        return self.hash_counts.get(current_hash, 0)


# the empty row handed out for PodCliques with no live pods — shared,
# never mutated (PodAggregate only mutates rows it stored itself)
EMPTY_COUNTERS = PodCounters()

_Features = Tuple[int, int, int, int, int, int, Optional[str]]


def pod_features(pod) -> Optional[_Features]:
    """The pod's contribution vector to its PCLQ's counters, or None for
    terminating pods (excluded from every counter, status.py:54)."""
    if pod.metadata.deletion_timestamp is not None:
        return None
    ready = is_ready(pod)
    scheduled = is_scheduled(pod)
    err = has_erroneous_exit(pod)
    started = False
    for cs in pod.status.container_statuses:
        if cs.started:
            started = True
            break
    return (
        1,
        1 if ready else 0,
        1 if scheduled else 0,
        1 if is_schedule_gated(pod) else 0,
        # not-ready buckets of the MinAvailableBreached math
        # (reconcilestatus.go:205-215 via status.py:69-79)
        1 if (not ready and err) else 0,
        1 if (scheduled and not ready and not err and started) else 0,
        pod.metadata.labels.get(namegen.LABEL_POD_TEMPLATE_HASH),
    )


class PodAggregate:
    """Per-(namespace, podclique-label) counter rows, folded from deltas.

    One instance mirrors ONE store view (committed, or the lagged read
    cache); the Store applies every mutation of that view here, so reads
    are always exactly the full-rescan answer for that view.
    """

    __slots__ = ("_rows", "grand_total", "grand_ready")

    def __init__(self) -> None:
        self._rows: Dict[Tuple[str, str], PodCounters] = {}
        # this view's (total, ready) pod partial — the LEAF of the
        # hierarchical shard fold (runtime/shards.py ShardSummaryTree):
        # maintained here because _fold already computed the features, so
        # the level-1 cost is two int adds per event
        self.grand_total = 0
        self.grand_ready = 0

    def counters(self, namespace: str, pclq_name: str) -> PodCounters:
        return self._rows.get((namespace, pclq_name), EMPTY_COUNTERS)

    # -- maintenance (Store-internal) ------------------------------------

    def _fold(self, pod, sign: int) -> None:
        feats = pod_features(pod)
        if feats is None:
            return
        # view-wide partial first: EVERY live pod counts toward the shard
        # leaf (clique-labeled or not), so the hierarchical summary equals
        # a full non-terminating-pod rescan
        self.grand_total += sign * feats[0]
        self.grand_ready += sign * feats[1]
        pclq = pod.metadata.labels.get(namegen.LABEL_PODCLIQUE)
        if pclq is None:
            return
        key = (pod.metadata.namespace, pclq)
        row = self._rows.get(key)
        if row is None:
            row = self._rows[key] = PodCounters()
        row.total += sign * feats[0]
        row.ready += sign * feats[1]
        row.scheduled += sign * feats[2]
        row.gated += sign * feats[3]
        row.error_exits += sign * feats[4]
        row.started_not_ready += sign * feats[5]
        h = feats[6]
        if h is not None:
            n = row.hash_counts.get(h, 0) + sign
            if n:
                row.hash_counts[h] = n
            else:
                row.hash_counts.pop(h, None)
        if sign < 0 and row.total == 0 and not row.hash_counts:
            # bound memory: a fully-drained PCLQ (deleted set) drops its row
            self._rows.pop(key, None)

    def apply(self, type_: str, obj, old) -> None:
        """Fold one view mutation. `old` is the view's previous object for
        the same key (None for Added). Deleted folds the removed object out."""
        if obj.kind != "Pod":
            return
        if type_ == "Deleted":
            self._fold(old if old is not None else obj, -1)
            return
        if old is not None:
            self._fold(old, -1)
        self._fold(obj, +1)

    def rebuild(self, pods) -> None:
        """Recompute from scratch (full informer resync)."""
        self._rows.clear()
        self.grand_total = 0
        self.grand_ready = 0
        for pod in pods:
            self._fold(pod, +1)
