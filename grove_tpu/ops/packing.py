"""Batched all-or-nothing gang packing kernels (JAX/XLA, TPU-first).

The hot path of the framework: places G pending gangs onto N nodes with
hierarchical topology packing, replacing the external KAI scheduler of the
reference architecture (SURVEY §2, BASELINE.json north star).

Two kernels share one per-gang placement routine (`gang_select_and_fill`):

- `solve_packing` — EXACT sequential greedy: one `lax.scan` over gangs,
  matching the NumPy oracle decision-for-decision. The parity baseline.
- `solve_wave_chunk` — the SCALE path: a chunk of gangs is decided in
  parallel (vmap) against the same capacity snapshot, then committed by a
  cheap sequential capacity-check scan; conflicting gangs retry in the next
  wave (host loop in grove_tpu.solver.kernel). Wave convergence trades exact
  greedy order within a chunk for massive parallelism; quality is gated
  against the oracle (≤0.5% regression, BASELINE.md).

Design for the MXU/VPU + XLA compilation model: static shapes (bucketed
padding), wide vector math over the node axis, `segment_sum` over pre-sorted
contiguously-numbered topology domains, branch-free level selection, L+1
unrolled fused fills.

Semantics (mirroring the PodGang contract, scheduler podgang.go:50-114):
- a gang is ADMITTED iff every group places >= min_count pods (MinReplicas
  floor); extra pods up to `count` are placed best-effort with the gang.
- `req_level` (TopologyPackConstraint.Required): the gang must fit inside ONE
  domain at that level or narrower; no cluster-wide fallback.
- `pref_level` (…Preferred): that level is tried first, then levels closest
  to it (narrower wins ties), then cluster-wide scatter. -1 → narrowest.
- PlacementScore: level-weighted co-location — for each level, the fraction
  of the gang's pods inside its dominant domain, weighted toward narrow
  levels; 1.0 = everything inside one narrowest-level domain.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

_INT_CAP = 1 << 20  # cap on pods-per-node fit counts (avoid inf→int wrap)
# narrow-cap sentinel (lazy-rescue wave path): "retry with the cluster-wide
# fill only" — distinct from -1 ("no broader level; done for good")
_CLUSTER_RETRY = -2

# Segment count of the deterministic prefix sums below. 64 comfortably
# exceeds any mesh axis we shard the node dimension over (8-way today,
# headroom for a 64-chip slice), so every shard owns whole segments and the
# local scans never cross a shard boundary.
_SCAN_SEGMENTS = 64


def _seg_cumsum(a: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Deterministic, partition-safe inclusive prefix sum.

    ``jnp.cumsum`` over an axis the GSPMD partitioner has sharded is
    miscompiled on this image's XLA rev: the partitioned scan folds every
    replica of an UNUSED mesh axis into the result (measured: the
    node-axis capped-fit prefix sums came back exactly ``dp``-times too
    large under the (dp=4, tp=2) solver mesh — the root cause of the
    sharded-vs-single-device alloc/score divergence, PARITY.md). Instead
    of relying on that rewrite, restructure the scan into the textbook
    per-shard form XLA partitions mechanically and deterministically:

    - reshape the axis into ``[S, n/S]`` segments (S static, a multiple of
      any shard count we use, so segments never straddle shards),
    - local cumsum inside each segment (no cross-shard communication),
    - segment offsets via a strictly-upper-triangular matmul over the
      tiny ``[S]`` totals (dot-general partitioning is exact and
      deterministic — the "per-shard reduce" commit).

    Integer inputs (the capped-fit tables, pod counts, commit usage in
    quantized units) are EXACTLY associative, so the result is
    bit-identical to ``jnp.cumsum`` on any mesh including a single
    device. Float inputs get a fixed association that no mesh shape can
    change (the quantized solver tensors are integer-valued floats, so
    they too are exact). Axes not divisible by any power-of-two segment
    count fall back to the plain cumsum (such shapes cannot be evenly
    sharded in the first place)."""
    a = jnp.moveaxis(a, axis, -1)
    n = a.shape[-1]
    s = _SCAN_SEGMENTS
    while s > 1 and n % s:
        s //= 2
    if s <= 1:
        out = jnp.cumsum(a, axis=-1)
    else:
        parts = a.reshape(a.shape[:-1] + (s, n // s))
        local = jnp.cumsum(parts, axis=-1)
        totals = parts.sum(axis=-1)  # [..., S]
        # offs[t] = sum of totals of EARLIER segments (exclusive prefix)
        tri = jnp.triu(jnp.ones((s, s), a.dtype), k=1)
        offs = jnp.einsum("...s,st->...t", totals, tri)
        out = (local + offs[..., None]).reshape(a.shape)
    return jnp.moveaxis(out, -1, axis)


class GangInputs(NamedTuple):
    demand: jnp.ndarray  # [P, R]
    count: jnp.ndarray  # [P]
    min_count: jnp.ndarray  # [P]
    req_level: jnp.ndarray  # scalar
    pref_level: jnp.ndarray  # scalar
    # per-GROUP required pack level (-1 none): the PodGroup/PCSG constraint
    # tier — each group must fit inside ONE domain at its level, chosen
    # independently per group inside the gang's own domain
    group_req: jnp.ndarray = None  # [P]
    # pinned domain id per group at its required level (-1 none): recovery
    # replacements must rejoin the domain where the group's surviving pods
    # already live instead of re-choosing by free capacity
    group_pin: jnp.ndarray = None  # [P]
    # pinned domain id for the WHOLE gang at req_level (-1 none): a gang
    # with a gang-level required pack whose surviving pods already occupy a
    # domain must place its replacements in that same domain — otherwise a
    # recovery delta-solve could split the live gang across two domains in
    # violation of TopologyPackConstraint.Required
    gang_pin: jnp.ndarray = None  # scalar
    # topology SPREAD constraint (TopologySpreadConstraint): level whose
    # domains the gang's pods must be distributed across (-1 none). Composes
    # with packing: req_level packs the gang into one broad domain while
    # spread_level balances its pods across the narrower domains inside it
    # (e.g. pack within a slice, spread across hosts for fault tolerance).
    spread_level: jnp.ndarray = None  # scalar
    # minimum distinct domains the placement must span (effective floor is
    # min(spread_min, pods placed)); <=1 → balance only
    spread_min: jnp.ndarray = None  # scalar
    # hard vs soft: required spread rejects placements spanning fewer than
    # spread_min domains (DoNotSchedule); soft spread only shapes the score
    spread_required: jnp.ndarray = None  # scalar bool
    # recovery seed: SURVIVOR pod counts per spread-level domain ([D]) — a
    # delta-solve replacing failed pods must judge the spread of the LIVE
    # gang (survivors + replacements), and the balanced fill must steer
    # replacements away from already-loaded survivor domains (the spread
    # analogue of the pack path's gang_pin)
    spread_seed: jnp.ndarray = None  # [D]
    # demand-dedup PAIR index ([P] int32, None = dedup off): row u of the
    # chunk's shared `cs_pair [U, N+1]` capped-fit prefix-sum table for this
    # group's (demand row, count) pair. Gangs stamped from a handful of
    # templates repeat identical (demand, count) pairs ~100x in the stress
    # mix; the wave solver computes min(_pods_fit_per_node, count) + cumsum
    # once per UNIQUE pair per chunk, and each gang's candidate scan becomes
    # pure boundary gathers — BIT-exact (same integer ops on the same
    # values), the per-gang [P,N,R] divide and [P,N] cumsum disappear.
    # Row 0 is reserved all-zero: gangs masked out by the pending filter
    # (count == 0) redirect here on device.
    uidx: jnp.ndarray = None  # [P]


def _pods_fit_per_node(free: jnp.ndarray, demand_p: jnp.ndarray) -> jnp.ndarray:
    """k[n] = how many pods of this group fit on node n given free capacity."""
    safe = jnp.where(demand_p > 0, demand_p, 1.0)
    ratio = jnp.floor(free / safe[None, :])
    ratio = jnp.where(demand_p[None, :] > 0, ratio, jnp.inf)
    k = jnp.min(ratio, axis=1)
    return jnp.clip(k, 0, _INT_CAP).astype(jnp.int32)


def _fill_floors_first(free, mask, demand, count, min_count, uniform=False):
    """Two-phase fill: place every group's admission FLOOR first, then the
    best-effort extras — a full-count greedy would let an early group's
    extras starve a later group's floor (guaranteed gang scheduling is for
    MinReplicas; extras must never defeat it).

    Floors are clamped to the available count and extras to >= 0: a recovery
    delta-solve can momentarily have fewer pending pods than the remaining
    floor (count < min_count), and a negative extras count would corrupt the
    fill (negative allocations inflate free capacity). The clamped floor can
    never satisfy `placed_min >= min_count`, so such gangs correctly wait.

    `uniform` is a STATIC host-side flag: min_count == count for EVERY gang
    in the problem (the all-or-nothing common case — the whole stress mix).
    Then floors == min(count, count) == count and extras == 0 everywhere a
    fill runs with the gang's own counts, and the callers that substitute
    counts (spill: min_count=0; rescue: the gang's own uniform pair) keep
    the extras phase a provable no-op — so HALF the fill scans compile
    away, bit-exactly. (Spill's placed_min changes from 0 to placed, but
    its only consumer gates on cluster_rescue, which is False for spill.)
    Returns (alloc [P,N], placed [P], placed_min [P], free_after)."""
    if uniform:
        alloc, placed, free_after = _fill(
            free, mask, demand, jnp.minimum(min_count, count)
        )
        return alloc, placed, placed, free_after
    floors = jnp.minimum(min_count, count)
    extras = jnp.maximum(count - min_count, 0)
    alloc_min, placed_min, free1 = _fill(free, mask, demand, floors)
    alloc_ext, placed_ext, free2 = _fill(free1, mask, demand, extras)
    return alloc_min + alloc_ext, placed_min + placed_ext, placed_min, free2


def _fill_grouped(
    free, mask, demand, count, min_count, group_req, group_pin,
    topo, seg_starts, seg_ends, seed,
):
    """Floors-first fill honoring per-GROUP pack constraints: a group with
    group_req[p] >= 0 must land inside ONE domain at that level (chosen
    inside `mask`); unconstrained groups use `mask` directly. Floors of ALL
    groups place before any group's extras, and a constrained group's extras
    never leave its chosen domain.
    Returns (alloc [P,N], placed [P], placed_min [P], free_after)."""
    n_nodes, n_levels = topo.shape
    p_dim = demand.shape[0]
    floors = jnp.minimum(min_count, count)
    extras = jnp.maximum(count - min_count, 0)

    def group_mask(free_c, p):
        """Domain choice for group p at its required level (inside mask)."""
        k = _pods_fit_per_node(free_c, demand[p])
        k = jnp.minimum(jnp.where(mask, k, 0), jnp.maximum(floors[p], 1))
        cs = jnp.concatenate([jnp.zeros((1,), k.dtype), _seg_cumsum(k)])
        any_req = group_req[p] >= 0
        lvl = jnp.where(any_req, group_req[p], 0)
        starts = seg_starts[lvl]
        ends = seg_ends[lvl]
        K = cs[ends] - cs[starts]  # pods of group p fitting per domain
        feas = (K >= floors[p]) & (ends > starts)
        # capacity-weighted strided pick (seed 0 → deterministic first-best)
        w = jnp.where(feas, K, 0).astype(jnp.float32)
        cum_w = _seg_cumsum(w)
        h = jnp.mod(seed * jnp.int32(40503), 1 << 16).astype(jnp.float32) / (
            1 << 16
        )
        u = h * cum_w[-1]
        best = jnp.argmax(cum_w > u)
        best = jnp.where(cum_w[-1] > 0, best, jnp.argmax(feas))
        ok_any = jnp.any(feas)
        # recovery pin: rejoin the surviving pods' domain unconditionally
        # (the fill validates whether the floor still fits there)
        pinned = group_pin[p] >= 0
        best = jnp.where(pinned, group_pin[p], best)
        ok_any = ok_any | pinned
        slab = topo[:, lvl] == best
        return jnp.where(any_req, slab & mask & ok_any, mask)

    free_c = free
    masks = []
    alloc_rows = []
    floor_placed = []
    extra_placed = []
    for p in range(p_dim):  # static unroll (P small): floors first
        mask_p = group_mask(free_c, p)
        masks.append(mask_p)
        a, pl, free_c = _fill(free_c, mask_p, demand[p : p + 1], floors[p : p + 1])
        alloc_rows.append(a[0])
        floor_placed.append(pl[0])
    for p in range(p_dim):  # then extras, inside each group's own mask
        a, pl, free_c = _fill(free_c, masks[p], demand[p : p + 1], extras[p : p + 1])
        alloc_rows[p] = alloc_rows[p] + a[0]
        extra_placed.append(pl[0])
    alloc = jnp.stack(alloc_rows)
    placed_min = jnp.stack(floor_placed)
    placed = placed_min + jnp.stack(extra_placed)
    return alloc, placed, placed_min, free_c


def _fill_dispatch(
    grouped, free, mask, demand, count, min_count, group_req, group_pin,
    topo, seg_starts, seg_ends, seed, uniform=False,
):
    """Static dispatch: problems with no group-level constraints (the common
    case — checked host-side) compile the cheap two-phase fill; the grouped
    fill with per-group domain selection is only paid when used."""
    if grouped:
        return _fill_grouped(
            free, mask, demand, count, min_count, group_req, group_pin,
            topo, seg_starts, seg_ends, seed,
        )
    return _fill_floors_first(free, mask, demand, count, min_count, uniform)


def _fill(free, mask, demand, count, unroll=False):
    """Sequentially fill each group inside `mask` (nodes are topology-sorted,
    so the exclusive-cumsum take packs into contiguous domains first).
    `unroll` (static): unroll the group scan — worth it when P is small and
    the last group's carry (free_after) is dead downstream, which a scan
    must still compute but an unrolled chain lets XLA eliminate.
    Returns (alloc [P,N], placed [P], free_after)."""

    def group_step(free_c, inputs):
        demand_p, count_p = inputs
        k = _pods_fit_per_node(free_c, demand_p)
        # cap at the group's own count: bounds the int32 cumsum below at
        # count*N (a zero-demand group would otherwise contribute _INT_CAP
        # per node and wrap the prefix sum negative)
        k = jnp.minimum(jnp.where(mask, k, 0), count_p)
        cum = _seg_cumsum(k) - k  # exclusive prefix
        take = jnp.clip(count_p - cum, 0, k)
        free_c = free_c - take[:, None].astype(free_c.dtype) * demand_p[None, :]
        return free_c, (take, take.sum())

    free_after, (alloc, placed) = jax.lax.scan(
        group_step, free, (demand, count), unroll=unroll
    )
    return alloc, placed, free_after


def _fill_slab_pair(free, sl_start, sl_end, gang: GangInputs, cs_pair, eff):
    """Uniform fill over the contiguous node slab [sl_start, sl_end) using
    the chunk-shared capped-fit prefix tables (`cs_pair [U, N+1]`) instead
    of per-gang divides.

    Group 0 always fills against the pristine chunk snapshot (every gang
    in the wave decides against the same `free`), so its per-node take is
    pure boundary math on its pair's prefix row — the [N, R] divide, the
    [N] min-reduce AND the [N] cumsum of the generic fill all collapse
    into one row gather (bit-exact: the row is the same capped-fit cumsum
    the generic fill would compute). Later groups see free mutated by
    group 0's take, so they keep the generic path (unrolled: the final
    free update is dead and XLA removes it).

    Caller guarantees (static): uniform (floors == counts), no group
    constraints, no spread, no recovery pins, lazy_rescue (free_after is
    never consumed). Returns (alloc [P,N], placed [P])."""
    p_dim = gang.demand.shape[0]
    n_nodes = free.shape[0]
    cs0 = cs_pair[eff[0]]  # [N+1] row gather: capped-fit prefix sums
    k0 = cs0[1:] - cs0[:-1]  # capped per-node fits (recovered, no divide)
    n_idx = jnp.arange(n_nodes)
    in_slab = (n_idx >= sl_start) & (n_idx < sl_end)
    cnt0 = gang.count[0]
    # exclusive prefix WITHIN the slab = cs - cs[start] (zeros before the
    # slab never contribute; positions outside the slab are masked anyway)
    cumex = cs0[:-1] - cs0[sl_start]
    take0 = jnp.where(in_slab, jnp.clip(cnt0 - cumex, 0, k0), 0)
    placed0 = jnp.minimum(cnt0, cs0[sl_end] - cs0[sl_start])
    if p_dim == 1:
        return take0[None], placed0[None]
    free1 = free - take0[:, None].astype(free.dtype) * gang.demand[0][None, :]
    alloc_rest, placed_rest, _ = _fill(
        free1, in_slab, gang.demand[1:], gang.count[1:], unroll=True
    )
    alloc = jnp.concatenate([take0[None], alloc_rest], axis=0)
    placed = jnp.concatenate([placed0[None], placed_rest])
    return alloc, placed


def _spread_defaults(
    g_shape, spread_level, spread_min, spread_required, spread_seed
):
    """Fill unset spread tensors with their sentinels (no constraint).

    The seed defaults to a ZERO-WIDTH [G, 0] placeholder, not [G, D]: a
    full-width zeros tensor is ~200MB at stress scale (G=10k, D=5k node-
    level domains) and would be shipped to the device on every solve that
    carries no recovery seeds — i.e. almost all of them."""
    if spread_level is None:
        spread_level = jnp.full(g_shape, -1, dtype=jnp.int32)
    if spread_min is None:
        spread_min = jnp.zeros(g_shape, dtype=jnp.int32)
    if spread_required is None:
        spread_required = jnp.zeros(g_shape, dtype=bool)
    if spread_seed is None:
        spread_seed = jnp.zeros(tuple(g_shape) + (0,), dtype=jnp.int32)
    return spread_level, spread_min, spread_required, spread_seed


def _seed_or_none(spread_seed):
    """Treat the [.., 0] placeholder as 'no seed' (static shape check)."""
    if spread_seed is None or spread_seed.shape[-1] == 0:
        return None
    return spread_seed


def _spread_quota(
    K: jnp.ndarray, cnt: jnp.ndarray, load: jnp.ndarray
) -> jnp.ndarray:
    """Balanced (water-filling) per-domain quota: q[d] <= K[d],
    sum(q) = min(cnt, sum(K)), and max(q) minimized — the most even
    distribution of `cnt` pods over domains with capacities K.

    The water level t = smallest integer with sum(min(K, t)) >= cnt is found
    by a fixed 22-step bisection (counts are capped at _INT_CAP), then the
    overshoot sum(min(K, t)) - cnt is shaved off — f(t) - f(t-1) =
    #{K >= t} guarantees the overshoot is strictly smaller than the
    water-level set, so every quota stays >= t-1 >= 0."""

    def body(_, lohi):
        lo, hi = lohi
        mid = (lo + hi) // 2
        ge = jnp.sum(jnp.minimum(K, mid)) >= cnt
        return (jnp.where(ge, lo, mid + 1), jnp.where(ge, mid, hi))

    _, t = jax.lax.fori_loop(
        0, 22, body, (jnp.int32(0), jnp.int32(2 * _INT_CAP))
    )
    q0 = jnp.minimum(K, t)
    excess = jnp.sum(q0) - jnp.minimum(cnt, jnp.sum(K))
    at_level = K >= t
    # The overshoot is shaved off the MOST-LOADED water-level domains
    # (load = pods this gang already placed per domain by earlier groups).
    # Shaving a fixed domain order instead would skip the same domains for
    # every group, and a multi-group gang could systematically miss them —
    # load-aware shaving is what makes the per-group fills jointly span the
    # most domains. Ties break toward shaving the highest index, keeping
    # early domains occupied. Non-candidates sort last (load -1).
    d = K.shape[0]
    load_eff = jnp.where(at_level, load, -1)
    perm = jnp.lexsort((-jnp.arange(d), -load_eff))
    rank = jnp.argsort(perm)
    shave = at_level & (rank < excess)
    return q0 - shave.astype(jnp.int32)


def _fill_spread(
    free, mask, demand, count, topo_col, starts_l, ends_l, load0=None
):
    """Sequentially fill each group inside `mask`, BALANCING pods across the
    contiguous domains of one level instead of packing: per-group
    water-filled domain quotas (_spread_quota, load-aware so the groups
    jointly span the most domains), then an in-domain exclusive prefix take
    against each domain's quota. Same prefix-sum/gather-only structure as
    _fill — no scatters.
    Returns (alloc [P,N], placed [P], free_after, load [D])."""

    def group_step(carry, inputs):
        free_c, load = carry
        demand_p, count_p = inputs
        k = _pods_fit_per_node(free_c, demand_p)
        k = jnp.minimum(jnp.where(mask, k, 0), count_p)
        cs = jnp.concatenate([jnp.zeros((1,), k.dtype), _seg_cumsum(k)])
        K = cs[ends_l] - cs[starts_l]  # [D] per-domain fit counts
        q = _spread_quota(K, count_p, load)
        # in-domain exclusive prefix: node n's fill position inside its slab
        in_dom = cs[:-1] - cs[starts_l[topo_col]]
        take = jnp.clip(q[topo_col] - in_dom, 0, k)
        free_c = free_c - take[:, None].astype(free_c.dtype) * demand_p[None, :]
        cs_t = jnp.concatenate([jnp.zeros((1,), take.dtype), _seg_cumsum(take)])
        load = load + (cs_t[ends_l] - cs_t[starts_l])
        return (free_c, load), (take, take.sum())

    if load0 is None:
        load0 = jnp.zeros(starts_l.shape, dtype=jnp.int32)
    (free_after, load), (alloc, placed) = jax.lax.scan(
        group_step, (free, load0), (demand, count)
    )
    return alloc, placed, free_after, load


def _fill_spread_floors_first(
    free, mask, demand, count, min_count, topo_col, starts_l, ends_l,
    load0=None,
):
    """Floors-first two-phase spread fill (same contract as
    _fill_floors_first) plus the count of distinct domains the final
    placement spans at the spread level — including `load0` survivor
    domains on a recovery delta-solve.
    Returns (alloc [P,N], placed [P], placed_min [P], free_after, used)."""
    floors = jnp.minimum(min_count, count)
    extras = jnp.maximum(count - min_count, 0)
    alloc_min, placed_min, free1, load1 = _fill_spread(
        free, mask, demand, floors, topo_col, starts_l, ends_l, load0
    )
    alloc_ext, placed_ext, free2, load2 = _fill_spread(
        free1, mask, demand, extras, topo_col, starts_l, ends_l, load1
    )
    alloc = alloc_min + alloc_ext
    used = jnp.sum((load2 > 0).astype(jnp.int32))
    return alloc, placed_min + placed_ext, placed_min, free2, used


def _spread_select(gang: GangInputs, seg_starts, seg_ends, topo):
    """Per-gang spread-level segment views (safe index when unset)."""
    sl = jnp.maximum(gang.spread_level, 0)
    return (
        gang.spread_level >= 0,
        jnp.take(topo, sl, axis=1),
        seg_starts[sl],
        seg_ends[sl],
    )


def _dispatch_with_spread(
    spread, grouped, free, mask, gang: GangInputs,
    topo, seg_starts, seg_ends, seed, uniform=False,
):
    """Fill dispatch for problems that may mix spread and non-spread gangs:
    with the static `spread` flag off, exactly the plain dispatch; with it
    on, both variants are computed and selected per gang (spread problems
    pay the double fill, everyone else compiles it away).
    Returns (alloc, placed, placed_min, free_after, used, spread_on)."""
    if not spread:
        a, p, pm, f = _fill_dispatch(
            grouped, free, mask, gang.demand, gang.count, gang.min_count,
            gang.group_req, gang.group_pin, topo, seg_starts, seg_ends, seed,
            uniform,
        )
        return a, p, pm, f, jnp.int32(0), jnp.asarray(False)
    spread_on, topo_col, starts_l, ends_l = _spread_select(
        gang, seg_starts, seg_ends, topo
    )
    a_s, p_s, pm_s, f_s, used = _fill_spread_floors_first(
        free, mask, gang.demand, gang.count, gang.min_count,
        topo_col, starts_l, ends_l, _seed_or_none(gang.spread_seed),
    )
    a_n, p_n, pm_n, f_n = _fill_dispatch(
        grouped, free, mask, gang.demand, gang.count, gang.min_count,
        gang.group_req, gang.group_pin, topo, seg_starts, seg_ends, seed,
        uniform,
    )
    alloc = jnp.where(spread_on, a_s, a_n)
    placed = jnp.where(spread_on, p_s, p_n)
    placed_min = jnp.where(spread_on, pm_s, pm_n)
    free_after = jnp.where(spread_on, f_s, f_n)
    return alloc, placed, placed_min, free_after, used, spread_on


def _live_total(gang: GangInputs, placed_total):
    """Pods of the LIVE gang: this solve's placements plus recovery
    survivors (the seed) — the spread target is judged against both."""
    seed = _seed_or_none(gang.spread_seed)
    if seed is None:
        return placed_total
    return placed_total + jnp.sum(seed)


def _spread_admit(gang: GangInputs, spread_on, used, placed_total):
    """Hard-spread admission: a required spread rejects placements spanning
    fewer than min(spread_min, live pods) distinct domains (`used` already
    counts survivor domains via the seed load)."""
    eff = jnp.minimum(
        jnp.maximum(gang.spread_min, 1), _live_total(gang, placed_total)
    )
    return jnp.where(spread_on & gang.spread_required, used >= eff, True)


def _spread_score(gang: GangInputs, spread_on, used, placed_total, coloc):
    """Score select: a spread gang's PlacementScore is its domain coverage
    toward the spread target (1.0 = target met) — replacing the co-location
    score, whose objective points the other way."""
    eff = jnp.minimum(
        jnp.maximum(gang.spread_min, 1), _live_total(gang, placed_total)
    )
    cover = used.astype(jnp.float32) / jnp.maximum(eff, 1).astype(jnp.float32)
    return jnp.where(spread_on, jnp.clip(cover, 0.0, 1.0), coloc)


def _level_weights(num_levels: int) -> jnp.ndarray:
    w = jnp.arange(1, num_levels + 1, dtype=jnp.float32)
    return w / w.sum()


def _gang_pin_mask(
    free: jnp.ndarray, topo: jnp.ndarray, gang: GangInputs, pinned: bool
):
    """Node mask confining a pinned gang to its surviving pods' domain at
    req_level (all-true when unpinned), plus the capacity view with
    out-of-domain nodes zeroed so aggregate feasibility and domain selection
    never look outside the pin.

    `pinned` is a STATIC host-side flag (like `grouped`): the common case —
    no recovery pins anywhere in the problem — must not pay the per-gang
    [N]-gather + [N,R]-where this machinery costs (measured ~10% on the
    full-size CPU bench)."""
    if not pinned:
        return jnp.ones(topo.shape[:1], dtype=bool), free
    pin = gang.gang_pin if gang.gang_pin is not None else jnp.int32(-1)
    pin_on = (pin >= 0) & (gang.req_level >= 0)
    rq = jnp.maximum(gang.req_level, 0)
    pin_mask = jnp.where(pin_on, jnp.take(topo, rq, axis=1) == pin, True)
    free_vis = jnp.where(pin_mask[:, None], free, 0.0)
    return pin_mask, free_vis


def _aggregate_tables(free: jnp.ndarray, gang: GangInputs, cs_pair=None):
    """Shared prelude of both per-gang selectors: capped per-node fit counts,
    prefix-sum tables for boundary gathers, float-cumsum tolerance, and the
    admission floor's joint resource demand.

    `cs_pair [U, N+1]` (wave path only): pre-computed capped-fit prefix sums
    for the chunk's unique (demand, count) pairs against the SHARED capacity
    snapshot — the per-gang [P,N,R] divide, count cap, and [P,N] cumsum all
    collapse into the shared table; the level loop gathers the SAME integer
    values at segment boundaries (bit-exact). `cs_k` comes back None on that
    path. Only valid when every gang in the vmap sees the same `free` (never
    under recovery pins, whose `free_vis` differs per gang — caller guards)."""
    active = gang.count > 0
    if cs_pair is not None and gang.uidx is not None:
        cs_k = None  # level loop gathers from cs_pair via the gang's uidx
    else:
        k_all = jax.vmap(lambda d: _pods_fit_per_node(free, d))(gang.demand)  # [P,N]
        # cap per-node fits at the group count: preserves every >=min/>=count
        # comparison (sum-of-mins bound) while keeping int32 prefix sums exact
        k_all = jnp.minimum(k_all, gang.count[:, None])
        zero_col = jnp.zeros((k_all.shape[0], 1), dtype=k_all.dtype)
        cs_k = jnp.concatenate([zero_col, _seg_cumsum(k_all, axis=1)], axis=1)
    min_demand = jnp.sum(
        gang.min_count[:, None].astype(free.dtype) * gang.demand, axis=0
    )  # [R]
    cs_free = jnp.concatenate(
        [
            jnp.zeros((1, free.shape[1]), dtype=free.dtype),
            _seg_cumsum(free, axis=0),
        ],
        axis=0,
    )
    # float32 prefix sums of byte-scale capacity accumulate rounding error;
    # slack the joint check so it can only false-KEEP (the fill is exact)
    free_tol = 1e-5 * cs_free[-1]
    return active, cs_k, cs_free, free_tol, min_demand


def _coloc_score(
    alloc, placed_total, seg_starts, seg_ends, weights, ok, seg_list=None
):
    """Level-weighted dominant-domain co-location score (shared).

    `seg_list` (optional): per-level ragged (starts, ends) views — the
    padded [L, D] rows pad EVERY level to the broadest level's domain
    count (host level: one domain per node), so the boundary gathers of
    the narrow levels read mostly padding; the ragged views keep them at
    their true width (identical values — padding only appends empty
    ranges whose max can never win)."""
    n_levels = seg_starts.shape[0] if seg_list is None else len(seg_list)
    pods_per_node = alloc.sum(axis=0)
    total = jnp.maximum(placed_total.sum(), 1)
    cs_pods = jnp.concatenate(
        [jnp.zeros((1,), dtype=pods_per_node.dtype), _seg_cumsum(pods_per_node)]
    )

    def bounds(l):
        if seg_list is not None:
            return seg_list[l]
        return seg_starts[l], seg_ends[l]

    score = 0.0
    for l in range(n_levels):
        starts_l, ends_l = bounds(l)
        score = score + weights[l] * (
            jnp.max(cs_pods[ends_l] - cs_pods[starts_l]).astype(jnp.float32)
            / total.astype(jnp.float32)
        )
    return jnp.clip(jnp.where(ok, score, 0.0), 0.0, 1.0)


def gang_select_and_fill(
    free: jnp.ndarray,
    topo: jnp.ndarray,
    seg_starts: jnp.ndarray,  # [L, D] contiguous-domain boundaries
    seg_ends: jnp.ndarray,  # [L, D]
    gang: GangInputs,
    grouped: bool = False,
    pinned: bool = False,
    spread: bool = False,
    uniform: bool = False,
    seg_list=None,  # ragged per-level (starts, ends) views (see above)
):
    """One gang's placement decision against `free`.

    Shared by the exact sequential kernel (inside lax.scan) and the wave
    kernel (vmapped across a chunk against one capacity snapshot).
    Returns (free_new, alloc [P,N], placed [P], ok_min, chosen_l, score).

    Topology-sorted nodes make every domain a contiguous slab, so all
    per-domain aggregates are prefix-sum boundary gathers — no scatters
    (TPU scatters serialize; gathers vectorize).
    """
    n_nodes, n_levels = topo.shape
    weights = _level_weights(n_levels)

    pin_mask, free_vis = _gang_pin_mask(free, topo, gang, pinned)
    active, cs_k, cs_free, free_tol, min_demand = _aggregate_tables(
        free_vis, gang
    )
    any_active = jnp.any(active)
    all_nodes = jnp.ones((n_nodes,), dtype=bool)
    no_nodes = jnp.zeros((n_nodes,), dtype=bool)

    # Per-level candidate domain: per-group fit counts AND joint resource
    # feasibility (both optimistic w.r.t. fragmentation — the actual fill
    # below is the ground truth). Best-fit tie-break by smallest spare.
    def level_candidate(l):
        if seg_list is not None:
            starts, ends = seg_list[l]
        else:
            starts, ends = seg_starts[l], seg_ends[l]
        K = cs_k[:, ends] - cs_k[:, starts]  # [P, D] gather
        free_agg = cs_free[ends] - cs_free[starts]  # [D, R] gather
        feas = jnp.all(
            jnp.where(active[:, None], K >= gang.min_count[:, None], True),
            axis=0,
        )
        feas &= jnp.all(
            free_agg >= (min_demand - free_tol)[None, :], axis=1
        )
        feas &= ends > starts  # padded empty domains never selected
        feas &= any_active  # a fully-padded gang selects nothing
        # Best-fit: primary key is leftover fit-count (K is capped at the
        # gang's count, so full-fit domains tie at spare=0 — break the tie
        # toward the domain with the least total free capacity, preserving
        # large domains for large gangs)
        spare = jnp.sum(
            jnp.where(active[:, None], K - gang.count[:, None], 0), axis=0
        )
        free_total = jnp.sum(free_agg, axis=1)
        tie = free_total / (jnp.max(free_total) + 1.0)
        key = spare.astype(jnp.float32) + tie.astype(jnp.float32)
        best = jnp.argmin(jnp.where(feas, key, jnp.inf))
        return jnp.any(feas), best

    # Try the actual fill at every level (narrow masks included) plus a
    # cluster-wide candidate; choose by preference among levels whose fill
    # truly meets the admission floor. L is small and static → L+1 fused
    # unrolled fills.
    lv = jnp.arange(n_levels)
    min_allowed = jnp.where(gang.req_level >= 0, gang.req_level, 0)

    cand_alloc, cand_placed, cand_free, cand_ok, cand_used = [], [], [], [], []
    spread_on = jnp.asarray(False)
    for l in range(n_levels):
        ok_l, best_l = level_candidate(l)
        mask_l = jnp.where(ok_l, (topo[:, l] == best_l) & pin_mask, no_nodes)
        alloc_l, placed_l, placed_min_l, free_l, used_l, spread_on = (
            _dispatch_with_spread(
                spread, grouped, free, mask_l, gang,
                topo, seg_starts, seg_ends, jnp.int32(0), uniform,
            )
        )
        fill_ok = (
            ok_l
            & (lv[l] >= min_allowed)
            & jnp.all(jnp.where(active, placed_min_l >= gang.min_count, True))
            & _spread_admit(gang, spread_on, used_l, placed_l.sum())
        )
        cand_alloc.append(alloc_l)
        cand_placed.append(placed_l)
        cand_free.append(free_l)
        cand_ok.append(fill_ok)
        cand_used.append(used_l)
    # cluster-wide fallback (only when no required pack level)
    alloc_c, placed_c, placed_min_c, free_c, used_c, spread_on = (
        _dispatch_with_spread(
            spread, grouped, free, all_nodes, gang,
            topo, seg_starts, seg_ends, jnp.int32(0), uniform,
        )
    )
    cluster_ok = (
        (gang.req_level < 0)
        & any_active
        & jnp.all(jnp.where(active, placed_min_c >= gang.min_count, True))
        & _spread_admit(gang, spread_on, used_c, placed_c.sum())
    )
    cand_alloc.append(alloc_c)
    cand_placed.append(placed_c)
    cand_free.append(free_c)
    cand_ok.append(cluster_ok)
    cand_used.append(used_c)

    oks = jnp.stack(cand_ok)  # [L+1]
    # Preference order (TopologyPackConstraint.Preferred): preferred level
    # first, then closest levels (narrower wins ties), cluster-wide last.
    pref_eff = jnp.where(gang.pref_level >= 0, gang.pref_level, n_levels - 1)
    if spread:
        # spread gangs prefer the BROADEST allowed mask (their required pack
        # level, else the broadest level): a narrow mask holds few
        # spread-level domains, and narrow-first preference would leave a
        # SOFT (ScheduleAnyway) spread gang packed into one domain even
        # with the whole cluster free — the wave kernel (gang_select_single)
        # applies the same override, keeping the two kernels consistent
        pref_eff = jnp.where(
            spread_on, jnp.maximum(gang.req_level, 0), pref_eff
        )
    level_rank = 2 * (n_levels - jnp.abs(lv - pref_eff)) + (lv > pref_eff)
    # cluster rank 0 — EXCEPT for spread gangs with no required pack: the
    # cluster-wide mask holds every spread-level domain, while even the
    # broadest level candidate is a single domain of that level. Packing a
    # soft (ScheduleAnyway) spread gang into one broadest-level domain on a
    # free multi-root-domain cluster would defeat the spread; rank
    # cluster-wide ABOVE all level candidates for such gangs.
    cluster_rank = jnp.where(
        spread_on & (gang.req_level < 0),
        jnp.asarray(2 * (n_levels + 1), dtype=level_rank.dtype),
        jnp.asarray(0, dtype=level_rank.dtype),
    )
    pref_rank = jnp.concatenate([level_rank, cluster_rank[None]])
    chosen = jnp.argmax(jnp.where(oks, pref_rank + 1, 0))
    ok_min = jnp.any(oks)

    one_hot = jax.nn.one_hot(chosen, n_levels + 1, dtype=free.dtype)
    alloc = sum(
        one_hot[i] * cand_alloc[i].astype(free.dtype) for i in range(n_levels + 1)
    ).astype(jnp.int32)
    placed = sum(
        one_hot[i] * cand_placed[i].astype(free.dtype) for i in range(n_levels + 1)
    ).astype(jnp.int32)
    free_after = sum(one_hot[i] * cand_free[i] for i in range(n_levels + 1))
    used = sum(
        one_hot[i] * cand_used[i].astype(free.dtype) for i in range(n_levels + 1)
    ).astype(jnp.int32)

    # best-effort extras: pods beyond the packed domain scatter cluster-wide
    # (no gang-level required constraint, never for group-constrained groups
    # — their extras must stay inside their chosen domain — and never for
    # spread gangs, whose whole allocation comes from the balanced fill)
    chose_packed_level = ok_min & (chosen < n_levels)
    spill = (gang.req_level < 0) & chose_packed_level & ~spread_on
    remaining = jnp.where(
        spill & (gang.group_req < 0), gang.count - placed, 0
    )
    alloc2, placed2, free_after2 = _fill(free_after, all_nodes, gang.demand, remaining)
    alloc = jnp.where(spill, alloc + alloc2, alloc)
    placed_total = jnp.where(spill, placed + placed2, placed)
    free_final = jnp.where(spill, free_after2, free_after)

    # all-or-nothing: revert capacity if not admitted
    free_new = jnp.where(ok_min, free_final, free)
    alloc = jnp.where(ok_min, alloc, 0)
    placed_total = jnp.where(ok_min, placed_total, 0)
    any_level = ok_min & (chosen < n_levels)
    chosen_l = jnp.where(any_level, chosen, -1)

    score = _coloc_score(
        alloc, placed_total, seg_starts, seg_ends, weights, ok_min, seg_list
    )
    score = jnp.where(
        ok_min,
        _spread_score(gang, spread_on, used, placed_total.sum(), score),
        0.0,
    )

    return free_new, alloc, placed_total, ok_min, chosen_l, score


@partial(
    jax.jit,
    static_argnames=(
        "with_alloc", "grouped", "pinned", "spread", "uniform", "level_widths",
    ),
)
def solve_packing(
    capacity: jnp.ndarray,  # [N, R] float32
    topo: jnp.ndarray,  # [N, L] int32, dense ids per level
    seg_starts: jnp.ndarray,  # [L, D] contiguous-domain boundaries
    seg_ends: jnp.ndarray,  # [L, D]
    demand: jnp.ndarray,  # [G, P, R] float32
    count: jnp.ndarray,  # [G, P] int32
    min_count: jnp.ndarray,  # [G, P] int32
    req_level: jnp.ndarray,  # [G] int32 (-1 none)
    pref_level: jnp.ndarray,  # [G] int32 (-1 → narrowest)
    group_req: jnp.ndarray = None,  # [G, P] int32 (-1 none)
    group_pin: jnp.ndarray = None,  # [G, P] int32 (-1 none)
    gang_pin: jnp.ndarray = None,  # [G] int32 (-1 none)
    spread_level: jnp.ndarray = None,  # [G] int32 (-1 none)
    spread_min: jnp.ndarray = None,  # [G] int32
    spread_required: jnp.ndarray = None,  # [G] bool
    spread_seed: jnp.ndarray = None,  # [G, D] int32
    with_alloc: bool = True,
    grouped: bool = False,
    pinned: bool = False,
    spread: bool = False,
    uniform: bool = False,
    level_widths: tuple = None,  # ragged candidate scan (see solve_waves_device)
):
    """Exact sequential greedy (oracle-parity kernel)."""
    if group_req is None:
        group_req = jnp.full(count.shape, -1, dtype=jnp.int32)
    if group_pin is None:
        group_pin = jnp.full(count.shape, -1, dtype=jnp.int32)
    if gang_pin is None:
        gang_pin = jnp.full(count.shape[:1], -1, dtype=jnp.int32)
    spread_level, spread_min, spread_required, spread_seed = _spread_defaults(
        count.shape[:1], spread_level, spread_min, spread_required, spread_seed
    )

    seg_list = None
    if level_widths is not None:
        seg_list = tuple(
            (seg_starts[l, :w], seg_ends[l, :w])
            for l, w in enumerate(level_widths)
        )

    def gang_step(free, gang: GangInputs):
        free_new, alloc, placed, ok_min, chosen_l, score = gang_select_and_fill(
            free, topo, seg_starts, seg_ends, gang, grouped=grouped,
            pinned=pinned, spread=spread, uniform=uniform, seg_list=seg_list,
        )
        ys = (ok_min, placed, score, chosen_l)
        if with_alloc:
            ys = ys + (alloc,)
        return free_new, ys

    inputs = GangInputs(
        demand=demand,
        count=count,
        min_count=min_count,
        req_level=req_level,
        pref_level=pref_level,
        group_req=group_req,
        group_pin=group_pin,
        gang_pin=gang_pin,
        spread_level=spread_level,
        spread_min=spread_min,
        spread_required=spread_required,
        spread_seed=spread_seed,
    )
    free_after, ys = jax.lax.scan(gang_step, capacity, inputs)
    if with_alloc:
        admitted, placed, score, chosen_level, alloc = ys
    else:
        admitted, placed, score, chosen_level = ys
        alloc = None
    return {
        "admitted": admitted,
        "placed": placed,
        "score": score,
        "chosen_level": chosen_level,
        "alloc": alloc,
        "free_after": free_after,
    }


@partial(
    jax.jit,
    static_argnames=(
        "commit_iters", "grouped", "pinned", "spread", "uniform",
        "level_widths",
    ),
)
def solve_wave_chunk(
    free: jnp.ndarray,  # [N, R]
    topo: jnp.ndarray,  # [N, L]
    seg_starts: jnp.ndarray,  # [L, D]
    seg_ends: jnp.ndarray,  # [L, D]
    demand: jnp.ndarray,  # [C, P, R] — one CHUNK of gangs
    count: jnp.ndarray,  # [C, P]
    min_count: jnp.ndarray,  # [C, P]
    req_level: jnp.ndarray,  # [C]
    pref_level: jnp.ndarray,  # [C]
    pending: jnp.ndarray,  # [C] bool
    narrow_cap: jnp.ndarray,  # [C] int32
    seeds: jnp.ndarray,  # [C] int32
    group_req: jnp.ndarray = None,  # [C, P]
    group_pin: jnp.ndarray = None,  # [C, P]
    gang_pin: jnp.ndarray = None,  # [C]
    spread_level: jnp.ndarray = None,  # [C]
    spread_min: jnp.ndarray = None,  # [C]
    spread_required: jnp.ndarray = None,  # [C]
    spread_seed: jnp.ndarray = None,  # [C, D]
    pair_demand: jnp.ndarray = None,  # [U, R]
    pair_count: jnp.ndarray = None,  # [U]
    pair_idx: jnp.ndarray = None,  # [C, P]
    commit_iters: int = 2,
    grouped: bool = False,
    pinned: bool = False,
    spread: bool = False,
    uniform: bool = False,
    level_widths: tuple = None,
):
    """One wave over one chunk, with per-pod allocations materialized (the
    binding path). Same core as the device-resident stats solver."""
    seg_list = None
    if level_widths is not None:
        seg_list = tuple(
            (seg_starts[l, :w], seg_ends[l, :w])
            for l, w in enumerate(level_widths)
        )
    if group_req is None:
        group_req = jnp.full(count.shape, -1, dtype=jnp.int32)
    if group_pin is None:
        group_pin = jnp.full(count.shape, -1, dtype=jnp.int32)
    if gang_pin is None:
        gang_pin = jnp.full(count.shape[:1], -1, dtype=jnp.int32)
    spread_level, spread_min, spread_required, spread_seed = _spread_defaults(
        count.shape[:1], spread_level, spread_min, spread_required, spread_seed
    )
    free_after, accept, placed, score, chosen, retry, new_cap, fill_failed, alloc = (
        wave_chunk_core(
            free,
            topo,
            seg_starts,
            seg_ends,
            demand,
            count,
            min_count,
            req_level,
            pref_level,
            pending,
            narrow_cap,
            seeds,
            group_req,
            group_pin,
            gang_pin,
            spread_level,
            spread_min,
            spread_required,
            spread_seed,
            commit_iters,
            grouped,
            pinned,
            spread,
            pair_dem=pair_demand,
            pair_cap=pair_count,
            uidx=pair_idx,
            uniform=uniform,
            seg_list=seg_list,
        )
    )
    n_levels = topo.shape[1]
    return {
        "admitted": accept,
        "retry": retry,
        "new_cap": new_cap,
        "placed": jnp.where(accept[:, None], placed, 0),
        "score": jnp.where(accept, score, 0.0),
        "chosen_level": jnp.where(
            accept, jnp.where(chosen >= n_levels, -1, chosen), -1
        ),
        "alloc": jnp.where(accept[:, None, None], alloc, 0),
        "free_after": free_after,
    }


@partial(
    jax.jit,
    static_argnames=(
        "commit_iters", "grouped", "pinned", "spread", "uniform",
    ),
)
def solve_wave_chunk_stack(
    free,  # [B, N, R] — one capacity snapshot per stacked subproblem
    topo,  # [B, N, L]
    seg_starts,  # [B, L, D]
    seg_ends,  # [B, L, D]
    demand,  # [B, C, P, R] — one CHUNK of gangs per subproblem lane
    count,  # [B, C, P]
    min_count,  # [B, C, P]
    req_level,  # [B, C]
    pref_level,  # [B, C]
    pending,  # [B, C] bool
    narrow_cap,  # [B, C] int32
    seeds,  # [B, C] int32
    group_req,  # [B, C, P]
    group_pin,  # [B, C, P]
    gang_pin,  # [B, C]
    spread_level,  # [B, C]
    spread_min,  # [B, C]
    spread_required,  # [B, C]
    spread_seed,  # [B, C, D]
    commit_iters: int = 2,
    grouped: bool = False,
    pinned: bool = False,
    spread: bool = False,
    uniform: bool = False,
):
    """One wave over one chunk of EVERY stacked subproblem lane at once —
    the partitioned-frontier batch dispatch (solver/frontier.py).

    Node-disjoint subproblems padded to one shape are stacked on a leading
    batch axis and decided in a single ``jax.vmap`` of the exact same
    :func:`wave_chunk_core` the host-loop binding path runs per problem, so
    B small same-shape solves cost one kernel dispatch instead of B. Each
    lane carries its OWN capacity snapshot, topology slabs and narrow-cap
    state — lanes never read or write each other's rows, which is what
    makes the per-lane results bit-identical to solving each subproblem
    alone (pinned by the frontier selfcheck). Inert padding lanes (zero
    capacity, zero counts, pending False) are provably no-ops: a zero
    count zeroes the fill and the commit mask, leaving free untouched.

    Static flags are the OR over the whole stack (uniform: the AND): a
    lane without groups/pins/spread takes the same values through the
    flagged code paths (the kernel's documented flag-equivalences), so
    mixed stacks stay exact."""

    def lane(
        free_b, topo_b, ss_b, se_b, dem_b, cnt_b, mn_b, rq_b, pf_b,
        pend_b, ncap_b, seed_b, grq_b, gpin_b, gangpin_b,
        slvl_b, smin_b, sreq_b, sseed_b,
    ):
        free_after, accept, placed, score, chosen, retry, new_cap, _ff, alloc = (
            wave_chunk_core(
                free_b, topo_b, ss_b, se_b,
                dem_b, cnt_b, mn_b, rq_b, pf_b, pend_b, ncap_b, seed_b,
                grq_b, gpin_b, gangpin_b, slvl_b, smin_b, sreq_b, sseed_b,
                commit_iters, grouped, pinned, spread, uniform=uniform,
            )
        )
        n_levels = topo_b.shape[1]
        # identical post-processing to solve_wave_chunk so the stacked
        # lane and the per-problem host path can never diverge
        return (
            free_after,
            accept,
            retry,
            new_cap,
            jnp.where(accept[:, None], placed, 0),
            jnp.where(accept, score, 0.0),
            jnp.where(
                accept, jnp.where(chosen >= n_levels, -1, chosen), -1
            ),
            jnp.where(accept[:, None, None], alloc, 0),
        )

    return jax.vmap(lane)(
        free, topo, seg_starts, seg_ends, demand, count, min_count,
        req_level, pref_level, pending, narrow_cap, seeds,
        group_req, group_pin, gang_pin,
        spread_level, spread_min, spread_required, spread_seed,
    )


# ---------------------------------------------------------------------------
# Wave-solver core (shared by the chunked binding path and the
# device-resident stats loop)
# ---------------------------------------------------------------------------


def wave_chunk_core(
    free, topo, seg_starts, seg_ends,
    dem, cnt, mn, rq, pf, pend, ncap, seeds, grq, gpin, gangpin,
    spreadlvl, spreadmin, spreadreq, spreadseed, commit_iters,
    grouped=False, pinned=False, spread=False,
    pair_dem=None, pair_cap=None, uidx=None, uniform=False,
    lazy_rescue=False, seg_list=None,
):
    """Decide one chunk of gangs in parallel (gang_select_single vmapped over
    the chunk against one capacity snapshot), commit via iterative vectorized
    prefix-acceptance with a final joint-feasibility guarantee, and produce
    the retry/narrow-cap bookkeeping for the next wave.

    `pair_dem [U,R]` + `pair_cap [U]` + `uidx [C,P]` (optional, encode-time
    demand dedup — kernel.dedup_demand): the candidate scan's capped-fit
    prefix sums are computed once per UNIQUE (demand, count) pair against
    the shared snapshot; each gang's level loop then gathers the SAME
    integer values at segment boundaries (bit-exact), eliminating the
    per-gang divide + cumsum that dominates wave 1 in template-stamped
    populations. Disabled under `pinned` (per-gang `free_vis` breaks the
    shared-snapshot premise).
    Returns (free, accept, placed, score, chosen, retry, new_cap,
    fill_failed, alloc)."""
    assert not lazy_rescue or uniform, (
        "lazy_rescue requires the uniform invariant: only then is the "
        "extras spill provably empty"
    )
    cnt = cnt * pend[:, None]
    use_dedup = pair_dem is not None and uidx is not None and not pinned
    cs_pair = None
    if use_dedup:
        fit_pair = jax.vmap(
            lambda d, cap: jnp.minimum(_pods_fit_per_node(free, d), cap)
        )(pair_dem, pair_cap)  # [U, N]
        cs_pair = jnp.concatenate(
            [
                jnp.zeros((fit_pair.shape[0], 1), dtype=fit_pair.dtype),
                _seg_cumsum(fit_pair, axis=1),
            ],
            axis=1,
        )  # [U, N+1]
    inputs = GangInputs(
        dem, cnt, mn, rq, pf, grq, gpin, gangpin,
        spreadlvl, spreadmin, spreadreq, spreadseed,
        uidx if use_dedup else None,
    )
    alloc, placed, ok, chosen, score, had_cand, fallback_cap = jax.vmap(
        lambda *xs: gang_select_single(
            *xs, grouped=grouped, pinned=pinned, spread=spread,
            uniform=uniform, lazy_rescue=lazy_rescue,
        ),
        in_axes=(None, None, None, None, 0, 0, 0, None, None),
    )(free, topo, seg_starts, seg_ends, inputs, ncap, seeds, cs_pair, seg_list)

    usage = jnp.einsum("cpn,cpr->cnr", alloc.astype(free.dtype), dem)  # [C,N,R]
    accept = ok
    for _ in range(commit_iters):
        cum = _seg_cumsum(jnp.where(accept[:, None, None], usage, 0), axis=0)
        fits = jnp.all(cum <= free[None] + 1e-6, axis=(1, 2))
        accept = ok & fits
    # final guarantee: with this accept set, every accepted prefix fits
    cum = _seg_cumsum(jnp.where(accept[:, None, None], usage, 0), axis=0)
    fits = jnp.all(cum <= free[None] + 1e-6, axis=(1, 2))
    accept &= fits
    free = free - jnp.sum(jnp.where(accept[:, None, None], usage, 0), axis=0)

    # retry bookkeeping: a failed fill jumps the cap straight to the next
    # broader aggregate-feasible level; cluster fallback was already
    # attempted in-wave, so a -1 cap means the gang is done for good
    fill_failed = pend & had_cand & ~ok
    new_cap = jnp.where(fill_failed, fallback_cap, ncap)
    min_allowed = jnp.where(rq >= 0, rq, 0)
    retry = pend & ((ok & ~accept) | (fill_failed & (new_cap >= min_allowed)))
    if lazy_rescue:
        # deferred cluster rescues carry the sentinel cap and MUST retry
        retry = retry | (pend & fill_failed & (new_cap == _CLUSTER_RETRY))
    return (
        free,
        accept & pend,
        placed,
        score,
        chosen,
        retry,
        new_cap,
        fill_failed,
        alloc,
    )


def gang_select_single(
    free, topo, seg_starts, seg_ends, gang: GangInputs, narrow_cap, seed,
    cs_pair=None, seg_list=None,
    grouped: bool = False, pinned: bool = False, spread: bool = False,
    uniform: bool = False, lazy_rescue: bool = False,
):
    """Single-fill variant of gang_select_and_fill for the wave solver.

    Candidate levels are ranked by aggregate feasibility (cheap prefix-sum
    gathers); ONE fill is attempted at the best allowed level (or
    cluster-wide when none). A fill that misses the floor is signalled to the
    caller, which lowers `narrow_cap` (the narrowest level this gang may try)
    and retries next wave — amortizing the L+1 fills of the exact kernel
    across waves instead of paying them per gang.

    Returns (alloc, placed, ok, chosen, score, had_candidate, fallback_cap).
    chosen: level index, n_levels for cluster-wide, -1 when nothing allowed.
    fallback_cap: the retry narrow-cap for a fill-failed gang — the next
    BROADER aggregate-feasible level, -1 when none remains, or the
    _CLUSTER_RETRY sentinel (-2, lazy_rescue only) meaning "retry with the
    cluster-wide fill next wave" (wave_chunk_core's retry rule understands
    the sentinel).
    """
    n_nodes, n_levels = topo.shape
    weights = _level_weights(n_levels)

    pin_mask, free_vis = _gang_pin_mask(free, topo, gang, pinned)
    active, cs_k, cs_free, free_tol, min_demand = _aggregate_tables(
        free_vis, gang, cs_pair
    )
    any_active = jnp.any(active)
    if cs_k is None:
        # dedup path: redirect masked-out gangs (count zeroed by the pending
        # filter) to the reserved all-zero row 0, then gather the capped-fit
        # prefix sums at segment boundaries only
        eff = jnp.where(active, gang.uidx, 0)

    oks, bests = [], []
    for l in range(n_levels):
        # ragged per-level views when provided: the padded [L, D] rows pad
        # every level to the broadest level's width (host level = N
        # domains), so the narrow levels' [P, D] boundary gathers below
        # would read ~4x more padding than data at stress shape
        # (1/1/80/640/5120 real domains, all padded to 5120)
        if seg_list is not None:
            starts, ends = seg_list[l]
        else:
            starts, ends = seg_starts[l], seg_ends[l]
        if cs_k is None:
            K = (
                cs_pair[eff[:, None], ends[None, :]]
                - cs_pair[eff[:, None], starts[None, :]]
            )  # [P, D]
        else:
            K = cs_k[:, ends] - cs_k[:, starts]
        free_agg = cs_free[ends] - cs_free[starts]
        feas = jnp.all(
            jnp.where(active[:, None], K >= gang.min_count[:, None], True), axis=0
        )
        feas &= jnp.all(free_agg >= (min_demand - free_tol)[None, :], axis=1)
        feas &= ends > starts
        feas &= any_active
        # STRIDED choice: gangs deciding in parallel against the same
        # capacity snapshot must not all pick the same best-fit domain (the
        # whole chunk would collide at commit). Each gang takes the
        # (seed mod n)-th domain among the candidates — perfect spread, and
        # co-location score is unaffected by WHICH single domain is chosen.
        # Prefer domains that hold the FULL count (extras stay in-domain
        # instead of spilling cluster-wide, which would dilute the score).
        feas_full = feas & jnp.all(
            jnp.where(active[:, None], K >= gang.count[:, None], True), axis=0
        )
        pool = jnp.where(jnp.any(feas_full), feas_full, feas)
        # CAPACITY-WEIGHTED pick: spread gangs across candidate domains in
        # proportion to how many copies of this gang each domain can host —
        # commits per wave then approach the capacity-limited maximum.
        w = jnp.where(pool, jnp.sum(K, axis=0), 0).astype(jnp.float32)
        cum_w = _seg_cumsum(w)
        total_w = cum_w[-1]
        h = (
            jnp.mod(seed * jnp.int32(40503), 1 << 16).astype(jnp.float32)
            / (1 << 16)
        )
        u = h * total_w
        best = jnp.argmax(cum_w > u)
        # degenerate fallback (all weights zero): first pool domain
        best = jnp.where(total_w > 0, best, jnp.argmax(pool))
        oks.append(jnp.any(feas))
        bests.append(best)
    oks = jnp.stack(oks)
    bests = jnp.stack(bests)

    lv = jnp.arange(n_levels)
    min_allowed = jnp.where(gang.req_level >= 0, gang.req_level, 0)
    allowed = oks & (lv >= min_allowed) & (lv <= narrow_cap)
    pref_eff = jnp.where(gang.pref_level >= 0, gang.pref_level, n_levels - 1)
    if spread:
        # a spread gang gets ONE fill attempt per wave: aim at the broadest
        # allowed mask (its required pack level, else the broadest level) —
        # a narrow mask holds few spread-level domains, and walking broader
        # via fill-failure retries would burn a wave per level
        s_on = gang.spread_level >= 0 if gang.spread_level is not None else (
            jnp.asarray(False)
        )
        pref_eff = jnp.where(s_on, jnp.maximum(gang.req_level, 0), pref_eff)
        # spread gangs with no required pack go straight to the cluster-wide
        # fill: it sees every spread-level domain, whereas any level
        # candidate is a single domain — packing there would leave a soft
        # spread gang un-spread on a free multi-root-domain cluster (the
        # exact kernel applies the same cluster-over-levels override)
        allowed = allowed & ~(s_on & (gang.req_level < 0))
    level_rank = 2 * (n_levels - jnp.abs(lv - pref_eff)) + (lv > pref_eff)
    has_level = jnp.any(allowed)
    chosen_level = jnp.argmax(jnp.where(allowed, level_rank + 1, 0))
    use_cluster = (~has_level) & (gang.req_level < 0) & any_active
    had_candidate = has_level | use_cluster

    # Slab fast path (the stress-bench configuration): with the dedup
    # tables present and no grouped/spread/pin machinery, every fill mask
    # is a contiguous node slab — the chosen level's picked domain, the
    # whole cluster, or nothing — so the fill can run on slab BOUNDS and
    # reuse the chunk-shared prefix tables instead of per-gang divides
    # (_fill_slab_pair). lazy_rescue is required because this path never
    # materializes free_after (the eager rescue consumes it).
    use_slab_fill = (
        cs_pair is not None and gang.uidx is not None and uniform
        and lazy_rescue and not grouped and not spread and not pinned
    )
    if use_slab_fill:
        sl_start = jnp.where(
            has_level,
            seg_starts[chosen_level, bests[chosen_level]],
            jnp.int32(0),
        )
        sl_end = jnp.where(
            has_level,
            seg_ends[chosen_level, bests[chosen_level]],
            jnp.where(use_cluster, jnp.int32(n_nodes), jnp.int32(0)),
        )
        alloc, placed = _fill_slab_pair(
            free, sl_start, sl_end, gang, cs_pair, eff
        )
        placed_min = placed  # uniform: floors ARE the counts
        used, spread_on = jnp.int32(0), jnp.asarray(False)
    else:
        all_nodes = jnp.ones((n_nodes,), dtype=bool)
        no_nodes = jnp.zeros((n_nodes,), dtype=bool)
        packed_mask = (topo[:, chosen_level] == bests[chosen_level]) & pin_mask
        mask = jnp.where(
            has_level, packed_mask, jnp.where(use_cluster, all_nodes, no_nodes)
        )

        alloc, placed, placed_min, free_after, used, spread_on = (
            _dispatch_with_spread(
                spread, grouped, free, mask, gang,
                topo, seg_starts, seg_ends, seed, uniform,
            )
        )
    level_fill_ok = (
        had_candidate
        & any_active
        & jnp.all(jnp.where(active, placed_min >= gang.min_count, True))
        & _spread_admit(gang, spread_on, used, placed.sum())
    )

    # when the level fill fails, the retry cap jumps straight to the next
    # BROADER level whose aggregates looked feasible (skip hopeless levels)
    lower_feasible = jnp.where(allowed & (lv < chosen_level), lv, -1)
    fallback_cap = jnp.max(lower_feasible)

    if lazy_rescue:
        # uniform-only fast path (caller asserts): the extras spill is
        # provably empty (placed == count whenever the level fill met the
        # floor), and the cluster rescue is DEFERRED to the next wave via
        # the _CLUSTER_RETRY narrow-cap sentinel — the retry wave is
        # compacted and nearly free, while the in-wave second fill below
        # costs a full dispatch for EVERY gang in EVERY wave. A deferred
        # gang's next decide sees no allowed level (cap sentinel) and
        # takes the existing use_cluster branch, i.e. the same
        # cluster-wide fill, one wave later against fresher capacity.
        # Boundary: a gang that defers on the LAST wave (max_waves
        # exhausted, or the no-progress early-exit fires) would never get
        # the cluster attempt the eager path makes in-wave — CLOSED by the
        # solve_waves_device epilogue, which runs exactly the deferred
        # residue through one final pass after the wave loop (admission
        # parity at budget exhaustion is pinned by test_solver.py::
        # test_lazy_rescue_deferral_at_max_waves_matches_eager).
        defer = (
            has_level
            & ~level_fill_ok
            & (gang.req_level < 0)
            & (fallback_cap < 0)
            & any_active
        )
        fallback_cap = jnp.where(
            defer, jnp.int32(_CLUSTER_RETRY), fallback_cap
        )
        fill_ok = level_fill_ok
    else:
        # Second fill doubles as both paths:
        # - level fill met the floor → best-effort extras spill cluster-wide
        # - level fill missed the floor AND no broader feasible level remains
        #   (and no required pack) → cluster-wide scatter as a last resort;
        #   otherwise the gang retries at the fallback level next wave,
        #   keeping it packed instead of eagerly scattering
        cluster_rescue = (
            has_level
            & ~level_fill_ok
            & (gang.req_level < 0)
            & (fallback_cap < 0)
            & any_active
        )
        # spread gangs never spill: their whole allocation comes from the
        # balanced fill (rescue still applies — it re-runs the spread fill
        # cluster-wide, where more domains are visible)
        spill = level_fill_ok & has_level & (gang.req_level < 0) & ~spread_on
        base_free = jnp.where(cluster_rescue, free, free_after)
        # extras of group-constrained groups must stay inside their chosen
        # domain — only unconstrained groups may spill cluster-wide
        spillable = gang.group_req < 0
        remaining = jnp.where(
            cluster_rescue,
            gang.count,
            jnp.where(spill & spillable, gang.count - placed, 0),
        )
        rescue_min = jnp.where(cluster_rescue, gang.min_count, 0)
        alloc2, placed2, placed2_min, _, used2, _ = _dispatch_with_spread(
            spread, grouped, base_free, all_nodes,
            gang._replace(count=remaining, min_count=rescue_min),
            topo, seg_starts, seg_ends, seed, uniform,
        )
        rescue_ok = (
            cluster_rescue
            & jnp.all(jnp.where(active, placed2_min >= gang.min_count, True))
            & _spread_admit(gang, spread_on, used2, placed2.sum())
        )
        alloc = jnp.where(
            rescue_ok, alloc2, jnp.where(spill, alloc + alloc2, alloc)
        )
        placed = jnp.where(
            rescue_ok, placed2, jnp.where(spill, placed + placed2, placed)
        )
        used = jnp.where(rescue_ok, used2, used)
        fill_ok = level_fill_ok | rescue_ok
        chosen_level = jnp.where(rescue_ok, n_levels, chosen_level)
        has_level = has_level & ~rescue_ok
        use_cluster = use_cluster | rescue_ok

    # shared epilogue (lazy and eager): mask out failed fills, score, pick
    # the reported level
    alloc = jnp.where(fill_ok, alloc, 0)
    placed = jnp.where(fill_ok, placed, 0)

    score = _coloc_score(
        alloc, placed, seg_starts, seg_ends, weights, fill_ok, seg_list
    )
    score = jnp.where(
        fill_ok, _spread_score(gang, spread_on, used, placed.sum(), score), 0.0
    )

    chosen = jnp.where(
        has_level, chosen_level, jnp.where(use_cluster, n_levels, -1)
    )
    return alloc, placed, fill_ok, chosen, score, had_candidate, fallback_cap


@partial(
    jax.jit,
    static_argnames=(
        "n_chunks", "max_waves", "commit_iters", "grouped", "pinned",
        "spread", "uniform", "lazy_rescue", "level_widths",
    ),
)
def solve_waves_device(
    capacity,  # [N, R]
    topo,  # [N, L]
    seg_starts,  # [L, D]
    seg_ends,  # [L, D]
    demand,  # [G, P, R], G divisible by n_chunks
    count,  # [G, P]
    min_count,  # [G, P]
    req_level,  # [G]
    pref_level,  # [G]
    group_req=None,  # [G, P]
    group_pin=None,  # [G, P]
    gang_pin=None,  # [G]
    spread_level=None,  # [G]
    spread_min=None,  # [G]
    spread_required=None,  # [G]
    spread_seed=None,  # [G, D]
    pair_demand=None,  # [U, R] encode-time demand dedup (kernel.dedup_demand)
    pair_count=None,  # [U]
    pair_idx=None,  # [G, P]
    n_chunks: int = 20,
    max_waves: int = 8,
    # ZERO refinement passes — the final joint-feasibility mask alone.
    # Safety: the final cumsum includes usage of gangs the mask then
    # rejects, so every accepted gang's own prefix is <= the checked cum —
    # the accepted set is always jointly feasible, just conservatively
    # small (rejected-by-inflation gangs retry in a compacted, nearly-free
    # wave). Refinement iterations buy within-wave acceptances at one
    # [C,N,R] cumsum+reduce pass each; measured full-size, 2 -> 1 -> 0
    # gave 29.9 -> 28.2 -> (post-lazy) 17.4 -> 16.4 s with IDENTICAL
    # admissions/score — the strided capacity-weighted domain picks
    # already avoid most intra-chunk collisions. The host-loop binding
    # path keeps 2 (its waves are not compacted).
    commit_iters: int = 0,
    grouped: bool = False,
    pinned: bool = False,
    spread: bool = False,
    uniform: bool = False,
    lazy_rescue: bool = False,
    # per-level REAL domain counts (static; host-derived from the
    # topology): lets the candidate scan and score use ragged per-level
    # segment views instead of rows padded to the broadest level's width
    level_widths: tuple = None,
):
    """Whole multi-wave wave-parallel solve in ONE device program — zero
    host↔device round trips until the final results (critical when the chip
    sits behind a high-latency link, and cheap dispatch regardless).

    Per wave, per chunk: decide all C gangs in parallel against the chunk's
    capacity snapshot (gang_select_single), then commit with an iterative
    vectorized prefix-acceptance (no per-gang scan): accept the set of gangs
    whose cumulative usage fits, re-checking `commit_iters` times as rejected
    gangs' usage is removed, with a final masking pass that guarantees the
    accepted set is jointly feasible. Conflicting or fill-failed gangs retry
    in the next wave (fill failures lower the gang's narrow_cap so it retries
    at a coarser level).
    """
    g_total, p_max, _ = demand.shape
    n_nodes, n_levels = topo.shape
    if group_req is None:
        group_req = jnp.full((g_total, p_max), -1, dtype=jnp.int32)
    if group_pin is None:
        group_pin = jnp.full((g_total, p_max), -1, dtype=jnp.int32)
    if gang_pin is None:
        gang_pin = jnp.full((g_total,), -1, dtype=jnp.int32)
    spread_level, spread_min, spread_required, spread_seed = _spread_defaults(
        (g_total,), spread_level, spread_min, spread_required, spread_seed
    )
    use_dedup = (
        pair_demand is not None
        and pair_count is not None
        and pair_idx is not None
        and not pinned
    )
    c = g_total // n_chunks
    seg_list = None
    if level_widths is not None:
        seg_list = tuple(
            (seg_starts[l, :w], seg_ends[l, :w])
            for l, w in enumerate(level_widths)
        )

    def reshape_chunks(a):
        return a.reshape((n_chunks, c) + a.shape[1:])

    state0 = {
        "free": capacity,
        "pending": jnp.ones((g_total,), dtype=bool),
        "narrow_cap": jnp.full((g_total,), n_levels - 1, dtype=jnp.int32),
        "admitted": jnp.zeros((g_total,), dtype=bool),
        "placed": jnp.zeros((g_total, p_max), dtype=jnp.int32),
        "score": jnp.zeros((g_total,), dtype=jnp.float32),
        "chosen": jnp.full((g_total,), -1, dtype=jnp.int32),
        "rescue": jnp.zeros((g_total,), dtype=bool),
        "wave": jnp.asarray(0, dtype=jnp.int32),
        "progress": jnp.asarray(True),
    }

    def chunk_step(free, xs):
        # settled chunks skip the whole decision+commit (lax.cond executes
        # one branch): waves after the first mostly touch a few chunks
        dem, pend, ncap = xs[0], xs[5], xs[6]
        c_gangs = dem.shape[0]

        def passthrough(free):
            return free, (
                jnp.zeros((c_gangs,), dtype=bool),
                jnp.zeros((c_gangs, dem.shape[1]), dtype=jnp.int32),
                jnp.zeros((c_gangs,), dtype=jnp.float32),
                jnp.full((c_gangs,), -1, dtype=jnp.int32),
                jnp.zeros((c_gangs,), dtype=bool),
                ncap,
                jnp.zeros((c_gangs,), dtype=bool),
            )

        return jax.lax.cond(
            jnp.any(pend), lambda f: _active_chunk_step(f, xs), passthrough, free
        )

    def _active_chunk_step(free, xs):
        (
            dem, cnt, mn, rq, pf, pend, ncap, seeds, grq, gpin, gangpin,
            slvl, smin, sreq, sseed,
        ) = xs[:15]
        uidx_c = xs[15] if use_dedup else None
        free, accept, placed, score, chosen, retry, new_cap, fill_failed, _ = (
            wave_chunk_core(
                free, topo, seg_starts, seg_ends,
                dem, cnt, mn, rq, pf, pend, ncap, seeds, grq, gpin, gangpin,
                slvl, smin, sreq, sseed,
                commit_iters, grouped, pinned, spread,
                pair_dem=pair_demand if use_dedup else None,
                pair_cap=pair_count if use_dedup else None,
                uidx=uidx_c,
                uniform=uniform,
                lazy_rescue=lazy_rescue,
                seg_list=seg_list,
            )
        )
        return free, (accept, placed, score, chosen, retry, new_cap, fill_failed)

    def wave_body(state):
        # COMPACTION: pending gangs are packed to the FRONT (stable, so
        # in-wave order among pending gangs is preserved) before chunking —
        # a wave's cost is per ACTIVE chunk (the settled-chunk lax.cond
        # skips whole chunks only), and without compaction the stragglers
        # of late waves are scattered across nearly every chunk, making
        # each late wave cost almost as much as wave 1 (measured: 383 ms x
        # 80 chunks on the full-size CPU run). Wave 1 has everything
        # pending, so its order — and therefore the headline first-wave
        # placement — is IDENTICAL to the uncompacted solver; later waves
        # regroup only which retry gangs share a commit chunk. Each gang
        # keeps its own seed through the permutation.
        order = jnp.argsort(~state["pending"], stable=True)
        inv = jnp.argsort(order, stable=True)
        seeds = jnp.arange(g_total, dtype=jnp.int32) + state["wave"] * jnp.int32(
            7919
        )

        def permute(a):
            return jnp.take(a, order, axis=0)

        free, ys = jax.lax.scan(
            chunk_step,
            state["free"],
            tuple(
                reshape_chunks(permute(a))
                for a in (
                    demand,
                    count,
                    min_count,
                    req_level,
                    pref_level,
                    state["pending"],
                    state["narrow_cap"],
                    seeds,
                    group_req,
                    group_pin,
                    gang_pin,
                    spread_level,
                    spread_min,
                    spread_required,
                    spread_seed,
                )
                + ((pair_idx,) if use_dedup else ())
            ),
        )
        accept, placed, score, chosen, retry, new_cap, fill_failed = (
            jnp.take(y.reshape((g_total,) + y.shape[2:]), inv, axis=0)
            for y in ys
        )
        return {
            "free": free,
            "pending": retry,
            "narrow_cap": new_cap,
            "admitted": state["admitted"] | accept,
            "placed": jnp.where(accept[:, None], placed, state["placed"]),
            "score": jnp.where(accept, score, state["score"]),
            "chosen": jnp.where(accept, chosen, state["chosen"]),
            # gangs whose heuristic single fill ever missed the floor are
            # exact-tail candidates (the seed-picked domain may simply have
            # been the wrong one)
            "rescue": state["rescue"] | fill_failed,
            "wave": state["wave"] + 1,
            "progress": jnp.any(accept) | jnp.any(retry),
        }

    def cond(state):
        return (
            (state["wave"] < max_waves)
            & state["progress"]
            & jnp.any(state["pending"] | (state["wave"] == 0))
        )

    final = jax.lax.while_loop(cond, wave_body, state0)
    if lazy_rescue:
        # Budget-boundary epilogue (round-4 advisor #3 / verdict weak #6):
        # a gang that DEFERS its cluster rescue on the final wave exits the
        # loop with the _CLUSTER_RETRY sentinel still pending and would
        # never get the cluster attempt the eager path makes in-wave. Run
        # ONE more pass restricted to exactly that residue: with the
        # sentinel cap, the deferred gang's decide sees no allowed level
        # and takes the ordinary use_cluster branch — the same cluster-wide
        # fill the eager path would have run, so admissions match the
        # eager path at budget exhaustion. Other pending gangs (level
        # retries that ran out of waves) are EXCLUDED: giving them an extra
        # level attempt would over-admit relative to eager-with-max_waves.
        deferred = final["pending"] & (
            final["narrow_cap"] == jnp.int32(_CLUSTER_RETRY)
        )

        def _epilogue(state):
            epi = wave_body({**state, "pending": deferred})
            return {
                **epi,
                "pending": epi["pending"] | (state["pending"] & ~deferred),
            }

        # deferral on the exact final wave is rare; skip the extra full
        # wave pass entirely when nothing deferred
        final = jax.lax.cond(
            jnp.any(deferred), _epilogue, lambda state: state, final
        )
    chosen = final["chosen"]
    return {
        "admitted": final["admitted"],
        "placed": final["placed"],
        "score": final["score"],
        "chosen_level": jnp.where(chosen >= n_levels, -1, chosen),
        "free_after": final["free"],
        "waves": final["wave"],
        "pending": final["pending"]
        | (final["rescue"] & ~final["admitted"]),
    }
