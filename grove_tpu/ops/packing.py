"""Batched all-or-nothing gang packing kernel (JAX/XLA, TPU-first).

The hot path of the framework: places G pending gangs onto N nodes with
hierarchical topology packing, replacing the external KAI scheduler of the
reference architecture (SURVEY §2, BASELINE.json north star).

Design for the MXU/VPU + XLA compilation model:
- ONE `lax.scan` over gangs (sequential commit is inherent to all-or-nothing
  packing: each admission consumes capacity) — everything inside a step is
  wide vector math over the node axis, which XLA fuses and vectorizes.
- static shapes everywhere: problems are padded into size buckets so each
  bucket compiles once and is cached.
- topology choice is computed for ALL levels with `segment_sum` over
  pre-sorted, contiguously-numbered domains, then the narrowest feasible
  allowed level is selected branch-free.

Semantics (mirroring the PodGang contract, scheduler podgang.go:50-114):
- a gang is ADMITTED iff every group places >= min_count pods (MinReplicas
  floor); extra pods up to `count` are placed best-effort with the gang.
- `req_level` (TopologyPackConstraint.Required): the gang must fit inside ONE
  domain at that level or narrower; no cluster-wide fallback.
- `pref_level` (…Preferred): narrower levels are tried first; falls back to
  broader levels, then cluster-wide scatter when no single domain fits.
- PlacementScore: level-weighted co-location — for each level, the fraction
  of the gang's pods inside its dominant domain, weighted toward narrow
  levels; 1.0 = everything on one node-domain at the narrowest level.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

_INT_CAP = 1 << 20  # cap on pods-per-node fit counts (avoid inf→int wrap)


class GangInputs(NamedTuple):
    demand: jnp.ndarray  # [P, R]
    count: jnp.ndarray  # [P]
    min_count: jnp.ndarray  # [P]
    req_level: jnp.ndarray  # scalar
    pref_level: jnp.ndarray  # scalar


def _pods_fit_per_node(free: jnp.ndarray, demand_p: jnp.ndarray) -> jnp.ndarray:
    """k[n] = how many pods of this group fit on node n given free capacity."""
    safe = jnp.where(demand_p > 0, demand_p, 1.0)
    ratio = jnp.floor(free / safe[None, :])
    ratio = jnp.where(demand_p[None, :] > 0, ratio, jnp.inf)
    k = jnp.min(ratio, axis=1)
    return jnp.clip(k, 0, _INT_CAP).astype(jnp.int32)


def _fill(free, mask, demand, count):
    """Sequentially fill each group inside `mask` (nodes are topology-sorted,
    so the exclusive-cumsum take packs into contiguous domains first).
    Returns (alloc [P,N], placed [P], free_after)."""

    def group_step(free_c, inputs):
        demand_p, count_p = inputs
        k = _pods_fit_per_node(free_c, demand_p)
        # cap at the group's own count: bounds the int32 cumsum below at
        # count*N (a zero-demand group would otherwise contribute _INT_CAP
        # per node and wrap the prefix sum negative)
        k = jnp.minimum(jnp.where(mask, k, 0), count_p)
        cum = jnp.cumsum(k) - k  # exclusive prefix
        take = jnp.clip(count_p - cum, 0, k)
        free_c = free_c - take[:, None].astype(free_c.dtype) * demand_p[None, :]
        return free_c, (take, take.sum())

    free_after, (alloc, placed) = jax.lax.scan(group_step, free, (demand, count))
    return alloc, placed, free_after


def _level_weights(num_levels: int) -> jnp.ndarray:
    w = jnp.arange(1, num_levels + 1, dtype=jnp.float32)
    return w / w.sum()


@partial(jax.jit, static_argnames=("with_alloc",))
def solve_packing(
    capacity: jnp.ndarray,  # [N, R] float32
    topo: jnp.ndarray,  # [N, L] int32, dense ids per level
    demand: jnp.ndarray,  # [G, P, R] float32
    count: jnp.ndarray,  # [G, P] int32
    min_count: jnp.ndarray,  # [G, P] int32
    req_level: jnp.ndarray,  # [G] int32 (-1 none)
    pref_level: jnp.ndarray,  # [G] int32 (-1 → narrowest)
    with_alloc: bool = True,
):
    n_nodes, n_levels = topo.shape
    nseg = n_nodes  # dense per-level domain ids are < N
    weights = _level_weights(n_levels)

    def gang_step(free, gang: GangInputs):
        active = gang.count > 0
        any_active = jnp.any(active)
        k_all = jax.vmap(lambda d: _pods_fit_per_node(free, d))(gang.demand)  # [P,N]
        # aggregate resource demand of the admission floor (joint check)
        min_demand = jnp.sum(
            gang.min_count[:, None].astype(free.dtype) * gang.demand, axis=0
        )  # [R]

        all_nodes = jnp.ones((n_nodes,), dtype=bool)
        no_nodes = jnp.zeros((n_nodes,), dtype=bool)

        # Per-level candidate domain: per-group fit counts AND joint resource
        # feasibility (both optimistic w.r.t. fragmentation — the actual fill
        # below is the ground truth). Best-fit tie-break by smallest spare.
        def level_candidate(l):
            seg = topo[:, l]
            K = jax.vmap(
                lambda kp: jax.ops.segment_sum(kp, seg, num_segments=nseg)
            )(k_all)  # [P, nseg]
            free_agg = jax.vmap(
                lambda col: jax.ops.segment_sum(col, seg, num_segments=nseg),
                in_axes=1,
                out_axes=1,
            )(free)  # [nseg, R]
            feas = jnp.all(
                jnp.where(active[:, None], K >= gang.min_count[:, None], True),
                axis=0,
            )
            feas &= jnp.all(free_agg >= min_demand[None, :], axis=1)
            feas &= any_active  # a fully-padded gang selects nothing
            spare = jnp.sum(
                jnp.where(active[:, None], K - gang.count[:, None], 0), axis=0
            )
            best = jnp.argmin(jnp.where(feas, spare, jnp.inf).astype(jnp.float32))
            return jnp.any(feas), best

        # Try the actual fill at every level (narrow masks included) plus a
        # cluster-wide candidate; choose the narrowest allowed level whose
        # fill truly meets the admission floor. L is small and static, so
        # this unrolls into L+1 fused fills.
        lv = jnp.arange(n_levels)
        min_allowed = jnp.where(gang.req_level >= 0, gang.req_level, 0)

        cand_alloc, cand_placed, cand_free, cand_ok = [], [], [], []
        for l in range(n_levels):
            ok_l, best_l = level_candidate(l)
            mask_l = jnp.where(ok_l, topo[:, l] == best_l, no_nodes)
            alloc_l, placed_l, free_l = _fill(free, mask_l, gang.demand, gang.count)
            fill_ok = (
                ok_l
                & (lv[l] >= min_allowed)
                & jnp.all(jnp.where(active, placed_l >= gang.min_count, True))
            )
            cand_alloc.append(alloc_l)
            cand_placed.append(placed_l)
            cand_free.append(free_l)
            cand_ok.append(fill_ok)
        # cluster-wide fallback (only when no required pack level)
        alloc_c, placed_c, free_c = _fill(free, all_nodes, gang.demand, gang.count)
        cluster_ok = (
            (gang.req_level < 0)
            & any_active
            & jnp.all(jnp.where(active, placed_c >= gang.min_count, True))
        )
        cand_alloc.append(alloc_c)
        cand_placed.append(placed_c)
        cand_free.append(free_c)
        cand_ok.append(cluster_ok)

        oks = jnp.stack(cand_ok)  # [L+1]
        # Preference order (TopologyPackConstraint.Preferred): try the
        # preferred level first, then levels closest to it (narrower wins
        # ties), cluster-wide last. pref_level=-1 → narrowest level first.
        pref_eff = jnp.where(
            gang.pref_level >= 0, gang.pref_level, n_levels - 1
        )
        level_rank = 2 * (n_levels - jnp.abs(lv - pref_eff)) + (lv > pref_eff)
        pref_rank = jnp.concatenate(
            [level_rank, jnp.zeros((1,), dtype=level_rank.dtype)]
        )  # cluster rank 0
        chosen = jnp.argmax(jnp.where(oks, pref_rank + 1, 0))
        ok_min = jnp.any(oks)

        one_hot = jax.nn.one_hot(chosen, n_levels + 1, dtype=free.dtype)
        alloc = sum(
            one_hot[i] * cand_alloc[i].astype(free.dtype)
            for i in range(n_levels + 1)
        ).astype(jnp.int32)
        placed = sum(
            one_hot[i] * cand_placed[i].astype(free.dtype)
            for i in range(n_levels + 1)
        ).astype(jnp.int32)
        free_after = sum(one_hot[i] * cand_free[i] for i in range(n_levels + 1))

        # best-effort extras: pods beyond the packed domain scatter
        # cluster-wide (no required constraint only)
        chose_packed_level = ok_min & (chosen < n_levels)
        spill = (gang.req_level < 0) & chose_packed_level
        remaining = jnp.where(spill, gang.count - placed, 0)
        alloc2, placed2, free_after2 = _fill(
            free_after, all_nodes, gang.demand, remaining
        )
        alloc = jnp.where(spill, alloc + alloc2, alloc)
        placed_total = jnp.where(spill, placed + placed2, placed)
        free_final = jnp.where(spill, free_after2, free_after)

        # all-or-nothing: revert capacity if not admitted
        free_new = jnp.where(ok_min, free_final, free)
        alloc = jnp.where(ok_min, alloc, 0)
        placed_total = jnp.where(ok_min, placed_total, 0)
        any_level = ok_min & (chosen < n_levels)
        chosen_l = jnp.where(any_level, chosen, -1)

        # placement score: level-weighted dominant-domain co-location
        pods_per_node = alloc.sum(axis=0)
        total = jnp.maximum(placed_total.sum(), 1)

        def level_coloc(l):
            agg = jax.ops.segment_sum(pods_per_node, topo[:, l], num_segments=nseg)
            return jnp.max(agg).astype(jnp.float32) / total.astype(jnp.float32)

        score = sum(
            weights[l] * level_coloc(l) for l in range(n_levels)
        )
        score = jnp.clip(jnp.where(ok_min, score, 0.0), 0.0, 1.0)

        ys = (ok_min, placed_total, score, chosen_l)
        if with_alloc:
            ys = ys + (alloc,)
        return free_new, ys

    inputs = GangInputs(
        demand=demand,
        count=count,
        min_count=min_count,
        req_level=req_level,
        pref_level=pref_level,
    )
    free_after, ys = jax.lax.scan(gang_step, capacity, inputs)
    if with_alloc:
        admitted, placed, score, chosen_level, alloc = ys
    else:
        admitted, placed, score, chosen_level = ys
        alloc = None
    return {
        "admitted": admitted,
        "placed": placed,
        "score": score,
        "chosen_level": chosen_level,
        "alloc": alloc,
        "free_after": free_after,
    }
