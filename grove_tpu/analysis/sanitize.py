"""Runtime sanitizer mode (``GROVE_TPU_SANITIZE=1``): dynamic twins of the
invariants grovelint cannot prove statically.

Four checks, all off unless the env var is set (and most also need an
explicit :func:`install` so they can hook the process-global singletons):

- **Lock-order assertions**: :class:`TrackingLock` wraps the well-known
  singleton locks (tracer, events, metrics, hashing evictor); each
  acquisition while holding another lock records an ordered edge, and an
  acquisition that would invert an observed edge (a cycle) is recorded as
  a violation — the dynamic twin of grovelint's GL009.
- **Store write-path byte-compare guard**: generalizes
  ``GROVE_TPU_STORE_GUARD`` — with sanitize on, every Store keeps
  canonical blobs on the copy-on-write path and
  ``verify_readonly_integrity()`` byte-compares committed objects at
  harness boundaries (see :func:`store_guard_enabled`).
- **Accountant-vs-recount**: :func:`accountant_drift` compares the
  incremental quota accountant against a full ``usage_oracle`` recount
  (shared with the chaos harness's per-tick invariant 3a).
- **Leaked-span / stranded-hold detection at teardown**:
  :func:`harness_problems` reports spans opened but never ended (via the
  tracing module's span hook) and monitor-held gangs with no scheduled
  backoff release.

One ``make chaos-matrix`` seed runs under the sanitizer
(scripts/chaos_smoke.py ``--sanitize-seed``), so every check executes in
anger on every matrix run. Stdlib-only: importable from the observability
singletons and the store without cycles.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Set, Tuple


def enabled() -> bool:
    """True when the process runs in sanitizer mode."""
    return os.environ.get("GROVE_TPU_SANITIZE", "").lower() not in (
        "",
        "0",
        "false",
    )


def store_guard_enabled() -> bool:
    """The store's byte-compare write guard: its dedicated env var, OR
    sanitize mode (the sanitizer generalizes the guard)."""
    if os.environ.get("GROVE_TPU_STORE_GUARD", "").lower() not in (
        "",
        "0",
        "false",
    ):
        return True
    return enabled()


# ---------------------------------------------------------------------------
# lock-order tracking
# ---------------------------------------------------------------------------


class LockOrderTracker:
    """Observed lock-acquisition partial order + inversion detection.

    Thread-local held-lock stacks; a global edge set ``(outer, inner)``.
    Acquiring B while holding A adds A→B; if a path B→…→A was already
    observed, the acquisition inverts the established order and is
    recorded (not raised — raising mid-acquisition could wedge the very
    code being sanitized; the harness asserts at teardown)."""

    def __init__(self) -> None:
        self._tls = threading.local()
        self._meta = threading.Lock()
        self.edges: Dict[Tuple[str, str], int] = {}
        self.violations: List[str] = []
        self._reported: Set[Tuple[str, str]] = set()

    def _held(self) -> List[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _path_exists(self, src: str, dst: str) -> bool:
        seen = {src}
        frontier = [src]
        while frontier:
            node = frontier.pop()
            for (a, b) in self.edges:
                if a == node and b not in seen:
                    if b == dst:
                        return True
                    seen.add(b)
                    frontier.append(b)
        return False

    def note_acquire(self, name: str) -> None:
        held = self._held()
        if held:
            with self._meta:
                for outer in held:
                    if outer == name:
                        continue
                    key = (outer, name)
                    if key not in self.edges and self._path_exists(
                        name, outer
                    ):
                        pair = (name, outer)
                        if pair not in self._reported:
                            self._reported.add(pair)
                            self.violations.append(
                                f"lock-order inversion: acquired {name!r}"
                                f" while holding {outer!r}, but the order"
                                f" {name!r} -> ... -> {outer!r} was"
                                " already observed"
                            )
                    self.edges.setdefault(key, 0)
                    self.edges[key] += 1
        held.append(name)

    def note_release(self, name: str) -> None:
        held = self._held()
        if name in held:
            held.remove(name)

    def observed_order(self) -> List[str]:
        return sorted(f"{a} -> {b}" for (a, b) in self.edges)


class TrackingLock:
    """Drop-in wrapper over a real lock reporting to a LockOrderTracker.
    Supports the `with` protocol and acquire/release, which is everything
    the wrapped singletons use."""

    def __init__(self, inner, name: str, tracker: LockOrderTracker) -> None:
        self._inner = inner
        self.name = name
        self._tracker = tracker

    def acquire(self, *args, **kwargs) -> bool:
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self._tracker.note_acquire(self.name)
        return got

    def release(self) -> None:
        self._tracker.note_release(self.name)
        self._inner.release()

    def __enter__(self) -> "TrackingLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False


# ---------------------------------------------------------------------------
# span-leak tracking (hooks grove_tpu.observability.tracing.SPAN_HOOK)
# ---------------------------------------------------------------------------


class SpanLeakTracker:
    """Open-span ledger fed by the tracing module's span hook."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._open: Dict[int, str] = {}

    def span_opened(self, span) -> None:
        with self._lock:
            self._open[id(span)] = span.name

    def span_closed(self, span) -> None:
        with self._lock:
            self._open.pop(id(span), None)

    def leaked(self) -> List[str]:
        with self._lock:
            return sorted(self._open.values())


# ---------------------------------------------------------------------------
# pure checks shared with the chaos harness
# ---------------------------------------------------------------------------


def accountant_drift(accountant, store) -> List[str]:
    """Incremental quota accountant vs. a full usage_oracle recount —
    the tick-boundary exactness check (chaos invariant 3a and the
    sanitizer teardown both call this)."""
    from grove_tpu.quota.oracle import usage_oracle

    accountant.ensure_built(store)
    oracle = usage_oracle(store.scan("Pod"), accountant.default_queue)
    snap = accountant.snapshot()
    problems: List[str] = []
    for q in sorted(set(snap) | set(oracle)):
        a, b = snap.get(q, {}), oracle.get(q, {})
        for r in sorted(set(a) | set(b)):
            if abs(a.get(r, 0.0) - b.get(r, 0.0)) > 1e-6:
                problems.append(
                    f"queue {q} usage {r}: accountant {a.get(r, 0.0)}"
                    f" != recount {b.get(r, 0.0)}"
                )
    return problems


def stranded_holds(monitor) -> List[str]:
    """Monitor-held gangs with no scheduled backoff release — a hold that
    would wait forever (chaos invariant 5 and the teardown check)."""
    problems: List[str] = []
    for gang_key in sorted(monitor._held):
        wq_key = ("PodGang",) + gang_key
        if not monitor.requeue.has_delayed(wq_key):
            problems.append(
                f"held gang {gang_key[0]}/{gang_key[1]} has no scheduled"
                " backoff release (stranded)"
            )
    return problems


# ---------------------------------------------------------------------------
# install / teardown
# ---------------------------------------------------------------------------


class Sanitizer:
    def __init__(self) -> None:
        self.lock_order = LockOrderTracker()
        self.spans = SpanLeakTracker()
        self._restores: List = []

    # -- tracing SPAN_HOOK protocol --------------------------------------

    def span_opened(self, span) -> None:
        self.spans.span_opened(span)

    def span_closed(self, span) -> None:
        self.spans.span_closed(span)

    # -- wiring -----------------------------------------------------------

    def wrap_lock(self, holder, attr: str, name: str) -> None:
        inner = getattr(holder, attr)
        if isinstance(inner, TrackingLock):
            return
        setattr(holder, attr, TrackingLock(inner, name, self.lock_order))
        self._restores.append((holder, attr, inner))

    def unwrap_all(self) -> None:
        for holder, attr, inner in reversed(self._restores):
            setattr(holder, attr, inner)
        self._restores.clear()

    # -- teardown verdict -------------------------------------------------

    def problems(self) -> List[str]:
        out = list(self.lock_order.violations)
        out.extend(f"leaked span: {name}" for name in self.spans.leaked())
        return out


SANITIZER: Optional[Sanitizer] = None


def active() -> bool:
    return SANITIZER is not None


def install() -> Sanitizer:
    """Engage the sanitizer: set the env flag (so stores built from here
    on keep guard blobs), wrap the singleton locks, and hook span
    open/close. Idempotent; pair with :func:`uninstall`."""
    global SANITIZER
    if SANITIZER is not None:
        return SANITIZER
    san = Sanitizer()
    # save the caller's env value so uninstall() restores rather than
    # clobbers an externally-set GROVE_TPU_SANITIZE
    san._prior_env = os.environ.get("GROVE_TPU_SANITIZE")
    os.environ["GROVE_TPU_SANITIZE"] = "1"
    from grove_tpu.api import hashing
    from grove_tpu.observability import tracing
    from grove_tpu.observability.events import EVENTS
    from grove_tpu.observability.metrics import METRICS
    from grove_tpu.observability.tracing import TRACER

    san.wrap_lock(TRACER, "_lock", "Tracer._lock")
    san.wrap_lock(EVENTS, "_lock", "EventRecorder._lock")
    san.wrap_lock(METRICS, "_lock", "Metrics._lock")
    san.wrap_lock(hashing, "_EVICT_LOCK", "api.hashing:_EVICT_LOCK")
    tracing.SPAN_HOOK = san
    san._tracer_was_enabled = TRACER.enabled
    TRACER.enable()  # leaked-span detection needs real spans
    SANITIZER = san
    return san


def uninstall() -> None:
    global SANITIZER
    san = SANITIZER
    if san is None:
        return
    from grove_tpu.observability import tracing
    from grove_tpu.observability.tracing import TRACER

    san.unwrap_all()
    tracing.SPAN_HOOK = None
    if not getattr(san, "_tracer_was_enabled", True):
        TRACER.disable()
    prior = getattr(san, "_prior_env", None)
    if prior is None:
        os.environ.pop("GROVE_TPU_SANITIZE", None)
    else:
        os.environ["GROVE_TPU_SANITIZE"] = prior
    SANITIZER = None


def harness_problems(harness) -> List[str]:
    """Teardown sweep over one SimHarness: lock order, leaked spans,
    stranded holds, accountant drift, store byte-compare integrity.
    Returns a flat problem list (empty = sanitized run stayed green)."""
    problems: List[str] = []
    if SANITIZER is not None:
        problems.extend(SANITIZER.problems())
    monitor = getattr(harness, "node_monitor", None)
    if monitor is not None:
        problems.extend(stranded_holds(monitor))
    scheduler = getattr(harness, "scheduler", None)
    quota = getattr(scheduler, "quota", None) if scheduler else None
    if quota is not None:
        problems.extend(
            f"accountant drift: {p}"
            for p in accountant_drift(quota.accountant, harness.store)
        )
    verify = getattr(harness.store, "verify_readonly_integrity", None)
    if verify is not None:
        try:
            verify()
        except AssertionError as e:
            problems.append(f"store guard: {e}")
    return problems
