"""grovelint: project-invariant static analysis + runtime sanitizer.

Two halves, one subsystem (docs/static-analysis.md):

- **Static analyzer** (`engine.py` + `rules/`): an AST-based rule engine
  enforcing the invariants this codebase's correctness rests on — virtual
  clock everywhere the sim/solver/control plane runs, every voluntary
  eviction behind a DisruptionBroker grant, every solve masked through
  ``Node.schedulable``, store writes through the copy-on-write path, JAX
  hygiene inside jitted kernels, registered event reasons, closed spans,
  non-blocking reconcile bodies, consistent lock order, and wire-decodable
  public API types. Run it via ``make lint`` / ``scripts/lint.py``.

- **Runtime sanitizer** (`sanitize.py`, ``GROVE_TPU_SANITIZE=1``): dynamic
  twins of the invariants static analysis cannot prove — lock-acquisition
  order observed at runtime, the store's byte-compare write guard,
  accountant-vs-recount drift, and leaked spans / stranded holds at
  harness teardown. One ``make chaos-matrix`` seed runs under it.

The package is stdlib-only at import time (ast/re/json/threading): linting
never drags in jax, and the sanitizer is importable from the observability
singletons without cycles.
"""

from grove_tpu.analysis.engine import (  # noqa: F401
    LintReport,
    Rule,
    Violation,
    lint_paths,
    lint_source,
    run_repo_lint,
)
