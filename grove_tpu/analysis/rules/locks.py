"""GL009 lock-acquisition-order consistency.

~8 modules hold `threading.Lock`s (apiserver, tracer, events, metrics,
encode cache, hashing evictor, grpcsolver watchers). None of them may
nest acquisitions in conflicting orders, or two threads interleaving
(reconcile workers vs. watch fan-out vs. scrape handlers) deadlock.

Static extraction: within every function, syntactically nested `with
<lock>` acquisitions produce ordered edges `outer → inner`; a call made
while holding a lock to a same-class method that acquires its own lock
contributes the edge too (one level of expansion — the pattern real
deadlocks here would take). Lock identity is `Class.attr` for
`self._lock`-style attributes and `module:NAME` for module-level locks.
The transitive order must stay acyclic; `finalize()` reports every cycle,
and `summary()` exposes the extracted partial order for the JSON artifact
(the runtime sanitizer asserts the same property dynamically).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from grove_tpu.analysis.engine import FileContext, Rule, Violation, dotted


def _is_lock_name(name: str) -> bool:
    return "lock" in name.lower()


class LockOrderRule(Rule):
    id = "GL009"
    name = "lock-order"
    description = (
        "lock acquisitions must follow one global partial order — nested"
        " `with lock:` blocks may never form a cycle across the codebase"
    )
    paths = ("grove_tpu/",)

    def __init__(self) -> None:
        # edge (outer, inner) -> first (path, line) witnessing it
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        # per (class, method) info for one-level call expansion
        self._acquires: Dict[Tuple[str, str], Set[str]] = {}
        self._calls_under_lock: List[Tuple[str, str, str, str, int]] = []
        # (class, holding_lock, called_method, path, line)

    def _lock_id(
        self, expr: ast.AST, cls: Optional[str], module: str
    ) -> Optional[str]:
        """Identity of a lock-ish with-context expression, else None."""
        if isinstance(expr, ast.Attribute) and _is_lock_name(expr.attr):
            base = dotted(expr.value)
            if base == "self" and cls:
                return f"{cls}.{expr.attr}"
            return f"{base}.{expr.attr}" if base else expr.attr
        if isinstance(expr, ast.Name) and _is_lock_name(expr.id):
            return f"{module}:{expr.id}"
        return None

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        ctx.annotate_classes()
        module = ctx.rel
        for fn in ctx.functions():
            cls = ctx.enclosing_class(fn)
            self._walk(fn.body, [], cls, module, ctx, fn.name)
        return ()

    def _walk(
        self,
        body: List[ast.stmt],
        held: List[str],
        cls: Optional[str],
        module: str,
        ctx: FileContext,
        fn_name: str,
    ) -> None:
        for stmt in body:
            if isinstance(stmt, ast.With):
                acquired = []
                for item in stmt.items:
                    lock = self._lock_id(item.context_expr, cls, module)
                    if lock is not None:
                        for outer in held + acquired:
                            self.edges.setdefault(
                                (outer, lock), (ctx.rel, stmt.lineno)
                            )
                        acquired.append(lock)
                if acquired and cls is not None:
                    key = (cls, fn_name)
                    self._acquires.setdefault(key, set()).update(acquired)
                self._walk(
                    stmt.body, held + acquired, cls, module, ctx, fn_name
                )
                # record method calls made while holding (for expansion)
                if held or acquired:
                    for node in ast.walk(stmt):
                        if (
                            isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and dotted(node.func.value) == "self"
                        ):
                            for h in held + acquired:
                                self._calls_under_lock.append(
                                    (
                                        cls or "",
                                        h,
                                        node.func.attr,
                                        ctx.rel,
                                        node.lineno,
                                    )
                                )
            else:
                for attr in ("body", "orelse", "finalbody", "handlers"):
                    sub = getattr(stmt, attr, None)
                    if not sub:
                        continue
                    if attr == "handlers":
                        for h in sub:
                            self._walk(
                                h.body, held, cls, module, ctx, fn_name
                            )
                    else:
                        self._walk(sub, held, cls, module, ctx, fn_name)
                # top-level acquisition recording for expansion (methods
                # that take their own lock at any depth are captured by the
                # With branch above via _acquires)

    def finalize(self) -> Iterable[Violation]:
        # one-level call expansion: holding L1, calling self.m() where m
        # acquires L2 -> edge L1 -> L2
        for cls, lock, method, path, line in self._calls_under_lock:
            inner = self._acquires.get((cls, method))
            if inner:
                for l2 in inner:
                    self.edges.setdefault((lock, l2), (path, line))
        # cycle detection over the edge graph
        graph: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            if a != b:
                graph.setdefault(a, set()).add(b)
        for cycle in self._cycles(graph):
            first_edge = (cycle[0], cycle[1 % len(cycle)])
            where = self.edges.get(first_edge, ("", 0))
            yield Violation(
                rule=self.id,
                path=where[0],
                line=where[1],
                col=0,
                message=(
                    "lock-order cycle: "
                    + " -> ".join(cycle + [cycle[0]])
                    + " — pick one global acquisition order"
                ),
            )

    @staticmethod
    def _cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
        """Elementary cycles via DFS (small graphs; dedup by node set)."""
        cycles: List[List[str]] = []
        seen_sets: Set[frozenset] = set()

        def dfs(start: str, node: str, path: List[str], visited: Set[str]):
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    key = frozenset(path)
                    if key not in seen_sets:
                        seen_sets.add(key)
                        cycles.append(list(path))
                elif nxt not in visited and nxt > start:
                    # only roots that are the lexicographically smallest
                    # member explore, so each cycle is found once
                    visited.add(nxt)
                    dfs(start, nxt, path + [nxt], visited)
                    visited.discard(nxt)

        for root in sorted(graph):
            dfs(root, root, [root], {root})
        return cycles

    def summary(self) -> Optional[dict]:
        return {
            "edges": sorted(f"{a} -> {b}" for (a, b) in self.edges),
            "locks": sorted(
                {a for a, _ in self.edges} | {b for _, b in self.edges}
            ),
        }
