"""GL022 gray-failure state encapsulation (docs/robustness.md
"Gray failures").

The gray-failure ladder works because each detector's memory has ONE
writer, and every state step is loud (a registered event + a metric):

- ``NodeHealthMonitor._suspicion`` (controller/nodehealth.py) — the
  EWMA of peer-relative heartbeat lateness. Only the monitor folds
  observations; a write from anywhere else can flip Ready ⇄ Degraded
  without the NodeDegraded/NodeRecovered events the remediation
  trigger and the chaos verdicts key on.
- ``SimCluster._failslow`` (sim/cluster.py) — the seeded fail-slow
  fault registry (kubelet-side NODE state). Armed and healed only via
  ``inject_failslow``/``heal_failslow``; harness swaps re-inject via
  the public ``failslow_names()``/``failslow_spec()`` accessors. A
  direct graft desyncs the lag trace from the suspicion oracle.
- ``StoreDurability.degraded_mode`` (durability/recovery.py) — the WAL
  degradation ladder (ok → degraded → read-only). Stepped only by the
  durability package's ``_set_degraded_mode``, which emits
  WalDegraded/WalRecovered and fences/unfences writes atomically with
  the step; a bare assignment leaves the fence and the mode disagreeing.
- the worker-boundary fault plan and its dedup ledgers
  (runtime/procworkers.py ``_faults`` / ``_tx_seq`` / ``_rx_seq`` /
  ``_last_sent`` / ``_crx_high`` / ``_creply_cache``) — armed only via
  ``inject_boundary_faults`` BEFORE the first drain (children inherit
  the plan at fork); mutating any of it mid-run splits the coordinator
  and its forked workers into different fault universes.

The injection KNOBS stay public by design — ``inject_failslow(...)``,
``inject_boundary_faults(...)``, ``wal.fault_slow_fsync``,
``wal.fault_disk_full`` are the sanctioned seams chaos and the smokes
drive — it is the detectors' memory and the ladder position that only
their owners may write.
"""

from __future__ import annotations

import ast
from typing import Iterable

from grove_tpu.analysis.engine import FileContext, Rule, Violation, dotted

# attr -> (owning package prefix, what breaks when grafted)
_OWNED = {
    "_suspicion": (
        "grove_tpu/controller/",
        "the suspicion EWMA is NodeHealthMonitor memory; Ready ⇄"
        " Degraded must flip through _suspect (events + metrics)",
    ),
    "_failslow": (
        "grove_tpu/sim/",
        "the fail-slow registry is kubelet state; arm/heal via"
        " inject_failslow/heal_failslow, re-inject across harness"
        " swaps via failslow_names()/failslow_spec()",
    ),
    "degraded_mode": (
        "grove_tpu/durability/",
        "the WAL ladder steps only through _set_degraded_mode, which"
        " emits WalDegraded/WalRecovered and moves the write fence"
        " atomically with the mode",
    ),
    "_faults": (
        "grove_tpu/runtime/",
        "the boundary fault plan is fixed at arm time"
        " (inject_boundary_faults); a mid-run write splits coordinator"
        " and forked workers into different fault universes",
    ),
    "_tx_seq": (
        "grove_tpu/runtime/",
        "frame-sequence state is the dedup protocol's memory",
    ),
    "_rx_seq": (
        "grove_tpu/runtime/",
        "frame-sequence state is the dedup protocol's memory",
    ),
    "_last_sent": (
        "grove_tpu/runtime/",
        "the retransmit buffer is the dedup protocol's memory",
    ),
    "_crx_high": (
        "grove_tpu/runtime/",
        "the worker-side high-water mark is the dedup protocol's memory",
    ),
    "_creply_cache": (
        "grove_tpu/runtime/",
        "the cached-reply ring is the idempotent-RPC memory",
    ),
}

_MUTATORS = {"append", "add", "clear", "pop", "popitem", "update",
             "setdefault", "extend", "remove", "discard"}


class GrayFailStateRule(Rule):
    id = "GL022"
    name = "grayfail-state"
    description = (
        "gray-failure detector memory (suspicion EWMA, fail-slow"
        " registry, WAL ladder position, boundary fault plan + dedup"
        " ledgers) has one writer each — state steps go through the"
        " owner's verbs, which emit the registered events"
    )
    paths = ("grove_tpu/",)

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        for node in ast.walk(ctx.tree):
            for name, base, lineno, col in self._written_attrs(node):
                owned = _OWNED.get(name)
                if owned is None:
                    continue
                owner, why = owned
                if ctx.rel.startswith(owner):
                    continue
                yield Violation(
                    rule=self.id,
                    path=ctx.rel,
                    line=lineno,
                    col=col,
                    message=(
                        f"gray-failure state `{base}.{name}` written"
                        f" outside {owner} — {why} (GL022)"
                    ),
                )

    @staticmethod
    def _written_attrs(node):
        """Every (attr, base, line, col) that `node` WRITES: assignment
        / augmented assignment / delete targets (tuple unpacking and
        subscript writes included), or a mutating method call on the
        attribute (`monitor._suspicion.clear()`)."""
        targets = ()
        if isinstance(node, (ast.Assign, ast.Delete)):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = (node.target,)
        for t in targets:
            elts = (
                t.elts if isinstance(t, (ast.Tuple, ast.List)) else (t,)
            )
            for elt in elts:
                inner = elt
                while isinstance(inner, ast.Subscript):
                    inner = inner.value
                if isinstance(inner, ast.Attribute):
                    yield (
                        inner.attr, dotted(inner.value), inner.lineno,
                        inner.col_offset,
                    )
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATORS
            and isinstance(node.func.value, ast.Attribute)
        ):
            owner = node.func.value
            yield (
                owner.attr, dotted(owner.value), owner.lineno,
                owner.col_offset,
            )
