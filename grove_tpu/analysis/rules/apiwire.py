"""GL010 wire-decodable public API types.

`api/serialize.py::to_dict` and `api/wire.py::decode_dataclass` give the
real-cluster mode its lossless object round trip. The reflective decoder
understands a fixed annotation grammar; a field added to `api/types.py`
outside it (a tuple, a multi-type Union, a non-str-keyed dict) serializes
fine but silently fails — or corrupts — on decode. This rule pins the
grammar statically; tests/test_serialize_roundtrip.py is its runtime twin
(seeded property round trips over every public dataclass).

Checked per dataclass field in api/types.py:
- annotation ∈ {str, int, float, bool, Any, dataclass ref, Optional[T],
  List[T], Dict[str, T]} recursively;
- the field name survives the camelCase round trip
  (snake(camel(name)) == name), or carries a wire alias.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from grove_tpu.analysis.engine import FileContext, Rule, Violation

_SCALARS = {"str", "int", "float", "bool", "Any", "object"}
_FORBIDDEN = {
    "tuple",
    "Tuple",
    "set",
    "Set",
    "frozenset",
    "FrozenSet",
    "bytes",
    "Callable",
    "Iterator",
    "Iterable",
    "Generator",
}


def _camel(name: str) -> str:
    head, *rest = name.split("_")
    return head + "".join(w.capitalize() for w in rest)


def _snake(name: str) -> str:
    out = []
    for ch in name:
        if ch.isupper():
            out.append("_")
            out.append(ch.lower())
        else:
            out.append(ch)
    return "".join(out)


def _is_dataclass_def(node: ast.ClassDef) -> bool:
    for dec in node.decorator_list:
        name = (
            dec.id
            if isinstance(dec, ast.Name)
            else dec.attr
            if isinstance(dec, ast.Attribute)
            else getattr(getattr(dec, "func", None), "id", None)
            or getattr(getattr(dec, "func", None), "attr", None)
        )
        if name == "dataclass":
            return True
    return False


class WireRoundTripRule(Rule):
    id = "GL010"
    name = "wire-roundtrip"
    description = (
        "public API dataclass fields must use the wire-decodable annotation"
        " grammar (scalars, dataclass refs, Optional/List/Dict[str, T])"
        " and camelCase-round-trippable names"
    )
    paths = ("grove_tpu/api/types.py",)

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        local_classes = {
            n.name
            for n in ast.walk(ctx.tree)
            if isinstance(n, ast.ClassDef)
        }
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef) or not _is_dataclass_def(
                node
            ):
                continue
            for stmt in node.body:
                if not isinstance(stmt, ast.AnnAssign) or not isinstance(
                    stmt.target, ast.Name
                ):
                    continue
                fname = stmt.target.id
                if fname.startswith("_") or fname == "kind":
                    continue
                problem = self._check_annotation(
                    stmt.annotation, local_classes
                )
                if problem is not None:
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=stmt.lineno,
                        col=stmt.col_offset,
                        message=(
                            f"{node.name}.{fname}: {problem} — the"
                            " api/wire.py decoder cannot round-trip it"
                        ),
                    )
                if _snake(_camel(fname)) != fname:
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=stmt.lineno,
                        col=stmt.col_offset,
                        message=(
                            f"{node.name}.{fname}: field name does not"
                            " survive the camelCase round trip"
                            f" ({_camel(fname)} -> {_snake(_camel(fname))})"
                            " — rename or add a wire alias in"
                            " api/wire.py::_FIELD_ALIASES"
                        ),
                    )

    def _check_annotation(
        self, ann: ast.AST, local: set
    ) -> Optional[str]:
        # string forward refs: re-parse
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return f"unparseable forward reference {ann.value!r}"
        if isinstance(ann, ast.Name):
            if ann.id in _FORBIDDEN:
                return f"type `{ann.id}` is outside the wire grammar"
            if ann.id in _SCALARS or ann.id in local:
                return None
            # imported dataclass refs (ObjectMeta, Condition, ...) pass:
            # conventionally UpperCamelCase types
            if ann.id[:1].isupper():
                return None
            return f"type `{ann.id}` is outside the wire grammar"
        if isinstance(ann, ast.Attribute):
            return None  # module-qualified dataclass ref
        if isinstance(ann, ast.Subscript):
            base = ann.value
            base_name = (
                base.id
                if isinstance(base, ast.Name)
                else base.attr
                if isinstance(base, ast.Attribute)
                else ""
            )
            args = (
                list(ann.slice.elts)
                if isinstance(ann.slice, ast.Tuple)
                else [ann.slice]
            )
            if base_name in ("Optional",):
                return self._check_annotation(args[0], local)
            if base_name in ("List", "list"):
                return self._check_annotation(args[0], local)
            if base_name in ("Dict", "dict"):
                key = args[0]
                if not (isinstance(key, ast.Name) and key.id == "str"):
                    return "Dict keys must be `str` on the wire"
                return self._check_annotation(args[1], local)
            if base_name == "Union":
                non_none = [
                    a
                    for a in args
                    if not (
                        isinstance(a, ast.Constant) and a.value is None
                    )
                    and not (isinstance(a, ast.Name) and a.id == "None")
                ]
                if len(non_none) > 1:
                    return (
                        "multi-type Union is undecodable (the decoder"
                        " picks the first member)"
                    )
                return self._check_annotation(non_none[0], local)
            if base_name in _FORBIDDEN:
                return f"type `{base_name}[...]` is outside the wire grammar"
            return f"unsupported generic `{base_name}[...]`"
        return "unsupported annotation shape"
