"""GL014 frontier-partition-state encapsulation (docs/solver.md
"Partitioned frontier").

The partitioned solver frontier (solver/frontier.py) keys everything on
its partition plan: the frontier level, the super-domain slab table, the
per-slab sub-encoding cache, and the per-solve assignment scratch. The
correctness story — subproblems are node-DISJOINT, the composite equals
the sequential per-subproblem reference bit-for-bit, degenerate ticks
bypass byte-identically — assumes only frontier.py derives and mutates
that state from the delta state's NodeEncoding. A controller (or test
helper) that pokes ``frontier._plan`` or the sub-encoding cache directly
can leave the plan describing a node set the encoding no longer matches:
the next solve would compose allocations onto the WRONG global node
columns, which binds pods to nodes the solver never chose.

Flagged outside ``grove_tpu/solver/frontier.py``: any WRITE (assignment,
augmented assignment, delete, or mutating call) to frontier-private state
reached through a frontier-named binding — ``frontier._plan``,
``frontier._plan_enc``, ``plan._sub_encodings`` — plus writes to the
public counters (they are the bench's ledger, owned by the module).

The sanctioned out-of-band hook is :meth:`FrontierState.invalidate`
(mirrors GL012's registration API for the delta state).
"""

from __future__ import annotations

import ast
from typing import Iterable

from grove_tpu.analysis.engine import FileContext, Rule, Violation, dotted

# FrontierState / FrontierPlan private fields (solver/frontier.py)
_FRONTIER_PRIVATE = {
    "_plan",
    "_plan_enc",
    "_sub_encodings",
}
# FrontierPlan's own fields: writable only by the owning module, even
# when reached through the chain (`frontier._plan.starts = ...`)
_PLAN_FIELDS = {
    "level",
    "starts",
    "ends",
    "num_partitions",
}
# lifetime counters: readable anywhere (the bench ledger), writable only
# by the owning module
_FRONTIER_COUNTERS = {
    "solves",
    "degenerate",
    "subproblems_total",
    "assigned_total",
    "residual_total",
    "dispatches_total",
    "last_subproblems",
    "last_residual_fraction",
    "last_overlap_occupancy",
    "selfcheck_seconds",
}

_MUTATORS = {"append", "add", "clear", "pop", "popitem", "update",
             "setdefault", "extend", "remove", "discard"}


def _frontier_chain(base: str) -> bool:
    """The access chain runs through a frontier-named binding (so
    `sched.frontier._plan.starts = x` is caught, not just
    `frontier.starts = x`)."""
    if not base:
        return False
    return any("frontier" in seg.lower() for seg in base.split("."))


def _plan_binding(base: str) -> bool:
    """The binding itself is a plan object (`plan = frontier.plan_for(...)`
    idiom). Only the LEAF is consulted — a bare `plan` segment deeper in
    an unrelated chain must not drag foreign `.starts`/`.level` writes
    into this rule."""
    leaf = base.split(".")[-1].lower() if base else ""
    return leaf in ("plan", "_plan")


class FrontierStateRule(Rule):
    id = "GL014"
    name = "frontier-partition-state"
    description = (
        "the partitioned frontier's plan/sub-encoding/counter state is"
        " private to solver/frontier.py — out-of-band invalidation goes"
        " through FrontierState.invalidate()"
    )
    paths = ("grove_tpu/",)
    exclude = ("grove_tpu/solver/frontier.py",)

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        for node in ast.walk(ctx.tree):
            for name, base, lineno, col in self._written_attrs(node):
                # frontier-private names match through any frontier chain
                # or a plan-typed binding; the GENERIC plan-field names
                # (starts/ends/level) require the frontier chain — a bare
                # `plan` segment elsewhere must not drag foreign writes in
                if (
                    name in _FRONTIER_PRIVATE
                    and (_frontier_chain(base) or _plan_binding(base))
                ) or (name in _PLAN_FIELDS and _frontier_chain(base)):
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=lineno,
                        col=col,
                        message=(
                            f"frontier partition state `{base}.{name}`"
                            " mutated outside solver/frontier.py — the"
                            " plan must stay coherent with the delta"
                            " state's NodeEncoding; call"
                            " frontier.invalidate() instead (GL014)"
                        ),
                    )
                elif name in _FRONTIER_COUNTERS and _frontier_chain(base):
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=lineno,
                        col=col,
                        message=(
                            f"frontier counter `{base}.{name}` written"
                            " outside solver/frontier.py — the counters"
                            " are the bench's ledger (read via"
                            " FrontierState.stats()) (GL014)"
                        ),
                    )

    @staticmethod
    def _written_attrs(node):
        """Every (attr, base, line, col) that `node` WRITES: assignment /
        augmented assignment / delete targets (tuple unpacking included),
        or a mutating method call on the attribute
        (`x._sub_encodings.clear()`)."""
        targets = ()
        if isinstance(node, (ast.Assign, ast.Delete)):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = (node.target,)
        for t in targets:
            elts = (
                t.elts if isinstance(t, (ast.Tuple, ast.List)) else (t,)
            )
            for elt in elts:
                inner = elt
                while isinstance(inner, ast.Subscript):
                    inner = inner.value
                if isinstance(inner, ast.Attribute):
                    yield (
                        inner.attr, dotted(inner.value), inner.lineno,
                        inner.col_offset,
                    )
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATORS
            and isinstance(node.func.value, ast.Attribute)
        ):
            owner = node.func.value
            yield (
                owner.attr, dotted(owner.value), owner.lineno,
                owner.col_offset,
            )
