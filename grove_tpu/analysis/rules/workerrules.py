"""GL018 worker-affinity (docs/control-plane.md §5).

The parallel control plane (runtime/workers.py) makes each keyspace
shard the ownership boundary of one reconcile worker: the shard's event
backlog, its workqueue buckets, its reconcile bodies and its WAL stream
are touched only from the owning worker context or at the documented
coordination points (the coordinator's routing/pop/completion loop, the
tick-boundary WAL pump). The serial-twin bit-identity argument leans on
exactly that affinity — a foreign module poking a backlog deque, a
queue's shard buckets, the store's deferred-capture plumbing or a WAL
buffer from an arbitrary thread silently breaks the deterministic
round-robin (or tears a group-commit batch) in ways no test reliably
catches.

Flagged outside the owning modules:

- the Engine's per-shard backlog state (``engine._backlogs``,
  ``engine._backlog_rotation``, ``engine._event_backlog``,
  ``engine._router_lock``) — owned by runtime/engine.py and
  runtime/workers.py;
- the WorkQueue's shard-bucket state (``queue._buckets``,
  ``queue._rotation``, ``queue._bucket_memo``) — owned by
  runtime/workqueue.py (the engine and the parallel drain go through
  ``pop``/``add``);
- the Store's deferred-fanout capture plumbing (``store._capture_tls``,
  ``store._per_shard_fns``, ``store._deferred_armed``, and the
  ``begin_deferred_capture``/``end_deferred_capture`` pair) — owned by
  runtime/store.py and runtime/workers.py;
- a WAL stream's group-commit buffer (``wal._buffer``, ``wal._dead``,
  ``wal._io_lock``) — owned by grove_tpu/durability/.

Public surface stays public: ``Engine.enable_workers``,
``engine.workers.stats()``/``utilization()``, ``WorkQueue.add/pop/...``,
``Store.subscribe_system_per_shard``/``arm_deferred_fanout``, and
``wal.note_event``/``flush``/``pending``.
"""

from __future__ import annotations

import ast
from typing import Iterable

from grove_tpu.analysis.engine import FileContext, Rule, Violation, dotted

# attr set -> (binding-leaf substring, owning-module prefixes); the
# process executor (runtime/procworkers.py) is a peer owner of the
# thread executor — its worker lanes and repatriation path ARE the
# owning worker context on the far side of the fork
_ENGINE_OWNERS = (
    "grove_tpu/runtime/engine.py",
    "grove_tpu/runtime/workers.py",
    "grove_tpu/runtime/procworkers.py",
)
_QUEUE_OWNERS = (
    "grove_tpu/runtime/workqueue.py",
    "grove_tpu/runtime/engine.py",
    "grove_tpu/runtime/workers.py",
    "grove_tpu/runtime/procworkers.py",
)
_STORE_OWNERS = (
    "grove_tpu/runtime/store.py",
    "grove_tpu/runtime/workers.py",
    "grove_tpu/runtime/procworkers.py",
)
_WAL_OWNERS = ("grove_tpu/durability/",)

_ENGINE_PRIVATE = {
    "_backlogs",
    "_backlog_rotation",
    "_event_backlog",
    "_router_lock",
}
_QUEUE_PRIVATE = {"_buckets", "_rotation", "_bucket_memo"}
_STORE_PRIVATE = {
    "_capture_tls",
    "_per_shard_fns",
    "_deferred_armed",
    "begin_deferred_capture",
    "end_deferred_capture",
}
_WAL_PRIVATE = {"_buffer", "_dead", "_io_lock"}


class WorkerAffinityRule(Rule):
    id = "GL018"
    name = "worker-affinity"
    description = (
        "mutable per-shard runtime state (engine backlogs/rotation,"
        " workqueue shard buckets, store deferred-capture plumbing,"
        " WAL group-commit buffers) may only be touched from its owning"
        " worker context or the documented coordination points — the"
        " owning runtime/durability modules; everything else goes"
        " through the public Engine/WorkQueue/Store/WAL APIs"
    )
    # repo-wide like GL013: affinity broken from ANYWHERE breaks the
    # serial-twin determinism argument
    paths = ("grove_tpu/",)
    exclude = ()

    def _owned(self, rel: str, owners) -> bool:
        return any(rel.startswith(o) for o in owners)

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Attribute):
                continue
            attr = node.attr
            base = dotted(node.value)
            leaf = (base.split(".")[-1] if base else "").lower()
            hit = None
            if attr in _ENGINE_PRIVATE and "engine" in leaf:
                if not self._owned(ctx.rel, _ENGINE_OWNERS):
                    hit = ("Engine per-shard backlog state", "Engine")
            elif attr in _QUEUE_PRIVATE and "queue" in leaf:
                if not self._owned(ctx.rel, _QUEUE_OWNERS):
                    hit = ("WorkQueue shard-bucket state", "WorkQueue")
            elif attr in _STORE_PRIVATE and "store" in leaf:
                if not self._owned(ctx.rel, _STORE_OWNERS):
                    hit = ("Store deferred-capture plumbing", "Store")
            elif attr in _WAL_PRIVATE and "wal" in leaf:
                if not self._owned(ctx.rel, _WAL_OWNERS):
                    hit = ("WAL group-commit buffer state", "WAL")
            if hit is not None:
                what, api = hit
                yield Violation(
                    rule=self.id,
                    path=ctx.rel,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"{what} `{base}.{attr}` touched outside its"
                        " owning worker context (GL018 worker-affinity,"
                        " docs/control-plane.md §5) — go through the"
                        f" public {api} API"
                    ),
                )
