"""grovelint rule registry. Each module holds one theme's rules; ALL_RULES
is the set `make lint` runs (docs/static-analysis.md is the catalog)."""

from grove_tpu.analysis.rules.apiwire import WireRoundTripRule
from grove_tpu.analysis.rules.clocks import BlockingTickRule, ClockDisciplineRule
from grove_tpu.analysis.rules.dirtymask import DirtyMaskRegistrationRule
from grove_tpu.analysis.rules.explainrule import ExplainReadonlyRule
from grove_tpu.analysis.rules.federationrule import FederationStateRule
from grove_tpu.analysis.rules.frontierrule import FrontierStateRule
from grove_tpu.analysis.rules.glassbox import GlassBoxStateRule
from grove_tpu.analysis.rules.grayfail import GrayFailStateRule
from grove_tpu.analysis.rules.jaxrules import JitHygieneRule
from grove_tpu.analysis.rules.ledgerrules import ActMustLogRule
from grove_tpu.analysis.rules.locks import LockOrderRule
from grove_tpu.analysis.rules.observability import EventReasonRule, SpanLeakRule
from grove_tpu.analysis.rules.procrules import ProcessBoundaryRule
from grove_tpu.analysis.rules.scheduling import (
    BrokerGrantRule,
    SchedulableMaskRule,
)
from grove_tpu.analysis.rules.shardrules import ShardInternalsRule
from grove_tpu.analysis.rules.slorules import TimeSeriesStateRule
from grove_tpu.analysis.rules.storepath import (
    StoreLoggedCommitRule,
    StoreWritePathRule,
)
from grove_tpu.analysis.rules.workerrules import WorkerAffinityRule

ALL_RULES = (
    ClockDisciplineRule,  # GL001
    BrokerGrantRule,  # GL002
    SchedulableMaskRule,  # GL003
    StoreWritePathRule,  # GL004
    JitHygieneRule,  # GL005
    EventReasonRule,  # GL006
    SpanLeakRule,  # GL007
    BlockingTickRule,  # GL008
    LockOrderRule,  # GL009
    WireRoundTripRule,  # GL010
    StoreLoggedCommitRule,  # GL011
    DirtyMaskRegistrationRule,  # GL012
    ShardInternalsRule,  # GL013
    FrontierStateRule,  # GL014
    GlassBoxStateRule,  # GL015
    ExplainReadonlyRule,  # GL016
    TimeSeriesStateRule,  # GL017
    WorkerAffinityRule,  # GL018
    ActMustLogRule,  # GL019
    ProcessBoundaryRule,  # GL020
    FederationStateRule,  # GL021
    GrayFailStateRule,  # GL022
)
