"""GL001 virtual-clock discipline + GL008 non-blocking reconcile bodies.

Determinism is what makes `make chaos-matrix` replayable and the bench
A/Bs honest: everything the sim/solver/control plane does must run on the
injectable clock (runtime/clock.py) and seeded RNGs. The real-cluster
paths (cluster/lease.py, cluster/cert.py, cluster/manager.py,
utils/platform.py) legitimately read wall time and are out of scope.
`time.perf_counter`/`time.monotonic` are deliberately allowed: they
measure real latency (tracing, metrics) without steering simulated-time
logic.

EXCEPT in the strict-scope files (``sim/traffic.py``): a traffic trace
must replay bit-identically from its seed, so not even a latency
measurement may read the wall — the SLO observatory's windowed numbers
(and `make serving-smoke`'s breach schedule) are only reproducible if
the generator is a pure function of (seed, virtual time).
"""

from __future__ import annotations

import ast
from typing import Iterable, Set

from grove_tpu.analysis.engine import FileContext, Rule, Violation, dotted

_BANNED_TIME_ATTRS = {"time", "sleep"}
# additionally banned in the strict scope (pure seed+virtual-time files)
_STRICT_TIME_ATTRS = {"time", "sleep", "perf_counter", "monotonic",
                      "monotonic_ns", "perf_counter_ns", "time_ns"}
_SEEDED_RNG_CTORS = {"Random", "default_rng", "RandomState", "SystemRandom"}
_DATETIME_ATTRS = {"now", "utcnow", "today"}


class _ImportTracker(ast.NodeVisitor):
    """Module aliases in one file: which local names are `time`, `random`,
    `numpy`, `datetime` (handles `import time as _time` etc.)."""

    def __init__(self) -> None:
        self.time: Set[str] = set()
        self.random: Set[str] = set()
        self.numpy: Set[str] = set()
        self.datetime: Set[str] = set()
        # names imported FROM those modules (from time import sleep)
        self.from_time: Set[str] = set()
        self.from_time_strict: Set[str] = set()  # perf_counter/monotonic
        self.from_random: Set[str] = set()

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            local = alias.asname or alias.name.split(".")[0]
            if alias.name == "time":
                self.time.add(local)
            elif alias.name == "random":
                self.random.add(local)
            elif alias.name in ("numpy", "numpy.random"):
                self.numpy.add(local)
            elif alias.name == "datetime":
                self.datetime.add(local)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        for alias in node.names:
            local = alias.asname or alias.name
            if node.module == "time" and alias.name in _BANNED_TIME_ATTRS:
                self.from_time.add(local)
            elif node.module == "time" and alias.name in _STRICT_TIME_ATTRS:
                self.from_time_strict.add(local)
            elif node.module == "random":
                self.from_random.add(local)
            elif node.module == "datetime" and alias.name == "datetime":
                self.datetime.add(local)


class ClockDisciplineRule(Rule):
    id = "GL001"
    name = "wall-clock"
    description = (
        "sim/solver/controller/runtime/disruption/quota code must use the"
        " injectable clock and seeded RNGs — no time.time()/time.sleep(),"
        " unseeded random, numpy global RNG, or datetime.now()"
    )
    paths = (
        "grove_tpu/sim/",
        "grove_tpu/solver/",
        "grove_tpu/controller/",
        "grove_tpu/runtime/",
        "grove_tpu/disruption/",
        "grove_tpu/quota/",
        "grove_tpu/observability/forecast.py",
    )
    # strict scope: bit-replayable generators — even perf_counter/
    # monotonic are wall reads there (the serving traffic trace must be a
    # pure function of seed + virtual time; the forecaster is pinned
    # bit-equal to a NumPy oracle over that same virtual timeline)
    strict_paths = (
        "grove_tpu/sim/traffic.py",
        "grove_tpu/observability/forecast.py",
    )

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        imports = _ImportTracker()
        imports.visit(ctx.tree)
        strict = ctx.rel in self.strict_paths
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            msg = self._classify(node, imports, strict)
            if msg is not None:
                yield Violation(
                    rule=self.id,
                    path=ctx.rel,
                    line=node.lineno,
                    col=node.col_offset,
                    message=msg,
                )

    def _classify(
        self, node: ast.Call, imports: _ImportTracker, strict: bool = False
    ):
        banned_attrs = _STRICT_TIME_ATTRS if strict else _BANNED_TIME_ATTRS
        fn = node.func
        if isinstance(fn, ast.Attribute):
            base = fn.value
            # time.time() / time.sleep() (any alias of the time module)
            if (
                isinstance(base, ast.Name)
                and base.id in imports.time
                and fn.attr in banned_attrs
            ):
                return (
                    f"wall-clock call `{dotted(fn)}()` — use the injectable"
                    " Clock (store.clock / harness clock) so virtual-time"
                    " runs stay deterministic"
                    + (
                        " (STRICT scope: traffic traces must replay"
                        " bit-identically, even latency reads are banned)"
                        if strict and fn.attr not in _BANNED_TIME_ATTRS
                        else ""
                    )
                )
            # random.<fn>() — only seeded constructors with args pass
            if isinstance(base, ast.Name) and base.id in imports.random:
                if fn.attr in _SEEDED_RNG_CTORS and (
                    node.args or node.keywords
                ):
                    return None
                return (
                    f"unseeded/global RNG `{dotted(fn)}()` — construct a"
                    " seeded random.Random(seed) instead"
                )
            # np.random.<fn>()
            if (
                isinstance(base, ast.Attribute)
                and base.attr == "random"
                and isinstance(base.value, ast.Name)
                and base.value.id in imports.numpy
            ):
                if fn.attr in _SEEDED_RNG_CTORS and (
                    node.args or node.keywords
                ):
                    return None
                return (
                    f"numpy global RNG `{dotted(fn)}()` — use"
                    " np.random.default_rng(seed)"
                )
            # datetime.now()/utcnow()/today() — the base must resolve to an
            # imported datetime module/class (aliases included), so a local
            # variable that happens to be named `datetime` is not flagged
            if fn.attr in _DATETIME_ATTRS:
                root = dotted(base)
                head, _, tail = root.partition(".")
                if head in imports.datetime and tail in ("", "datetime"):
                    return (
                        f"wall-clock call `{dotted(fn)}()` — derive"
                        " timestamps from the injectable Clock"
                    )
        elif isinstance(fn, ast.Name):
            if fn.id in imports.from_time or (
                strict and fn.id in imports.from_time_strict
            ):
                return (
                    f"wall-clock call `{fn.id}()` (imported from time) —"
                    " use the injectable Clock"
                )
            if fn.id in imports.from_random:
                if fn.id in _SEEDED_RNG_CTORS and (node.args or node.keywords):
                    return None
                return (
                    f"unseeded RNG `{fn.id}()` (imported from random) —"
                    " construct a seeded random.Random(seed)"
                )
        return None


_TICK_IO_ROOTS = {"socket", "subprocess", "requests", "urllib", "http"}


class BlockingTickRule(Rule):
    id = "GL008"
    name = "blocking-tick"
    description = (
        "reconcile/sync/tick bodies must not block: no sleep, socket,"
        " subprocess, HTTP, or open() inside a controller round"
    )
    paths = (
        "grove_tpu/controller/",
        "grove_tpu/runtime/",
        "grove_tpu/disruption/",
        "grove_tpu/solver/scheduler.py",
        "grove_tpu/autoscale/",
    )

    @staticmethod
    def _is_tick_fn(name: str) -> bool:
        return (
            name in ("reconcile", "sync", "tick")
            or name.endswith("_tick")
            or name.startswith("tick_")
            or "reconcile" in name
        )

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        for fn in ctx.functions():
            if not self._is_tick_fn(fn.name):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                msg = self._classify(node)
                if msg is not None:
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=node.lineno,
                        col=node.col_offset,
                        message=f"{msg} inside `{fn.name}()` — controller"
                        " rounds must stay non-blocking (requeue instead)",
                    )

    @staticmethod
    def _classify(node: ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id == "open":
            return "blocking file I/O `open()`"
        if isinstance(fn, ast.Attribute):
            src = dotted(fn)
            root = src.split(".", 1)[0]
            if root in _TICK_IO_ROOTS:
                return f"blocking I/O `{src}()`"
            # any .sleep() that is not the injectable clock's
            if fn.attr == "sleep" and "clock" not in src.lower():
                return f"blocking `{src}()`"
        return None
