"""GL016 explain-readonly (docs/observability.md "Admission explain").

The explain engine's whole value is that asking "why is my gang Pending"
is FREE of side effects: an operator (or a dashboard polling it every
second) must never perturb the admission state it is observing. That
contract has two halves, both enforced here:

1. **Inside** ``grove_tpu/observability/explain.py`` and
   ``grove_tpu/solver/introspect.py``: no call to any store
   commit/bind/evict primitive, no arming of the disruption broker, no
   delta/frontier invalidation or cache write — the read-only pin
   (``resource_version_vector()`` + ``state_fingerprint()`` byte-equal
   across a burst, tests/test_explain.py) is the runtime twin of this
   static gate.
2. **Outside** those modules: the engine's verdict cache (``_verdicts``)
   is private — a foreign writer could fabricate the "last verdict" the
   /debug/journeys pending annotation shows for a stuck gang (the GL015
   treatment applied to the explain layer).
"""

from __future__ import annotations

import ast
from typing import Iterable

from grove_tpu.analysis.engine import FileContext, Rule, Violation, dotted
from grove_tpu.analysis.rules.glassbox import GlassBoxStateRule

EXPLAIN_MODULES = (
    "grove_tpu/observability/explain.py",
    "grove_tpu/solver/introspect.py",
)

# mutation-primitive call names -> substrings the receiver chain must
# contain for the call to count (None = any receiver). Receiver scoping
# keeps dict.update()/list.append() out of scope while still catching
# store.update(...) / sched.delta.invalidate() / cluster.bind(...).
_FORBIDDEN_CALLS = {
    # store commits
    "create": ("store",),
    "update": ("store",),
    "update_status": ("store",),
    "delete": ("store",),
    "delete_collection": ("store",),
    "restore_objects": ("store",),
    "read_modify_write": ("store",),
    "commit_status": None,
    "commit_cow": None,
    # cluster mutators
    "bind": ("cluster",),
    "crash_node": ("cluster",),
    "restart_node": ("cluster",),
    "fail_node": ("cluster",),
    "fail_pod": ("cluster",),
    "rebuild_bindings": ("cluster",),
    # eviction primitives (GL002's set)
    "_evict_victim": None,
    "_evict_gang_whole": None,
    "_push_template_to_replica": None,
    # monitor / broker state
    "hold_gang": None,
    "grant": ("broker", "disruption"),
    "arm": ("broker", "disruption"),
    "note_failure": ("broker", "disruption"),
    # delta / frontier registration hooks & caches
    "invalidate": ("delta", "frontier"),
    "mark_node_dirty": ("delta",),
    "mark_gang_dirty": ("delta",),
    "store_spec": ("delta",),
    "enable_delta": None,
    "enable_frontier": None,
    # sticky-pad commit (read-only callers use .peek())
    "grow": ("pad", "pad_groups"),
}

# explain-engine private state, locked to its module when reached through
# an explain-named chain (harness.explain._verdicts, engine._verdicts, …)
_EXPLAIN_PRIVATE = {"_verdicts"}


def _explain_chain(base: str) -> bool:
    if not base:
        return False
    return any("explain" in seg.lower() for seg in base.split("."))


class ExplainReadonlyRule(Rule):
    id = "GL016"
    name = "explain-readonly"
    description = (
        "explain/introspect modules may not call store commit/bind/evict"
        " primitives (asking 'why is it Pending' must be free of side"
        " effects); the engine's verdict cache is private to"
        " observability/explain.py"
    )
    paths = ("grove_tpu/",)

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        if ctx.rel in EXPLAIN_MODULES:
            yield from self._check_readonly(ctx)
        else:
            yield from self._check_cache_privacy(ctx)

    def _check_readonly(self, ctx: FileContext) -> Iterable[Violation]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (
                fn.attr
                if isinstance(fn, ast.Attribute)
                else fn.id
                if isinstance(fn, ast.Name)
                else ""
            )
            scopes = _FORBIDDEN_CALLS.get(name, "missing")
            if scopes == "missing":
                continue
            base = (
                dotted(fn.value).lower()
                if isinstance(fn, ast.Attribute)
                else ""
            )
            if scopes is not None and not any(s in base for s in scopes):
                continue
            yield Violation(
                rule=self.id,
                path=ctx.rel,
                line=node.lineno,
                col=node.col_offset,
                message=(
                    f"mutation primitive `{(base + '.') if base else ''}"
                    f"{name}(...)` called from an explain/introspect"
                    " module — the admission explain engine is"
                    " READ-ONLY by contract (rv vector + delta"
                    " fingerprint pinned byte-identical across a burst);"
                    " compute on private snapshots instead (GL016)"
                ),
            )

    def _check_cache_privacy(self, ctx: FileContext) -> Iterable[Violation]:
        for node in ast.walk(ctx.tree):
            for name, base, lineno, col in GlassBoxStateRule._written_attrs(
                node
            ):
                if name in _EXPLAIN_PRIVATE and _explain_chain(base):
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=lineno,
                        col=col,
                        message=(
                            f"explain-engine private state `{base}.{name}`"
                            " mutated outside observability/explain.py —"
                            " a foreign writer could fabricate the 'last"
                            " verdict' journeys show for a stuck gang;"
                            " verdicts enter the cache only via"
                            " explain()/whatif() (GL016)"
                        ),
                    )
