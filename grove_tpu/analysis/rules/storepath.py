"""GL004 store write-path discipline.

PR 2's copy-on-write store removed pickling/deep-copying from the
control-plane write path; the contract is: reads are zero-copy readonly
views, writes go through `commit_cow` / the sanctioned `Store` methods
(create/update/update_status/delete/commit_status/commit_spec/
commit_finalizer_add). Two regressions this rule catches statically:

- **Serialization creep**: `copy.deepcopy` / `pickle.dumps|loads` back in
  control-plane packages (the sanctioned structural helper is
  `api.meta.deep_copy`, and only OFF the per-write path).
- **Private-state bypass**: reaching into the store's internals
  (`_committed`, `_blob`, ...) from outside runtime/store.py skips
  resourceVersion bumps, watch events, aggregates, and the byte-compare
  guard — the silent-corruption class `verify_readonly_integrity` exists
  to catch at runtime.
"""

from __future__ import annotations

import ast
from typing import Iterable

from grove_tpu.analysis.engine import FileContext, Rule, Violation, dotted

_STORE_PRIVATE = {
    "_committed",
    "_cache",
    "_blob",
    "_cache_blob",
    "_index",
    "_cache_index",
    "_rv",
    "_agg_committed",
    "_agg_cached",
    "_guard_blobs",
}

_SERIALIZERS = {
    "deepcopy": "copy.deepcopy",
    "dumps": "pickle.dumps",
    "loads": "pickle.loads",
}


class StoreWritePathRule(Rule):
    id = "GL004"
    name = "store-write-path"
    description = (
        "store mutation only via commit_cow/sanctioned Store methods — no"
        " pickling/deepcopy on the control-plane write path, no private"
        " store-state access outside runtime/store.py"
    )
    paths = (
        "grove_tpu/runtime/",
        "grove_tpu/controller/",
        "grove_tpu/solver/",
        "grove_tpu/sim/",
        "grove_tpu/disruption/",
        "grove_tpu/quota/",
        "grove_tpu/autoscale/",
    )
    exclude = ("grove_tpu/runtime/store.py",)

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        pickle_aliases = set()
        copy_aliases = set()
        from_names = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    if alias.name == "pickle":
                        pickle_aliases.add(local)
                    elif alias.name == "copy":
                        copy_aliases.add(local)
            elif isinstance(node, ast.ImportFrom):
                if node.module in ("pickle", "copy"):
                    for alias in node.names:
                        if alias.name in _SERIALIZERS:
                            from_names[alias.asname or alias.name] = (
                                f"{node.module}.{alias.name}"
                            )
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                fn = node.func
                full = None
                if isinstance(fn, ast.Attribute) and isinstance(
                    fn.value, ast.Name
                ):
                    if (
                        fn.value.id in pickle_aliases
                        and fn.attr in ("dumps", "loads")
                    ) or (fn.value.id in copy_aliases and fn.attr == "deepcopy"):
                        full = f"{fn.value.id}.{fn.attr}"
                elif isinstance(fn, ast.Name) and fn.id in from_names:
                    full = from_names[fn.id]
                if full is not None:
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"`{full}()` on the control-plane path — use"
                            " the copy-on-write store commits"
                            " (commit_cow/commit_status) or"
                            " api.meta.deep_copy off the write path"
                        ),
                    )
        # private store-state access: `<...>store.<_private>`
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.Attribute)
                and node.attr in _STORE_PRIVATE
            ):
                base = dotted(node.value)
                leaf = base.split(".")[-1] if base else ""
                if "store" in leaf.lower():
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"private store state `{base}.{node.attr}`"
                            " accessed outside runtime/store.py — writes"
                            " must go through the sanctioned Store API"
                            " (commit_cow, create, update, delete)"
                        ),
                    )
