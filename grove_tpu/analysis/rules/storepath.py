"""GL004 store write-path discipline + GL011 logged-commit mutations.

PR 2's copy-on-write store removed pickling/deep-copying from the
control-plane write path; the contract is: reads are zero-copy readonly
views, writes go through `commit_cow` / the sanctioned `Store` methods
(create/update/update_status/delete/commit_status/commit_spec/
commit_finalizer_add). Two regressions this rule catches statically:

- **Serialization creep**: `copy.deepcopy` / `pickle.dumps|loads` back in
  control-plane packages (the sanctioned structural helper is
  `api.meta.deep_copy`, and only OFF the per-write path).
- **Private-state bypass**: reaching into the store's internals
  (`_committed`, `_blob`, ...) from outside runtime/store.py skips
  resourceVersion bumps, watch events, aggregates, and the byte-compare
  guard — the silent-corruption class `verify_readonly_integrity` exists
  to catch at runtime.

GL011 (durability layer, docs/robustness.md) tightens the same contract
repo-wide for MUTATIONS: every store mutation must flow through the
logged commit APIs (create/update/update_status/delete/commit_cow/
restore_objects and the commit_* helpers). The write-ahead log observes
commits through the watch fanout — a direct mutation of store internals
(`store._committed[...] = obj`, `store._rv += 1`,
`store._blob.pop(...)`) would be invisible to the WAL, so a crash-restart
recovery would silently diverge from the live state it replaced. Only
`runtime/store.py` itself and the durability module (which replays
through `restore_objects`) are exempt.
"""

from __future__ import annotations

import ast
from typing import Iterable

from grove_tpu.analysis.engine import FileContext, Rule, Violation, dotted

_STORE_PRIVATE = {
    "_committed",
    "_cache",
    "_blob",
    "_cache_blob",
    "_index",
    "_cache_index",
    "_rv",
    "_agg_committed",
    "_agg_cached",
    "_guard_blobs",
}

_SERIALIZERS = {
    "deepcopy": "copy.deepcopy",
    "dumps": "pickle.dumps",
    "loads": "pickle.loads",
}

# methods that mutate a container in place — called on store-private
# state they bypass the logged commit path (GL011)
_MUTATORS = {
    "append",
    "extend",
    "insert",
    "remove",
    "pop",
    "popitem",
    "clear",
    "update",
    "setdefault",
    "add",
    "discard",
}


def _store_private_attr(node: ast.AST):
    """(base dotted path, private attr) when `node`'s attribute chain
    passes through `<...store>.<_private>`, else None."""
    probe = node
    while isinstance(probe, (ast.Attribute, ast.Subscript)):
        if isinstance(probe, ast.Attribute) and probe.attr in _STORE_PRIVATE:
            base = dotted(probe.value)
            leaf = base.split(".")[-1] if base else ""
            if "store" in leaf.lower():
                return base, probe.attr
        probe = probe.value
    return None


class StoreWritePathRule(Rule):
    id = "GL004"
    name = "store-write-path"
    description = (
        "store mutation only via commit_cow/sanctioned Store methods — no"
        " pickling/deepcopy on the control-plane write path, no private"
        " store-state access outside runtime/store.py"
    )
    paths = (
        "grove_tpu/runtime/",
        "grove_tpu/controller/",
        "grove_tpu/solver/",
        "grove_tpu/sim/",
        "grove_tpu/disruption/",
        "grove_tpu/quota/",
        "grove_tpu/autoscale/",
        # the WAL serializes every commit: pickle creeping in here would
        # tie the on-disk log to one code version
        "grove_tpu/durability/",
    )
    exclude = ("grove_tpu/runtime/store.py",)

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        pickle_aliases = set()
        copy_aliases = set()
        from_names = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    if alias.name == "pickle":
                        pickle_aliases.add(local)
                    elif alias.name == "copy":
                        copy_aliases.add(local)
            elif isinstance(node, ast.ImportFrom):
                if node.module in ("pickle", "copy"):
                    for alias in node.names:
                        if alias.name in _SERIALIZERS:
                            from_names[alias.asname or alias.name] = (
                                f"{node.module}.{alias.name}"
                            )
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                fn = node.func
                full = None
                if isinstance(fn, ast.Attribute) and isinstance(
                    fn.value, ast.Name
                ):
                    if (
                        fn.value.id in pickle_aliases
                        and fn.attr in ("dumps", "loads")
                    ) or (fn.value.id in copy_aliases and fn.attr == "deepcopy"):
                        full = f"{fn.value.id}.{fn.attr}"
                elif isinstance(fn, ast.Name) and fn.id in from_names:
                    full = from_names[fn.id]
                if full is not None:
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"`{full}()` on the control-plane path — use"
                            " the copy-on-write store commits"
                            " (commit_cow/commit_status) or"
                            " api.meta.deep_copy off the write path"
                        ),
                    )
        # private store-state access: `<...>store.<_private>`
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.Attribute)
                and node.attr in _STORE_PRIVATE
            ):
                base = dotted(node.value)
                leaf = base.split(".")[-1] if base else ""
                if "store" in leaf.lower():
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"private store state `{base}.{node.attr}`"
                            " accessed outside runtime/store.py — writes"
                            " must go through the sanctioned Store API"
                            " (commit_cow, create, update, delete)"
                        ),
                    )


class StoreLoggedCommitRule(Rule):
    id = "GL011"
    name = "store-logged-commits"
    description = (
        "store mutations must flow through the logged commit APIs"
        " (create/update/commit_cow/delete/restore_objects) — direct"
        " mutation of store internals outside runtime/store.py and the"
        " durability module is invisible to the write-ahead log, so"
        " crash-restart recovery would silently diverge"
    )
    # repo-wide: GL004 only covers the control-plane packages, but an
    # un-logged mutation ANYWHERE corrupts recovery
    paths = ("grove_tpu/",)
    exclude = (
        "grove_tpu/runtime/store.py",
        "grove_tpu/durability/",
    )

    def _violation(self, ctx: FileContext, node, base, attr, what) -> Violation:
        return Violation(
            rule=self.id,
            path=ctx.rel,
            line=node.lineno,
            col=node.col_offset,
            message=(
                f"{what} of store state `{base}.{attr}` bypasses the"
                " logged commit APIs — the WAL never sees it, so a"
                " crash-restart recovery diverges from the state it"
                " replaces (use create/update/commit_cow/delete, or"
                " restore_objects on the recovery path)"
            ),
        )

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for target in targets:
                    hit = _store_private_attr(target)
                    if hit is not None:
                        yield self._violation(
                            ctx, node, hit[0], hit[1], "direct assignment"
                        )
            elif isinstance(node, ast.Delete):
                for target in node.targets:
                    hit = _store_private_attr(target)
                    if hit is not None:
                        yield self._violation(
                            ctx, node, hit[0], hit[1], "`del`"
                        )
            elif isinstance(node, ast.Call):
                fn = node.func
                if (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in _MUTATORS
                ):
                    hit = _store_private_attr(fn.value)
                    if hit is not None:
                        yield self._violation(
                            ctx,
                            node,
                            hit[0],
                            hit[1],
                            f"in-place `.{fn.attr}()` mutation",
                        )
