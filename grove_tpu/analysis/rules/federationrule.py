"""GL021 federation-state encapsulation (docs/federation.md
"Router state").

The FederationRouter (federation/router.py) owns every cross-cluster
fact: the region registry, the placement map, the pristine PCS/Queue
templates, and the vt-stamped decision ledger. The correctness story —
placements always point at a Ready cluster that actually holds the
objects, the ledger replays every move, spillover is PCS-whole, the
level-3 quota fold sums exactly the Ready clusters — assumes only the
router mutates that state. A controller (or test helper) that pokes
``router._placements`` or ``router._clusters`` directly can record a
placement no store backs (a gang "placed" in a dead region), or strand
a template so a crash re-route has nothing to re-apply: the chaos
invariants would catch it ticks later with the causing write long gone.

Flagged outside ``grove_tpu/federation/``: any WRITE (assignment,
augmented assignment, delete, or mutating call) to router-private state
reached through a federation-named binding — ``router._clusters``,
``fed._placements``, ``federation._decisions`` …

The sanctioned mutations are the router's own verbs: ``apply`` /
``delete`` / ``crash_cluster`` / ``rejoin_cluster`` (each records its
decision), and the read side is ``placements()`` / ``decisions()`` /
``status()`` — copies, safe to hold.
"""

from __future__ import annotations

import ast
from typing import Iterable

from grove_tpu.analysis.engine import FileContext, Rule, Violation, dotted

# FederationRouter private fields (federation/router.py)
_ROUTER_PRIVATE = {
    "_clusters",
    "_specs",
    "_placements",
    "_queues",
    "_decisions",
}
# lifetime counters: readable anywhere (the bench "federation" block /
# GET /federation), writable only by the owning package
_ROUTER_COUNTERS = {
    "spillovers",
    "reroutes",
}

_MUTATORS = {"append", "add", "clear", "pop", "popitem", "update",
             "setdefault", "extend", "remove", "discard"}


def _federation_chain(base: str) -> bool:
    """The access chain runs through a federation-named binding (so
    `sim.router._placements[k] = x` is caught via a `router` or
    `fed`/`federation` segment, not just the bare `router` name)."""
    if not base:
        return False
    return any(
        "feder" in seg.lower() or seg.lower() == "router"
        for seg in base.split(".")
    )


class FederationStateRule(Rule):
    id = "GL021"
    name = "federation-state"
    description = (
        "the FederationRouter's registry/placement/ledger state is"
        " private to grove_tpu/federation/ — placements move only"
        " through the router's verbs (apply/delete/crash/rejoin), which"
        " record their decision"
    )
    paths = ("grove_tpu/",)
    exclude = ("grove_tpu/federation/",)

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        for node in ast.walk(ctx.tree):
            for name, base, lineno, col in self._written_attrs(node):
                if not _federation_chain(base):
                    continue
                if name in _ROUTER_PRIVATE:
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=lineno,
                        col=col,
                        message=(
                            f"federation router state `{base}.{name}`"
                            " mutated outside grove_tpu/federation/ —"
                            " placements and the decision ledger must"
                            " stay coherent with the per-cluster stores;"
                            " go through the router's verbs (GL021)"
                        ),
                    )
                elif name in _ROUTER_COUNTERS:
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=lineno,
                        col=col,
                        message=(
                            f"federation counter `{base}.{name}` written"
                            " outside grove_tpu/federation/ — the"
                            " counters are the bench's ledger (read via"
                            " FederationRouter.status()) (GL021)"
                        ),
                    )

    @staticmethod
    def _written_attrs(node):
        """Every (attr, base, line, col) that `node` WRITES: assignment /
        augmented assignment / delete targets (tuple unpacking included),
        or a mutating method call on the attribute
        (`router._placements.clear()`)."""
        targets = ()
        if isinstance(node, (ast.Assign, ast.Delete)):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = (node.target,)
        for t in targets:
            elts = (
                t.elts if isinstance(t, (ast.Tuple, ast.List)) else (t,)
            )
            for elt in elts:
                inner = elt
                while isinstance(inner, ast.Subscript):
                    inner = inner.value
                if isinstance(inner, ast.Attribute):
                    yield (
                        inner.attr, dotted(inner.value), inner.lineno,
                        inner.col_offset,
                    )
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATORS
            and isinstance(node.func.value, ast.Attribute)
        ):
            owner = node.func.value
            yield (
                owner.attr, dotted(owner.value), owner.lineno,
                owner.col_offset,
            )
