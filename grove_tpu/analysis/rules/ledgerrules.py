"""GL019 act-must-log (docs/observability.md "Remediation & ledger").

The remediation controller's auditability claim is structural: EVERY call
that changes the cluster — a broker ``grant``, a ``request_drain``, an
autoscaler ``scale_target`` — must be accounted for in the causal
decision→effect ledger, in the SAME function that makes the call. A
remediation path that acts without an in-function ``LEDGER.record(...)``
is a silent actuator: the ledger would show a clean run while the broker
log shows grants, and the decision→effect chain breaks exactly where it
matters (what did the controller believe when it acted?).

First tooth — **act-must-log**, scoped to ``controller/remediate.py``:
any function body containing an act call (attribute call named ``grant``
/ ``request_drain`` / ``scale_target``) must also contain a ``record``
call through a ledger-named binding. Same-function, not same-module: a
helper that acts while its caller logs can drift apart under refactors.

Second tooth — **ledger/forecast internals are private to their owning
modules** (the GL015/GL017 state-privacy pattern): outside
``observability/ledger.py`` + ``observability/forecast.py``, any WRITE
(assignment, augmented assignment, delete, or mutating call) to private
state reached through a ledger/forecast-named binding (``LEDGER._seq``,
``FORECASTER._watched``), plus direct ``enabled`` writes — arming goes
through ``enable()``/``disable()``, and the entry ring's bounded/
vt-ordered invariants assume only ``record()``/``effect()`` write it.
"""

from __future__ import annotations

import ast
from typing import Iterable

from grove_tpu.analysis.engine import FileContext, Rule, Violation, dotted

# the module the act-must-log tooth polices (the only module allowed to
# originate remediation actions; everything it does must hit the ledger)
_ACT_MODULE = "grove_tpu/controller/remediate.py"

# attribute-call names that change the cluster: broker budget grants,
# voluntary drains, autoscaler scale writes
_ACT_ATTRS = {"grant", "request_drain", "scale_target"}

# private ring/model state across ledger.py / forecast.py
_LEDGER_PRIVATE = {
    "_entries",
    "_seq",
    "_lock",
    "_watched",
    "_vt",
    "_now",
}
_LEDGER_FLAGS = {"enabled"}

_MUTATORS = {"append", "add", "clear", "pop", "popitem", "update",
             "setdefault", "extend", "remove", "discard"}


def _ledger_chain(base: str) -> bool:
    """The access chain runs through a ledger/forecast-named binding
    (``LEDGER._seq``, ``self.forecaster._watched``)."""
    if not base:
        return False
    for seg in base.split("."):
        low = seg.lower()
        if "ledger" in low or "forecast" in low:
            return True
    return False


class ActMustLogRule(Rule):
    id = "GL019"
    name = "act-must-log"
    description = (
        "remediation act calls (broker grant / request_drain /"
        " scale_target) in controller/remediate.py must write their"
        " causal chain via LEDGER.record() in the same function;"
        " ledger/forecast internals are private to observability/"
        "{ledger,forecast}.py"
    )
    paths = ("grove_tpu/",)
    exclude = (
        "grove_tpu/observability/ledger.py",
        "grove_tpu/observability/forecast.py",
    )

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        if ctx.rel == _ACT_MODULE:
            yield from self._check_act_must_log(ctx)
        for node in ast.walk(ctx.tree):
            for name, base, lineno, col in self._written_attrs(node):
                if not _ledger_chain(base):
                    continue
                if name in _LEDGER_PRIVATE:
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=lineno,
                        col=col,
                        message=(
                            f"ledger/forecast private state `{base}.{name}`"
                            " mutated outside observability/"
                            "{ledger,forecast}.py — the bounded vt-ordered"
                            " entry ring and the fitted-model state assume"
                            " only the owning modules write them; use"
                            " record()/effect()/forecast() (GL019)"
                        ),
                    )
                elif name in _LEDGER_FLAGS:
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=lineno,
                        col=col,
                        message=(
                            f"`{base}.{name}` assigned directly — arm the"
                            " ledger/forecaster via enable()/disable() so"
                            " clock/capacity wiring stays consistent"
                            " (GL019)"
                        ),
                    )

    # -- tooth 1: act calls must log, per function -----------------------

    def _check_act_must_log(self, ctx: FileContext) -> Iterable[Violation]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            acts = []
            logs = False
            for node in ast.walk(fn):
                # nested defs belong to themselves (ast.walk visits them
                # as their own FunctionDef nodes)
                if node is not fn and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                ):
                    continue
                if node.func.attr in _ACT_ATTRS:
                    acts.append(node)
                elif node.func.attr == "record" and _ledger_chain(
                    dotted(node.func.value)
                ):
                    logs = True
            if not logs:
                for call in acts:
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=call.lineno,
                        col=call.col_offset,
                        message=(
                            f"remediation act `{dotted(call.func)}()` in"
                            f" `{fn.name}` has no in-function ledger write"
                            " — every act call must record its causal"
                            " chain via LEDGER.record() in the same"
                            " function (GL019 act-must-log)"
                        ),
                    )

    # -- write extraction (the GL015/GL017 pattern) ----------------------

    @staticmethod
    def _written_attrs(node):
        """Every (attr, base, line, col) that `node` WRITES: assignment /
        augmented assignment / delete targets (tuple unpacking and
        subscripts included), or a mutating method call on the attribute
        (``LEDGER._entries.clear()``)."""
        targets = ()
        if isinstance(node, (ast.Assign, ast.Delete)):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = (node.target,)
        for t in targets:
            elts = (
                t.elts if isinstance(t, (ast.Tuple, ast.List)) else (t,)
            )
            for elt in elts:
                inner = elt
                while isinstance(inner, ast.Subscript):
                    inner = inner.value
                if isinstance(inner, ast.Attribute):
                    yield (
                        inner.attr, dotted(inner.value), inner.lineno,
                        inner.col_offset,
                    )
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATORS
            and isinstance(node.func.value, ast.Attribute)
        ):
            owner = node.func.value
            yield (
                owner.attr, dotted(owner.value), owner.lineno,
                owner.col_offset,
            )
