"""GL006 registered event reasons + GL007 span leak prevention.

- **GL006**: every reason string handed to the event recorder
  (`EVENTS.record(ref, type, reason, msg)` / `ctx.record_event(kind,
  reason, msg, ...)`) must be registered in `observability/events.py`
  (a `REASON_*` constant or a literal in `REGISTERED_REASONS`). The
  registry is what keeps `GET /events` filterable, dedup identity
  stable, and docs/observability.md's catalog honest (the drift test in
  tests/test_docs_drift.py pins registry ⊆ docs).

- **GL007**: a span opened via `TRACER.span(...)` must be closed — used
  as a `with` context manager, or assigned to a name whose `.end()` is
  called in the same function (the `span = TRACER.span(...) if
  TRACER.enabled else None` + `finally: span.end()` idiom). A leaked
  span corrupts the per-thread nesting stack for every span after it.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Set

from grove_tpu.analysis.engine import (
    FileContext,
    Rule,
    Violation,
    dotted,
    event_record_reason,
)


def _registry() -> Set[str]:
    """Registered reason values, lazily imported (jax-free module)."""
    from grove_tpu.observability import events

    values = {
        v
        for k, v in vars(events).items()
        if k.startswith("REASON_") and isinstance(v, str)
    }
    values |= set(getattr(events, "REGISTERED_REASONS", ()))
    return values


def _registered_names() -> Set[str]:
    from grove_tpu.observability import events

    return {k for k in vars(events) if k.startswith("REASON_")}


class EventReasonRule(Rule):
    id = "GL006"
    name = "event-reason"
    description = (
        "every EventRecorder reason must be registered in"
        " observability/events.py (REASON_* constant or REGISTERED_REASONS)"
    )
    paths = ("grove_tpu/",)
    exclude = ("grove_tpu/observability/events.py",)

    def __init__(self) -> None:
        self._values = _registry()
        self._names = _registered_names()

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            reason = event_record_reason(node)
            if reason is None:
                continue  # not an event-recorder call / unrecognized shape
            msg = self._classify(reason)
            if msg is not None:
                yield Violation(
                    rule=self.id,
                    path=ctx.rel,
                    line=reason.lineno,
                    col=reason.col_offset,
                    message=msg,
                )

    def _classify(self, reason: ast.AST) -> Optional[str]:
        if isinstance(reason, ast.Constant) and isinstance(reason.value, str):
            if reason.value in self._values:
                return None
            return (
                f"event reason {reason.value!r} is not registered in"
                " observability/events.py — add a REASON_ constant or"
                " REGISTERED_REASONS entry (and the docs catalog row)"
            )
        name = (
            reason.id
            if isinstance(reason, ast.Name)
            else reason.attr
            if isinstance(reason, ast.Attribute)
            else None
        )
        if name is not None and name.startswith("REASON_"):
            if name in self._names:
                return None
            return (
                f"`{name}` is not defined in observability/events.py —"
                " register the reason before emitting it"
            )
        if name is not None:
            # a local variable holding a registered constant (e.g.
            # `event_reason` chosen between two REASON_ values) — allowed;
            # the registry is enforced where the constant is born
            return None
        return (
            "dynamic event reason expression — reasons must be registered"
            " constants (dedup identity and docs catalog depend on it)"
        )


class SpanLeakRule(Rule):
    id = "GL007"
    name = "span-leak"
    description = (
        "spans must be context-managed (`with TRACER.span(...)`) or"
        " explicitly `.end()`ed in the same function"
    )
    paths = ("grove_tpu/",)
    exclude = ("grove_tpu/observability/tracing.py",)

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        for fn in ctx.functions():
            with_calls, assigned, ended = set(), {}, set()
            span_calls = []
            for node in ast.walk(fn):
                if isinstance(node, ast.With):
                    for item in node.items:
                        expr = item.context_expr
                        for c in ast.walk(expr):
                            if self._is_span_call(c):
                                with_calls.add(id(c))
                elif isinstance(node, ast.Assign):
                    for c in ast.walk(node.value):
                        if self._is_span_call(c):
                            for tgt in node.targets:
                                if isinstance(tgt, ast.Name):
                                    assigned[id(c)] = tgt.id
                elif isinstance(node, ast.Call):
                    if self._is_span_call(node):
                        span_calls.append(node)
                    elif (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr == "end"
                        and isinstance(node.func.value, ast.Name)
                    ):
                        ended.add(node.func.value.id)
            for call in span_calls:
                if id(call) in with_calls:
                    continue
                name = assigned.get(id(call))
                if name is not None and name in ended:
                    continue
                yield Violation(
                    rule=self.id,
                    path=ctx.rel,
                    line=call.lineno,
                    col=call.col_offset,
                    message=(
                        f"span opened in `{fn.name}()` is neither"
                        " context-managed nor `.end()`ed — a leaked span"
                        " corrupts the tracer's per-thread nesting stack"
                    ),
                )

    @staticmethod
    def _is_span_call(node: ast.AST) -> bool:
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "span"
        ):
            return False
        base = dotted(node.func.value)
        return base == "TRACER" or base.lower().endswith("tracer")
