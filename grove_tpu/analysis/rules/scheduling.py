"""GL002 broker-gated eviction + GL003 schedulable-mask discipline.

The two scheduling invariants PR 4/5 established and the upcoming
delta-solve/sharded-kernel refactors must not silently lose:

- **GL002**: every VOLUNTARY gang eviction flows through a
  DisruptionBroker grant. The eviction primitives (`_evict_victim`,
  `_evict_gang_whole`) may only be called from a function that also
  obtains a grant (`broker.grant(...)` / `_disruption_granted(...)`), or
  from the involuntary triage path (controller/nodehealth.py) and the
  disruption package itself.

- **GL003**: every node set fed to the solver (`_solve_batch` /
  `build_problem`) is masked through `Node.schedulable` (or its
  complement `unschedulable_names()`). A function that reads a raw
  `.nodes` list and solves must show the mask; functions receiving an
  already-masked node list (no raw `.nodes` read) pass — the mask is
  checked where the raw list is consumed.
"""

from __future__ import annotations

import ast
from typing import Iterable

from grove_tpu.analysis.engine import (
    FileContext,
    Rule,
    Violation,
    call_name,
    dotted,
)

_EVICTORS = {
    "_evict_victim",  # preemption / quota reclaim (solver/scheduler.py)
    "_evict_gang_whole",  # node drain (disruption/drain.py)
    "terminate_gang",  # generic gang teardown entry points
    "_push_template_to_replica",  # rolling update's replica disruptor
}
_GRANTS = {"grant", "_disruption_granted"}

_SOLVE_TRIGGERS = {"_solve_batch", "build_problem"}
_MASKS = {"schedulable", "unschedulable_names"}


class BrokerGrantRule(Rule):
    id = "GL002"
    name = "broker-grant"
    description = (
        "voluntary gang evictions must hold a DisruptionBroker grant:"
        " eviction primitives outside disruption/ require a grant in the"
        " same function"
    )
    paths = ("grove_tpu/",)
    exclude = (
        "grove_tpu/disruption/",  # the broker/drainer own the primitives
        "grove_tpu/controller/nodehealth.py",  # involuntary triage path
    )

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        for fn in ctx.functions():
            if fn.name in _EVICTORS:
                continue  # the primitive's own definition is the boundary
            has_grant = any(
                isinstance(n, ast.Call) and call_name(n) in _GRANTS
                for n in ast.walk(fn)
            )
            if has_grant:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and call_name(node) in _EVICTORS:
                    yield Violation(
                        rule=self.id,
                        path=ctx.rel,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"`{call_name(node)}()` called in"
                            f" `{fn.name}()` without a DisruptionBroker"
                            " grant — voluntary evictions must clear"
                            " broker.grant(victims, source) first"
                        ),
                    )


class SchedulableMaskRule(Rule):
    id = "GL003"
    name = "schedulable-mask"
    description = (
        "node sets fed to the solver must be masked via Node.schedulable"
        " (cordoned/NotReady/Lost nodes may never enter the dense tensors)"
    )
    paths = ("grove_tpu/",)

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        for fn in ctx.functions():
            if fn.name in _SOLVE_TRIGGERS:
                continue  # the solver boundary itself takes a masked list
            trigger_calls = [
                n
                for n in ast.walk(fn)
                if isinstance(n, ast.Call) and call_name(n) in _SOLVE_TRIGGERS
            ]
            if not trigger_calls:
                continue
            reads_raw_nodes = any(
                isinstance(n, ast.Attribute) and n.attr == "nodes"
                # `problem.nodes` etc. on solver outputs is not a raw read
                and not dotted(n).startswith(("problem", "result"))
                for n in ast.walk(fn)
            )
            if not reads_raw_nodes:
                continue  # caller hands in a pre-masked node list
            masked = any(
                (isinstance(n, ast.Attribute) and n.attr in _MASKS)
                or (isinstance(n, ast.Name) and n.id in _MASKS)
                for n in ast.walk(fn)
            )
            if masked:
                continue
            for call in trigger_calls:
                yield Violation(
                    rule=self.id,
                    path=ctx.rel,
                    line=call.lineno,
                    col=call.col_offset,
                    message=(
                        f"`{call_name(call)}()` in `{fn.name}()` consumes a"
                        " raw `.nodes` list without a `Node.schedulable`"
                        " mask (or `unschedulable_names()`) — unhealthy/"
                        "cordoned nodes would enter the solve"
                    ),
                )
